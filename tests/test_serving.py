"""Serving subsystem unit tests (tier-1: sub-second, no model compile).

Admission queue policy, request event plumbing, telemetry counters and
event-file output, serving proto round-trips/service table, and fault
injection at the serving servicer boundary. The decode-pool e2e tests
(compiled engine, gRPC server, hot reload) live in
tests/test_serving_e2e.py on the drills shard."""

import os

import pytest

from elasticdl_tpu.common.fault_injection import (
    SERVING_RPCS,
    FaultInjector,
    InjectedRpcError,
    maybe_wrap_servicer,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.admission import (
    AdmissionError,
    RequestQueue,
    ServingRequest,
)
from elasticdl_tpu.serving.server import ServingServicer, _Scheduler
from elasticdl_tpu.serving.telemetry import ServingTelemetry


class FakeClock(object):
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _req(prompt=(1, 2), new=4, deadline_ms=0, clock=None):
    kwargs = {} if clock is None else {"clock": clock}
    return ServingRequest(list(prompt), new, deadline_ms=deadline_ms,
                          **kwargs)


# ------------------------------------------------------------ admission


def test_queue_admits_and_pops_fifo():
    q = RequestQueue(capacity=4, seq_len=16)
    a, b = _req(), _req()
    q.submit(a)
    q.submit(b)
    assert len(q) == 2
    got, expired = q.pop_ready()
    assert got is a and not expired
    got, _ = q.pop_ready()
    assert got is b
    got, _ = q.pop_ready()
    assert got is None


def test_queue_full_rejects_resource_exhausted():
    q = RequestQueue(capacity=2, seq_len=16)
    q.submit(_req())
    q.submit(_req())
    with pytest.raises(AdmissionError) as e:
        q.submit(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"
    # backpressure frees as the scheduler pops
    q.pop_ready()
    q.submit(_req())  # admitted again


def test_queue_validates_budget_and_args():
    q = RequestQueue(capacity=4, seq_len=16)
    with pytest.raises(AdmissionError) as e:
        q.submit(_req(prompt=[], new=4))
    assert e.value.code == "INVALID_ARGUMENT"
    with pytest.raises(AdmissionError) as e:
        q.submit(_req(new=0))
    assert e.value.code == "INVALID_ARGUMENT"
    # prompt + new must fit the model's cache
    with pytest.raises(AdmissionError) as e:
        q.submit(_req(prompt=list(range(10)), new=7))
    assert e.value.code == "INVALID_ARGUMENT"
    q.submit(_req(prompt=list(range(10)), new=6))  # == seq_len fits


def test_queue_deadline_expiry_at_admission_and_in_queue():
    clock = FakeClock()
    q = RequestQueue(capacity=4, seq_len=16, clock=clock)
    # expired before admission -> DEADLINE_EXCEEDED, never queued
    stale = _req(deadline_ms=50, clock=clock)
    clock.t += 1.0
    with pytest.raises(AdmissionError) as e:
        q.submit(stale)
    assert e.value.code == "DEADLINE_EXCEEDED"
    assert len(q) == 0
    # expires while queued -> surfaced by pop_ready as expired, the
    # next live request is returned
    doomed = _req(deadline_ms=100, clock=clock)
    q.submit(doomed)
    live = _req(deadline_ms=0, clock=clock)
    q.submit(live)
    clock.t += 10.0
    got, expired = q.pop_ready()
    assert got is live and expired == [doomed]


def test_queue_token_budget_rejects_never_fits():
    """A request whose KV footprint exceeds the paged pool's WHOLE
    block budget can never seat — INVALID_ARGUMENT at submit, not an
    eternal queue residence."""
    q = RequestQueue(capacity=4, seq_len=16, max_cached_tokens=8)
    # cached rows = prompt + new - 1 = 9 > 8
    with pytest.raises(AdmissionError) as e:
        q.submit(_req(prompt=list(range(4)), new=6))
    assert e.value.code == "INVALID_ARGUMENT"
    q.submit(_req(prompt=list(range(4)), new=5))  # 8 rows fits
    # prefill-only requests never touch the pool: always admissible
    q.submit(_req(prompt=list(range(15)), new=1))


def test_queue_pop_ready_fit_predicate_preserves_fifo():
    """pop_ready(fit=...) is the paged pool's backpressure point: an
    unseatable head STAYS at the head (no skip-ahead starvation), and
    seats once capacity frees."""
    q = RequestQueue(capacity=4, seq_len=16)
    big, small = _req(prompt=[1, 2, 3], new=8), _req(new=2)
    q.submit(big)
    q.submit(small)
    got, expired = q.pop_ready(fit=lambda r: r is not big)
    assert got is None and not expired and len(q) == 2
    # capacity frees -> the SAME head pops first, FIFO intact
    got, _ = q.pop_ready(fit=lambda r: True)
    assert got is big
    got, _ = q.pop_ready()
    assert got is small
    # expired requests still drain out even when the head doesn't fit
    clock = FakeClock()
    q2 = RequestQueue(capacity=4, seq_len=16, clock=clock)
    doomed = _req(deadline_ms=100, clock=clock)
    q2.submit(doomed)
    q2.submit(_req(clock=clock))
    clock.t += 10.0
    got, expired = q2.pop_ready(fit=lambda r: False)
    assert got is None and expired == [doomed] and len(q2) == 1


def test_queue_close_rejects_backlog_and_new_submits():
    q = RequestQueue(capacity=4, seq_len=16)
    a = _req()
    q.submit(a)
    backlog = q.close()
    assert backlog == [a] and len(q) == 0
    with pytest.raises(AdmissionError) as e:
        q.submit(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"


def test_request_event_plumbing():
    r = _req()
    assert r.next_event(timeout=0.01) is None  # timeout, no hang
    r.push(("tokens", [5], 1))
    r.push(("done", 1))
    assert r.next_event() == ("tokens", [5], 1)
    assert r.next_event() == ("done", 1)
    # ids are unique across requests
    assert _req().request_id != _req().request_id


# ------------------------------------------- scheduler deadline semantics


class FakeEngine(object):
    """One-slot engine stand-in: enough surface for _Scheduler and
    ServingServicer without jax or a compiled step."""

    def __init__(self):
        self.num_slots = 1
        self.seq_len = 16
        self.model_version = 0
        self.reloaded = []
        self._slot = None

    def free_slots(self):
        return [] if self._slot is not None else [0]

    def can_seat(self, request):
        return True

    def insert(self, request):
        self._slot = request
        return 0, 11, False

    def evict_expired(self, now):
        if self._slot is not None and self._slot.expired(now):
            req, self._slot = self._slot, None
            return [req]
        return []

    def active_count(self):
        return 0 if self._slot is None else 1

    def active_requests(self):
        return [] if self._slot is None else [self._slot]

    def step(self):
        if self._slot is None:
            return []
        return [(0, self._slot, [12], False)]

    def set_params(self, state, version):
        self.reloaded.append(version)
        self.model_version = version

    def max_cached_tokens(self):
        return self.seq_len

    draft_k = 0
    draft_proposed = 0
    draft_accepted = 0

    def kv_stats(self):
        return {"kv_paged": False, "kv_shared": False,
                "kv_cache_dtype": "",
                "kv_block_size": 0,
                "kv_blocks_total": 0, "kv_blocks_free": 0,
                "kv_blocks_cached": 0, "kv_blocks_shared": 0,
                "kv_bytes_total": 0, "kv_bytes_in_use": 0,
                "prefix_hit_tokens": 0, "cow_copies": 0,
                "kv_host_blocks": 0, "kv_host_bytes": 0,
                "revive_uploads": 0, "prefill_tokens_revived": 0,
                "host_drops": 0}


def _rig(clock):
    engine = FakeEngine()
    queue = RequestQueue(capacity=4, seq_len=16, clock=clock)
    telemetry = ServingTelemetry(log_dir=None, clock=clock)
    sched = _Scheduler(engine, queue, telemetry, idle_wait_secs=0.001,
                       clock=clock)
    return engine, queue, telemetry, sched


def test_deadline_expired_while_queued_gets_explicit_error():
    """Expiry path 1: the request never seats — the scheduler must
    push DEADLINE_EXCEEDED when it pops the corpse, so the handler
    terminates with an explicit status."""
    clock = FakeClock()
    engine, queue, telemetry, sched = _rig(clock)
    doomed = _req(deadline_ms=100, clock=clock)
    queue.submit(doomed)
    clock.t += 1.0  # expires in the queue, before any slot frees
    sched._iterate()
    ev = doomed.next_event(timeout=0)
    assert ev == ("error", "DEADLINE_EXCEEDED",
                  "deadline expired while queued")
    assert telemetry.snapshot()["expired"] == 1
    assert engine.active_count() == 0  # never seated


def test_deadline_expired_while_executing_gets_explicit_error():
    """Expiry path 2: the request seats, decodes, and expires
    mid-flight — the scheduler evicts it between steps with
    DEADLINE_EXCEEDED; delivered tokens stand."""
    clock = FakeClock()
    engine, queue, telemetry, sched = _rig(clock)
    req = _req(deadline_ms=500, clock=clock)
    queue.submit(req)
    sched._iterate()  # seats + prefill token + one decode step
    assert engine.active_count() == 1
    assert req.next_event(timeout=0)[0] == "tokens"
    clock.t += 1.0  # deadline passes mid-decode
    sched._iterate()
    assert engine.active_count() == 0  # slot freed for live work
    events = []
    while True:
        ev = req.next_event(timeout=0)
        if ev is None:
            break
        events.append(ev)
    assert ("error", "DEADLINE_EXCEEDED",
            "deadline expired mid-decode") in events
    assert telemetry.snapshot()["expired"] == 1


def test_scheduler_records_queue_wait_and_snapshot_surfaces_it():
    clock = FakeClock()
    engine, queue, telemetry, sched = _rig(clock)
    req = _req(clock=clock)
    queue.submit(req)
    clock.t += 0.2  # 200 ms queued before the scheduler seats it
    sched._iterate()
    assert req.seated_at == clock.t
    assert req.queue_wait_secs() == pytest.approx(0.2)
    snap = telemetry.snapshot()
    assert snap["queue_wait_ms"] == pytest.approx(200.0)
    # the servicer surfaces the same number on the status RPC —
    # the router's load signal
    servicer = ServingServicer(queue, engine, telemetry,
                               scheduler_alive=lambda: True,
                               clock=clock,
                               draining=sched.is_draining)
    st = servicer.server_status(pb.ServerStatusRequest())
    assert st.queue_wait_ms == pytest.approx(200.0)
    assert not st.draining


def test_scheduler_advertises_draining_on_stop_and_reload():
    clock = FakeClock()
    engine, queue, telemetry, sched = _rig(clock)

    class OneShotWatcher(object):
        def __init__(self):
            self.pending = ("new-state", 7)

        def poll(self):
            out, self.pending = self.pending, None
            return out

    sched.watcher = OneShotWatcher()
    seen = []
    engine.set_params = lambda state, version: seen.append(
        (version, sched.is_draining())
    )
    assert not sched.is_draining()
    sched._iterate()  # reload applies WITH draining advertised
    assert seen == [(7, True)]
    assert not sched.is_draining()  # transient: cleared after the swap
    sched.stop(drain=True)  # SIGTERM path: advertised for good
    assert sched.is_draining()


def test_sigterm_drain_survives_concurrent_reload():
    """Regression: stop() landing while a hot-reload swap is mid-flight
    must not lose the permanent drain advertisement — the reload's
    cleanup used to clear the shared flag, and routers would keep
    routing new work to a terminating replica."""
    clock = FakeClock()
    engine, queue, telemetry, sched = _rig(clock)

    class OneShotWatcher(object):
        def __init__(self):
            self.pending = ("new-state", 7)

        def poll(self):
            out, self.pending = self.pending, None
            return out

    sched.watcher = OneShotWatcher()
    seen = []

    def swap(state, version):
        sched.stop(drain=True)  # SIGTERM arrives mid-swap
        seen.append((version, sched.is_draining()))

    engine.set_params = swap
    sched._iterate()
    assert seen == [(7, True)]
    # the reload's cleanup cleared only its OWN transient flag: the
    # SIGTERM advertisement stays up for good
    assert sched.is_draining()


def test_telemetry_counters_and_snapshot():
    clock = FakeClock()
    t = ServingTelemetry(log_dir=None, flush_every=2, clock=clock)
    t.count("admitted")
    t.count("rejected", 2)
    t.record_step(queue_depth=3, active_slots=2, step_secs=0.01,
                  tokens_committed=2)
    t.record_step(queue_depth=1, active_slots=4, step_secs=0.01,
                  tokens_committed=4)
    snap = t.snapshot()
    assert snap["admitted"] == 1 and snap["rejected"] == 2
    assert snap["tokens_generated"] == 6
    assert snap["max_active_slots"] == 4
    assert snap["steps"] == 2


def test_telemetry_ttft_and_event_file(tmp_path):
    clock = FakeClock()
    t = ServingTelemetry(log_dir=str(tmp_path), flush_every=1,
                         clock=clock)
    r = _req(clock=clock)
    clock.t += 0.25
    ttft = t.record_ttft(r)
    assert abs(ttft - 250.0) < 1e-6
    t.record_step(queue_depth=0, active_slots=1, step_secs=0.002,
                  tokens_committed=1)
    t.close()
    files = [f for f in os.listdir(str(tmp_path))
             if f.startswith("events.out.tfevents")]
    assert len(files) == 1
    assert os.path.getsize(os.path.join(str(tmp_path), files[0])) > 0


# ---------------------------------------------------------------- proto


def test_serving_proto_round_trip():
    req = pb.GenerateRequest(
        prompt=[1, 2, 3], max_new_tokens=5, temperature=0.5, seed=9,
        deadline_ms=2500,
    )
    req2 = pb.GenerateRequest.FromString(req.SerializeToString())
    assert list(req2.prompt) == [1, 2, 3]
    assert req2.max_new_tokens == 5 and req2.seed == 9
    assert req2.deadline_ms == 2500
    chunk = pb.TokenChunk(tokens=[7, 8], done=True, model_version=3)
    chunk2 = pb.TokenChunk.FromString(chunk.SerializeToString())
    assert list(chunk2.tokens) == [7, 8] and chunk2.done
    st = pb.ServerStatusResponse(
        queue_depth=1, active_slots=2, num_slots=4, admitted=10,
        tokens_generated=123, uptime_secs=1.5, max_active_slots=3,
        kv_paged=True, kv_block_size=16, kv_blocks_total=32,
        kv_blocks_free=7, kv_bytes_total=1 << 20,
        kv_bytes_in_use=4096, kv_bytes_in_use_peak=8192,
        kv_bytes_per_token=96.5,
        kv_host_blocks=5, kv_host_bytes=5 << 10,
        revive_uploads=3, prefill_tokens_revived=80, host_drops=2,
    )
    st2 = pb.ServerStatusResponse.FromString(st.SerializeToString())
    assert st2.num_slots == 4 and st2.tokens_generated == 123
    assert abs(st2.uptime_secs - 1.5) < 1e-9
    assert st2.kv_paged and st2.kv_blocks_free == 7
    assert st2.kv_bytes_total == 1 << 20
    assert st2.kv_bytes_in_use_peak == 8192
    assert abs(st2.kv_bytes_per_token - 96.5) < 1e-9
    # the tiered-host-spill fields survive the wire
    assert st2.kv_host_blocks == 5 and st2.kv_host_bytes == 5 << 10
    assert st2.revive_uploads == 3
    assert st2.prefill_tokens_revived == 80 and st2.host_drops == 2


def test_serving_service_descriptor():
    svc = pb.DESCRIPTOR.services_by_name["Serving"]
    names = [m.name for m in svc.methods]
    assert names == ["generate", "generate_stream", "server_status",
                     "export_chain", "transfer_chain",
                     "abort_transfer", "reload_checkpoint"]
    assert svc.methods_by_name["generate_stream"].server_streaming
    assert not svc.methods_by_name["generate"].server_streaming
    # the rollout swap handshake is unary
    assert not svc.methods_by_name["reload_checkpoint"].server_streaming
    # the disagg transfer RPCs are all unary
    assert not svc.methods_by_name["transfer_chain"].server_streaming
    # the hand-rolled binding table mirrors the descriptor
    from elasticdl_tpu.proto.service import _SERVING_METHODS

    assert set(_SERVING_METHODS) == set(names)
    assert _SERVING_METHODS["generate_stream"][2] is True


# ------------------------------------------------------ fault injection


class _EchoServicer(object):
    def generate(self, request, _context=None):
        return pb.GenerateResponse(tokens=list(request.prompt))

    def generate_stream(self, request, _context=None):
        return iter([pb.TokenChunk(tokens=list(request.prompt))])

    def server_status(self, request, _context=None):
        return pb.ServerStatusResponse(num_slots=1)


def test_fault_injection_wraps_serving_rpcs():
    inj = FaultInjector(spec="generate:drop:1;server_status:error:1")
    wrapped = maybe_wrap_servicer(_EchoServicer(), inj, rpcs=SERVING_RPCS)
    req = pb.GenerateRequest(prompt=[1])
    # first generate call is dropped (pre-handler)
    with pytest.raises(InjectedRpcError):
        wrapped.generate(req)
    # second goes through
    assert list(wrapped.generate(req).tokens) == [1]
    # error fires AFTER the handler ran
    with pytest.raises(InjectedRpcError):
        wrapped.server_status(pb.ServerStatusRequest())
    assert wrapped.server_status(pb.ServerStatusRequest()).num_slots == 1
    assert inj.injected == {"generate": 1, "server_status": 1}


def test_fault_injection_inactive_returns_servicer_unwrapped():
    s = _EchoServicer()
    assert maybe_wrap_servicer(s, None, rpcs=SERVING_RPCS) is s
