"""Client CLI tests: parser surface, master-arg reconstruction, zoo
scaffolding, master-pod submission (fake k8s), job monitor, and the
no-cluster end-to-end `train` path (reference elasticdl_client/tests +
scripts/client_test.sh in spirit)."""

import os

import pytest

from elasticdl_tpu.client import api
from elasticdl_tpu.client.job_monitor import EdlJobMonitor, PodMonitor
from elasticdl_tpu.client.main import build_argument_parser


def _parse(argv):
    return build_argument_parser().parse_known_args(argv)


def test_parser_train():
    args, extra = _parse([
        "train",
        "--model_zoo", "model_zoo",
        "--model_def", "m.m.custom_model",
        "--num_workers", "2",
        "--image_name", "img:1",
    ])
    assert args.command == "train"
    assert args.num_workers == 2
    assert args.func is api.train


def test_parser_zoo_init(tmp_path):
    args, _ = _parse(["zoo", "init", "--path", str(tmp_path)])
    assert args.zoo_command == "init"
    assert args.func is api.init_zoo


def test_build_master_args_filters_client_flags():
    args, extra = _parse([
        "train",
        "--model_zoo", "model_zoo",
        "--model_def", "m.m.custom_model",
        "--image_name", "img:1",
        "--minibatch_size", "64",
    ])
    master_args = api.build_master_args(args, extra)
    assert "--image_name" not in master_args
    assert "--detach" not in master_args
    i = master_args.index("--minibatch_size")
    assert master_args[i + 1] == "64"


def test_zoo_init_scaffolds_valid_module(tmp_path):
    args, _ = _parse(["zoo", "init", "--path", str(tmp_path)])
    api.init_zoo(args)
    assert (tmp_path / "requirements.txt").exists()
    assert (tmp_path / "Dockerfile").exists()
    # the generated template is a loadable zoo spec
    from elasticdl_tpu.common.model_utils import get_model_spec

    spec = get_model_spec(str(tmp_path), "my_model.custom_model")
    model = spec.create_model("")
    assert model is not None
    assert "mse" in spec.eval_metrics_fn()


def test_submit_master_pod_manifest():
    class FakeApi(object):
        def __init__(self):
            self.pods = []

        def create_namespaced_pod(self, namespace, manifest):
            self.pods.append((namespace, manifest))

        def read_namespaced_pod(self, namespace, name):
            return None

    args, extra = _parse([
        "train",
        "--model_zoo", "model_zoo",
        "--model_def", "m.m.custom_model",
        "--image_name", "img:1",
        "--job_name", "cli-test",
        "--detach",
    ])
    fake = FakeApi()
    api._submit_master_pod(args, api.build_master_args(args, extra),
                           core_api=fake)
    ns, manifest = fake.pods[0]
    assert manifest["metadata"]["name"] == "elasticdl-cli-test-master"
    assert manifest["metadata"]["ownerReferences"] == []
    container = manifest["spec"]["containers"][0]
    assert container["command"][-1] == "elasticdl_tpu.master.main"
    assert "--model_zoo" in container["args"]


class _FakeMonClient(object):
    def __init__(self, phases, log="line1\nline2"):
        self._phases = list(phases)
        self._log = log
        self.namespace = "ns"

        class Inner(object):
            def read_namespaced_pod_log(inner_self, name, ns, **kw):
                return self._log

        self.client = Inner()

    def get_master_pod_name(self):
        return "elasticdl-x-master"

    def get_pod(self, name):
        phase = self._phases.pop(0) if len(self._phases) > 1 else (
            self._phases[0]
        )
        return {"status": {"phase": phase}}


def test_pod_monitor_returns_on_success():
    client = _FakeMonClient(["Pending", "Running", "Succeeded"])
    monitor = PodMonitor(client, "elasticdl-x-master", poll_interval=0)
    assert monitor.monitor_status() == "Succeeded"


def test_job_monitor_raises_on_failure():
    client = _FakeMonClient(["Running", "Failed"])
    monitor = EdlJobMonitor(client, poll_interval=0)
    with pytest.raises(RuntimeError, match="Job failed"):
        monitor.monitor_job_status()


@pytest.mark.integration
def test_cli_train_local_end_to_end(tmp_path):
    """`elasticdl-tpu train` with no image runs the master in-process
    with subprocess workers and completes."""
    from elasticdl_tpu.client.main import main as cli_main
    from elasticdl_tpu.data import recordio_gen

    train_dir = str(tmp_path / "train")
    recordio_gen.gen_mnist_like(train_dir, num_files=1,
                                records_per_file=48)
    rc = cli_main([
        "train",
        "--model_zoo",
        os.path.join(os.path.dirname(__file__), "..", "model_zoo"),
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data", train_dir,
        "--minibatch_size", "16",
        "--records_per_task", "24",
        "--num_workers", "1",
        "--port", "0",
    ])
    assert rc == 0


def test_pod_monitor_gives_up_on_missing_pod():
    class GoneClient(object):
        def get_pod(self, name):
            return None

    monitor = PodMonitor(GoneClient(), "gone-pod", poll_interval=0)
    assert monitor.monitor_status() == "NotFound"
