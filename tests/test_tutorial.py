"""The user tutorial (docs/tutorials/train_on_kubernetes.md) is
executable documentation: every fenced bash block marked `<!-- ci -->`
runs verbatim here, in a scratch directory, against the real CLI and
library. If the tutorial drifts from the code, this fails — the same
contract the reference's CI enforced on its tutorial job scripts
(reference scripts/travis/run_job.sh)."""

import os
import re
import subprocess
import sys

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TUTORIAL = os.path.join(REPO, "docs", "tutorials",
                        "train_on_kubernetes.md")


def _ci_blocks():
    text = open(TUTORIAL).read()
    blocks = re.findall(r"<!-- ci -->\s*```bash\n(.*?)```", text,
                        re.DOTALL)
    assert blocks, "tutorial lost its ci-checked blocks"
    return blocks


def test_tutorial_ci_blocks_run(tmp_path):
    # Load-sensitive (like test_two_process_spmd_train): the blocks
    # spawn 5 jax processes; under heavily parallel pytest invocations
    # the job can outlive the generous ceiling. Passes serially.
    blocks = _ci_blocks()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the tutorial's relative paths (my_zoo, mnist_data, exported/...)
    # land in the scratch dir; model_zoo/scripts resolve via REPO
    script = "\n".join(
        ["set -euo pipefail",
         "ln -sfn %s/model_zoo model_zoo" % REPO,
         "ln -sfn %s/scripts scripts" % REPO]
        + blocks
    )
    # the blocks pay jax import + first-compile in five separate
    # processes (master, two workers, two python heredocs) — slow under
    # a loaded machine, so the ceiling is generous; a healthy run is
    # ~5 min
    proc = subprocess.run(
        ["bash", "-c", script.replace("python ", sys.executable + " ")],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=2400,
    )
    assert proc.returncode == 0, (
        "tutorial block failed:\nSTDOUT:\n%s\nSTDERR:\n%s"
        % (proc.stdout[-4000:], proc.stderr[-4000:])
    )
    assert "serving OK" in proc.stdout


def test_tutorial_references_exist():
    """Every repo path the tutorial names must exist."""
    text = open(TUTORIAL).read()
    for rel in (
        "manifests/elasticdl-tpu-rbac.yaml",
        "scripts/run_cluster_job_smoke.sh",
        "scripts/validate_job_status.py",
        "tests/test_convergence_parity.py",
        "tests/test_worker_master_integration.py",
        "tests/test_local_elastic_e2e.py",
        "elasticdl_tpu/api/local_executor.py",
        "common/tb_events.py",
        "docs/designs",
        "BENCHNOTES.md",
        "tests/test_finetune.py",
    ):
        assert rel in text, "tutorial no longer mentions %s" % rel
    assert os.path.exists(os.path.join(REPO, "elasticdl_tpu",
                                       "common", "tb_events.py"))
    for rel in ("manifests/elasticdl-tpu-rbac.yaml",
                "scripts/validate_job_status.py",
                "docs/designs", "BENCHNOTES.md"):
        assert os.path.exists(os.path.join(REPO, rel)), rel
