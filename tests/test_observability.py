"""Observability subsystem tests (tier-1: no jax, no sockets).

Locks the ISSUE's tentpole semantics: the log-linear histogram against
a sorted-list oracle (within bucket resolution), bucket-count merge =
recording the union, the span ring buffer's drop-OLDEST bound, one
request = ONE span tree across router re-dispatch and hedging with the
legs as SIBLING spans, the replica serve span parenting under the
router's dispatch span (cross-process merge via the dump tool), the
percentile fields on ServerStatus/router_status, bench_serving's
percentiles being the SAME code path, the closed telemetry counter
sets, telemetry tail-flush on close(), and the tb_events binary format
round-tripped through an independent record/CRC parser."""

import json
import os
import random
import struct
import threading
import time

import grpc
import pytest

from elasticdl_tpu.common.fault_injection import InjectedRpcError
from elasticdl_tpu.observability import dump as dump_mod
from elasticdl_tpu.observability.histogram import (
    NUM_BUCKETS,
    LogLinearHistogram,
    bucket_bounds,
    bucket_index,
    percentiles,
)
from elasticdl_tpu.observability.tracing import (
    SpanRecorder,
    children_of,
    chrome_trace,
    group_by_trace,
    recorder,
    trace_roots,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.admission import RequestQueue, ServingRequest
from elasticdl_tpu.serving.router import Router, RouterConfig
from elasticdl_tpu.serving.server import ServingServicer, _Scheduler
from elasticdl_tpu.serving.telemetry import (
    RouterTelemetry,
    ServingTelemetry,
)

# ------------------------------------------------------------- histogram


def _sorted_oracle(values, q):
    vs = sorted(values)
    return vs[min(len(vs) - 1, int(round(q / 100.0 * (len(vs) - 1))))]


def test_histogram_matches_sorted_oracle_within_resolution():
    """The acceptance pin: histogram percentiles equal the sorted-list
    oracle within the scheme's relative bucket resolution (2/SUBBUCKETS
    = ~3.1%), across magnitudes from sub-ms to minutes."""
    rng = random.Random(7)
    values = [rng.lognormvariate(3.0, 2.0) for _ in range(4000)]
    h = LogLinearHistogram()
    for v in values:
        h.record(v)
    for q in (50, 90, 99):
        oracle = _sorted_oracle(values, q)
        assert h.percentile(q) == pytest.approx(oracle, rel=0.04)
    assert h.count == len(values)
    assert h.min == min(values) and h.max == max(values)


def test_histogram_merge_equals_union_recording():
    rng = random.Random(11)
    values = [rng.expovariate(0.01) for _ in range(1000)]
    whole, a, b = (LogLinearHistogram() for _ in range(3))
    for v in values:
        whole.record(v)
    for v in values[:500]:
        a.record(v)
    for v in values[500:]:
        b.record(v)
    a.merge(b)
    assert a.counts == whole.counts and a.count == whole.count
    assert a.percentile(99) == whole.percentile(99)


def test_histogram_wire_round_trip_preserves_percentiles():
    h = LogLinearHistogram()
    for v in (0.5, 3.0, 3.0, 40.0, 900.0):
        h.record(v)
    counts = h.to_counts()
    assert counts and counts[-1] != 0  # trailing zeros trimmed
    back = LogLinearHistogram.from_counts(counts)
    assert back.count == h.count
    for q in (50, 90, 99):
        assert back.percentile(q) == pytest.approx(
            h.percentile(q), rel=0.04
        )


def test_histogram_edges():
    h = LogLinearHistogram()
    assert h.percentile(99) == 0.0  # empty -> proto-friendly 0
    for bad in (-1.0, float("nan"), float("inf")):
        h.record(bad)
    assert h.count == 0
    h.record(0.0)
    assert h.percentile(50) == 0.0
    # indexes stay in range across the whole magnitude span
    for v in (0.0, 0.005, 0.64, 1.0, 1e3, 1e7, 1e12, float("inf")):
        assert 0 <= bucket_index(v) < NUM_BUCKETS
    for i in (0, 63, 64, NUM_BUCKETS - 1):
        lo, hi = bucket_bounds(i)
        assert lo < hi


def test_bench_serving_uses_the_shared_percentile_code():
    """bench numbers and live numbers must be definitionally
    identical: the bench's percentile entry IS the histogram module's
    (same function object), and its answers match the sorted oracle
    within bucket resolution."""
    import scripts.bench_serving as bench

    assert bench.percentiles is percentiles
    rng = random.Random(3)
    values = [rng.uniform(1.0, 500.0) for _ in range(500)]
    out = percentiles(values, (50, 90, 99))
    for q in (50, 90, 99):
        assert out["p%d" % q] == pytest.approx(
            _sorted_oracle(values, q), rel=0.04
        )
    assert percentiles([], (50,)) == {"p50": None}


# ------------------------------------------------------ span ring buffer


def test_span_ring_drops_oldest_under_overflow():
    rec = SpanRecorder(service="t", capacity=3)
    spans = [rec.start_span("s%d" % i) for i in range(8)]
    for s in spans:
        s.finish()
    assert len(rec) == 3 and rec.dropped == 5
    kept = [s.name for s in rec.snapshot()]
    assert kept == ["s5", "s6", "s7"]  # newest survive
    assert rec.export()["dropped"] == 5


def test_span_finish_is_idempotent_and_unfinished_never_exports():
    rec = SpanRecorder(service="t")
    a = rec.start_span("a")
    rec.start_span("never-finished")
    a.finish("ok")
    a.finish("error")  # second finish is a no-op
    exported = rec.export()["spans"]
    assert [s["name"] for s in exported] == ["a"]
    assert exported[0]["status"] == "ok"


# ----------------------------------------------- replica-side span tree


class FinishingEngine(object):
    """Jax-free engine stand-in that completes every request at its
    second token, so the scheduler walks the full span lifecycle."""

    def __init__(self):
        self.num_slots = 2
        self.seq_len = 16
        self.model_version = 0
        self._slots = {}

    def free_slots(self):
        return [i for i in range(self.num_slots)
                if i not in self._slots]

    def can_seat(self, request):
        return True

    def insert(self, request):
        slot = self.free_slots()[0]
        if hasattr(request, "trace_event"):
            request.trace_event("prefill", bucket=16, slot=slot)
        if request.max_new_tokens == 1:
            return slot, 11, True
        self._slots[slot] = request
        return slot, 11, False

    def evict_expired(self, now):
        out = [r for r in self._slots.values() if r.expired(now)]
        self._slots = {s: r for s, r in self._slots.items()
                       if not r.expired(now)}
        return out

    def active_count(self):
        return len(self._slots)

    def active_requests(self):
        return list(self._slots.values())

    def step(self):
        out = []
        for slot, req in list(self._slots.items()):
            req.generated.append(12)
            finished = len(req.generated) >= req.max_new_tokens
            if finished:
                del self._slots[slot]
            out.append((slot, req, [12], finished))
        return out

    def set_params(self, state, version):
        self.model_version = version

    def max_cached_tokens(self):
        return self.seq_len

    draft_k = 0
    draft_proposed = 0
    draft_accepted = 0

    def kv_stats(self):
        return {"kv_paged": False, "kv_shared": False,
                "kv_cache_dtype": "",
                "kv_block_size": 0,
                "kv_blocks_total": 0, "kv_blocks_free": 0,
                "kv_blocks_cached": 0, "kv_blocks_shared": 0,
                "kv_bytes_total": 0, "kv_bytes_in_use": 0,
                "prefix_hit_tokens": 0, "cow_copies": 0}


def _replica_rig():
    engine = FinishingEngine()
    queue = RequestQueue(capacity=8, seq_len=16)
    telemetry = ServingTelemetry(log_dir=None)
    sched = _Scheduler(engine, queue, telemetry, idle_wait_secs=0.001)
    servicer = ServingServicer(
        queue, engine, telemetry, scheduler_alive=lambda: True,
        handler_poll_secs=0.02, draining=lambda: False,
    )
    return engine, queue, telemetry, sched, servicer


def test_replica_serve_span_lifecycle_and_parenting():
    recorder().clear()
    engine, queue, telemetry, sched, servicer = _replica_rig()
    req_pb = pb.GenerateRequest(
        prompt=[1, 2], max_new_tokens=3,
        trace_id="feedc0de00000001", parent_span_id="dad0000000000001",
    )
    done = {}

    def call():
        done["resp"] = servicer.generate(req_pb)

    t = threading.Thread(target=call)
    t.start()
    deadline = time.monotonic() + 5.0
    while "resp" not in done and time.monotonic() < deadline:
        sched._iterate()
    t.join(timeout=5.0)
    assert not t.is_alive() and list(done["resp"].tokens)[:2] == [1, 2]

    serve = [s for s in recorder().snapshot()
             if s.name == "serve"
             and s.trace_id == "feedc0de00000001"]
    assert len(serve) == 1
    span = serve[0].to_dict()
    # parented under the caller's (router's) dispatch span: the
    # cross-process tree edge
    assert span["parent_span_id"] == "dad0000000000001"
    assert span["status"] == "ok"
    names = [e["name"] for e in span["events"]]
    assert names == ["queued", "seated", "prefill", "first_token",
                     "completed"]
    # e2e completion landed in the histogram + snapshot percentiles
    snap = telemetry.snapshot()
    assert snap["e2e_p50_ms"] >= 0 and snap["ttft_p99_ms"] >= 0
    assert telemetry.hists["e2e_ms"].count == 1


def test_replica_rejection_finishes_span_with_status():
    recorder().clear()
    engine, queue, telemetry, sched, servicer = _replica_rig()
    # overflow the queue without a scheduler: capacity 8
    for _ in range(8):
        queue.submit(ServingRequest([1], 2))
    from elasticdl_tpu.serving.admission import AdmissionError

    with pytest.raises(AdmissionError):
        servicer.generate(pb.GenerateRequest(
            prompt=[1], max_new_tokens=2, trace_id="feedc0de00000002",
        ))
    spans = [s for s in recorder().snapshot()
             if s.trace_id == "feedc0de00000002"]
    assert len(spans) == 1
    assert spans[0].status == "RESOURCE_EXHAUSTED"
    assert [e[1] for e in spans[0].events] == ["rejected"]


# ------------------------------------------------- router-side span tree


class ForwardingStub(object):
    """ServingStub-shaped fake that forwards unary generates into a
    REAL in-process replica rig (servicer + scheduler thread), so the
    router's dispatch spans and the replica's serve spans land in one
    recorder exactly as one merged trace would."""

    def __init__(self, servicer, fail_first=0):
        self._servicer = servicer
        self.fail_first = fail_first
        self.block_until = None

    def server_status(self, request, timeout=None):
        return self._servicer.server_status(request)

    def generate(self, request, timeout=None):
        if self.block_until is not None:
            assert self.block_until.wait(5.0)
        if self.fail_first > 0:
            self.fail_first -= 1
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE, "replica down"
            )
        return self._servicer.generate(request)


def _router_over_real_replica(fail_first=0, n=1, **cfg_kwargs):
    rigs = [_replica_rig() for _ in range(n)]
    for rig in rigs:
        rig[3].start()  # scheduler thread (daemon, jax-free)
    stubs = {}
    for i, rig in enumerate(rigs):
        stubs["rep%d" % i] = ForwardingStub(
            rig[4], fail_first=fail_first if i == 0 else 0
        )
    cfg = RouterConfig(lease_secs=30.0, redispatch_window_secs=8.0,
                       base_delay_secs=0.001, max_delay_secs=0.002,
                       **cfg_kwargs)
    router = Router(sorted(stubs), config=cfg,
                    stub_factory=lambda a: stubs[a])
    router.poll_once()
    return router, rigs, stubs


def _tree(trace_id):
    spans = [s.to_dict() for s in recorder().snapshot()
             if s.trace_id == trace_id]
    return spans


def test_one_routed_request_is_one_span_tree():
    """The acceptance pin: router dispatch -> replica admission ->
    seated -> first_token -> completion, one tree, parsed back from
    the exported Chrome-trace JSON."""
    recorder().clear()
    router, rigs, stubs = _router_over_real_replica()
    try:
        resp = router.dispatch_generate(pb.GenerateRequest(
            prompt=[1, 2], max_new_tokens=3,
        ))
        assert len(resp.tokens) == 5
        roots = [s for s in recorder().snapshot()
                 if s.name == "router_generate"]
        assert len(roots) == 1
        spans = _tree(roots[0].trace_id)
        assert len(spans) == 3  # root + dispatch + serve
        root = [s for s in spans if s["name"] == "router_generate"][0]
        dispatch = children_of(spans, root["span_id"])
        assert [d["name"] for d in dispatch] == ["dispatch"]
        serve = children_of(spans, dispatch[0]["span_id"])
        assert [s["name"] for s in serve] == ["serve"]
        assert [e["name"] for e in serve[0]["events"]] == [
            "queued", "seated", "prefill", "first_token", "completed"
        ]
        # and it round-trips through the chrome export
        ct = chrome_trace(spans)
        slices = [e for e in ct["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in slices} == {
            "router_generate", "dispatch", "serve"
        }
        args = [e["args"] for e in slices]
        assert all(a["trace_id"] == root["trace_id"] for a in args)
        # router e2e histogram fed the status RPC fields
        status = router.status_response()
        assert status.e2e_p50_ms > 0
    finally:
        router._stop.set()
        for rig in rigs:
            rig[3].stop()


def test_redispatched_request_yields_sibling_dispatch_spans():
    recorder().clear()
    router, rigs, stubs = _router_over_real_replica(fail_first=1, n=2)
    try:
        resp = router.dispatch_generate(pb.GenerateRequest(
            prompt=[3], max_new_tokens=2,
        ))
        assert len(resp.tokens) == 3
        roots = [s for s in recorder().snapshot()
                 if s.name == "router_generate"]
        assert len(roots) == 1
        root = roots[0].to_dict()
        spans = _tree(root["trace_id"])
        legs = children_of(spans, root["span_id"])
        # both legs are SIBLINGS under the one root: the failed
        # dispatch and its replacement
        assert sorted(leg["status"] for leg in legs) == ["error", "ok"]
        assert {leg["name"] for leg in legs} == {"dispatch"}
        assert any(e["name"] == "redispatched" for e in root["events"])
        # the serve span hangs under the SUCCESSFUL leg only
        ok_leg = [leg for leg in legs if leg["status"] == "ok"][0]
        assert [s["name"] for s in children_of(
            spans, ok_leg["span_id"])] == ["serve"]
        bad_leg = [leg for leg in legs if leg["status"] == "error"][0]
        assert children_of(spans, bad_leg["span_id"]) == []
    finally:
        router._stop.set()
        for rig in rigs:
            rig[3].stop()


def test_hedged_request_yields_sibling_legs_in_one_tree():
    recorder().clear()
    router, rigs, stubs = _router_over_real_replica(
        n=2, hedge_delay_secs=0.05
    )
    try:
        # make rep0 primary and stall it so the hedge fires
        gate = threading.Event()
        stubs["rep0"].block_until = gate
        try:
            resp = router.dispatch_generate(pb.GenerateRequest(
                prompt=[1], max_new_tokens=2,
            ))
        finally:
            gate.set()
        assert len(resp.tokens) == 3
        time.sleep(0.1)  # let the released primary leg finish its span
        roots = [s for s in recorder().snapshot()
                 if s.name == "router_generate"]
        assert len(roots) == 1
        root = roots[0].to_dict()
        assert any(e["name"] == "hedged" for e in root["events"])
        assert any(e["name"] == "hedge_win" for e in root["events"])
        legs = children_of(_tree(root["trace_id"]), root["span_id"])
        assert len(legs) == 2  # primary + hedge, SIBLINGS
        assert sorted(leg["attrs"]["hedge"] for leg in legs) == [
            False, True
        ]
    finally:
        router._stop.set()
        for rig in rigs:
            rig[3].stop()


# ------------------------------------------------- cross-process merge


def test_dump_merges_per_process_exports_into_one_trace(tmp_path):
    """Two recorders standing in for two processes: the merged export
    reassembles the parent/child edge across the 'process' boundary,
    and the CLI writes loadable Chrome-trace JSON."""
    router_rec = SpanRecorder(service="router:1")
    replica_rec = SpanRecorder(service="replica:2")
    root = router_rec.start_span("router_generate")
    leg = router_rec.start_span("dispatch", trace_id=root.trace_id,
                                parent_span_id=root.span_id,
                                replica="localhost:2")
    serve = replica_rec.start_span("serve", trace_id=root.trace_id,
                                   parent_span_id=leg.span_id)
    serve.event("first_token").finish("ok")
    leg.finish("ok")
    root.finish("ok")
    router_rec.flush(str(tmp_path))
    replica_rec.flush(str(tmp_path))

    spans, meta = dump_mod.merge_dir(str(tmp_path))
    assert len(spans) == 3 and len(meta) == 2
    assert len(group_by_trace(spans)) == 1
    roots = trace_roots(spans)
    assert [r["name"] for r in roots] == ["router_generate"]
    serve_spans = [s for s in spans if s["name"] == "serve"]
    assert serve_spans[0]["service"] == "replica:2"
    assert serve_spans[0]["parent_span_id"] == leg.span_id

    out = str(tmp_path / "trace.json")
    assert dump_mod.main(["--dir", str(tmp_path), "--out", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(slices) == 3
    # services map to separate chrome pids with name metadata
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in metas} == {
        "router:1", "replica:2"
    }


# ------------------------------------------------ status RPC percentiles


def test_server_status_reports_histogram_percentiles():
    engine, queue, telemetry, sched, servicer = _replica_rig()
    for wait in (0.010, 0.020, 0.100):
        telemetry.record_queue_wait(wait)
    req = ServingRequest([1], 2)
    req.submitted_at -= 0.050  # 50 ms ago
    telemetry.record_ttft(req)
    st = servicer.server_status(pb.ServerStatusRequest())
    assert st.ttft_p50_ms == pytest.approx(50.0, rel=0.05)
    assert st.queue_wait_p99_ms == pytest.approx(100.0, rel=0.05)
    assert st.queue_wait_p50_ms <= st.queue_wait_p99_ms
    assert list(st.ttft_hist) and list(st.queue_wait_hist)


def test_router_status_merges_replica_histograms():
    """Fleet-wide percentiles come from BUCKET addition across
    replicas — percentiles of the merged counts, never averages of
    per-replica percentiles."""
    h1, h2 = LogLinearHistogram(), LogLinearHistogram()
    for v in (10.0, 12.0, 14.0):
        h1.record(v)
    for v in (200.0, 220.0, 240.0):
        h2.record(v)

    class HistStub(object):
        def __init__(self, hist):
            self._hist = hist

        def server_status(self, request, timeout=None):
            return pb.ServerStatusResponse(
                ttft_hist=self._hist.to_counts(),
                queue_wait_hist=self._hist.to_counts(),
            )

    stubs = {"rep0": HistStub(h1), "rep1": HistStub(h2)}
    router = Router(sorted(stubs), config=RouterConfig(),
                    stub_factory=lambda a: stubs[a])
    router.poll_once()
    st = router.status_response()
    merged = LogLinearHistogram()
    merged.merge(h1)
    merged.merge(h2)
    assert st.ttft_p50_ms == pytest.approx(merged.percentile(50))
    assert st.ttft_p99_ms == pytest.approx(merged.percentile(99))
    assert st.ttft_p99_ms == pytest.approx(240.0, rel=0.05)
    router._stop.set()


# ------------------------------------------------- closed counter sets


def test_serving_counter_set_is_closed():
    t = ServingTelemetry(log_dir=None)
    t.count("admitted")
    with pytest.raises(ValueError, match="unknown serving counter"):
        t.count("admittd")
    assert set(t.counters) == set(ServingTelemetry.COUNTERS)


def test_router_counter_set_is_closed():
    t = RouterTelemetry(log_dir=None)
    t.count("routed")
    with pytest.raises(ValueError, match="unknown router counter"):
        t.count("routd")


def test_router_snapshot_carries_rotation_gauges():
    t = RouterTelemetry(log_dir=None)
    snap = t.snapshot()
    assert snap["healthy_replicas"] == 0 and snap["replicas"] == 0
    t.record_poll(2, 3)
    snap = t.snapshot()
    assert snap["healthy_replicas"] == 2 and snap["replicas"] == 3


# ------------------------- tb_events round-trip + telemetry tail flush


def _crc32c_bitwise(data):
    """Independent (table-free) CRC32C for the round-trip pin — NOT
    the implementation under test."""
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
    return crc ^ 0xFFFFFFFF


def _unmask_check(masked, data):
    expect = ((_crc32c_bitwise(data) >> 15)
              | (_crc32c_bitwise(data) << 17)) + 0xA282EAD8
    return masked == (expect & 0xFFFFFFFF)


def _parse_event_file(path):
    """Minimal TFRecord + Event-proto parser: verifies both masked
    CRCs per record and decodes scalar summaries."""
    records = []
    with open(path, "rb") as f:
        blob = f.read()
    off = 0
    while off < len(blob):
        (length,) = struct.unpack_from("<Q", blob, off)
        header = blob[off:off + 8]
        (len_crc,) = struct.unpack_from("<I", blob, off + 8)
        payload = blob[off + 12:off + 12 + length]
        (data_crc,) = struct.unpack_from("<I", blob, off + 12 + length)
        assert _unmask_check(len_crc, header), "length CRC mismatch"
        assert _unmask_check(data_crc, payload), "payload CRC mismatch"
        records.append(payload)
        off += 12 + length + 4
    assert off == len(blob), "trailing garbage after last record"
    return [_parse_event(r) for r in records]


def _read_varint(buf, off):
    out = shift = 0
    while True:
        b = buf[off]
        off += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, off
        shift += 7


def _parse_fields(buf):
    """[(field_number, wire_type, value)] for one message level."""
    fields = []
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        num, wt = key >> 3, key & 7
        if wt == 0:
            val, off = _read_varint(buf, off)
        elif wt == 1:
            (val,) = struct.unpack_from("<d", buf, off)
            off += 8
        elif wt == 5:
            (val,) = struct.unpack_from("<f", buf, off)
            off += 4
        elif wt == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        else:
            raise AssertionError("unexpected wire type %d" % wt)
        fields.append((num, wt, val))
    return fields


def _parse_event(payload):
    """Event{1: wall_time, 2: step, 3: file_version, 5: summary}."""
    out = {"tags": {}}
    for num, _wt, val in _parse_fields(payload):
        if num == 1:
            out["wall_time"] = val
        elif num == 2:
            out["step"] = val
        elif num == 3:
            out["file_version"] = bytes(val)
        elif num == 5:
            for snum, _swt, sval in _parse_fields(val):
                if snum != 1:
                    continue
                tag, value = None, None
                for vnum, _vwt, vval in _parse_fields(sval):
                    if vnum == 1:
                        tag = bytes(vval).decode("utf-8")
                    elif vnum == 2:
                        value = vval
                out["tags"][tag] = value
    return out


def test_event_file_round_trips_through_independent_parser(tmp_path):
    """Pins the binary format the whole observability stack rides on:
    TFRecord framing with masked CRC32C + Event/Summary protobuf wire
    format, parsed back by an implementation-independent decoder."""
    from elasticdl_tpu.common.tb_events import EventFileWriter

    w = EventFileWriter(str(tmp_path))
    w.add_scalar("serving/ttft_ms", 12.5, 3)
    w.add_scalar("router/shed_total", 7.0, 4)
    w.close()
    events = _parse_event_file(w.path)
    assert events[0]["file_version"] == b"brain.Event:2"
    assert events[1]["tags"] == {
        "serving/ttft_ms": pytest.approx(12.5)
    }
    assert events[1]["step"] == 3
    assert events[2]["tags"] == {
        "router/shed_total": pytest.approx(7.0)
    }
    assert events[2]["step"] == 4
    assert all("wall_time" in e for e in events)


def test_telemetry_close_flushes_partial_window(tmp_path):
    """The satellite fix: a server stopped mid-window must still land
    its tokens/sec tail and final counter totals in the event file."""
    t = ServingTelemetry(log_dir=str(tmp_path), flush_every=50)
    t.count("admitted", 3)
    t.count("completed", 2)
    t.record_step(queue_depth=1, active_slots=2, step_secs=0.01,
                  tokens_committed=5)
    t.close()  # step 1 of 50: nothing flushed without the tail fix
    files = [f for f in os.listdir(str(tmp_path))
             if f.startswith("events.out.tfevents")]
    assert len(files) == 1
    events = _parse_event_file(os.path.join(str(tmp_path), files[0]))
    tags = {}
    for e in events:
        tags.update(e["tags"])
    assert tags["serving/admitted_total"] == pytest.approx(3.0)
    assert tags["serving/completed_total"] == pytest.approx(2.0)
    assert tags["serving/tokens_generated_total"] == pytest.approx(5.0)
    assert "serving/tokens_per_sec" in tags


# --------------------------------------------- training-plane span tree


class _FakeDispatcher(object):
    """Duck-typed task dispatcher for MasterServicer: one task, then
    re-dispatch of the same id, then reports."""

    def __init__(self):
        from elasticdl_tpu.master.task_dispatcher import Task, TaskType

        self._task = Task("shard", 0, 10, TaskType.TRAINING)
        self.model_version = 0

    def get(self, worker_id):
        return 1, self._task

    def get_eval_task(self, worker_id):
        return -1, None

    def finished(self):
        return False

    def invoke_deferred_callback(self):
        return False

    def report(self, task_id, success, exec_counters=None):
        return 0.5, self._task, 0


def test_master_task_dispatch_span_tree():
    from elasticdl_tpu.master.servicer import MasterServicer

    recorder().clear()
    servicer = MasterServicer(32, _FakeDispatcher())
    task = servicer.get_task(pb.GetTaskRequest(worker_id=0))
    assert task.trace_id and task.span_id  # context rides the proto

    # the worker-side span a real worker would open from those fields
    wspan = recorder().start_span(
        "worker_task", trace_id=task.trace_id,
        parent_span_id=task.span_id, task_id=task.task_id,
    )
    wspan.event("fetched")
    wspan.event("reported", ok=True)
    wspan.finish("ok")

    servicer.report_task_result(
        pb.ReportTaskResultRequest(task_id=task.task_id)
    )
    spans = [s.to_dict() for s in recorder().snapshot()
             if s.trace_id == task.trace_id]
    dispatch = [s for s in spans if s["name"] == "task_dispatch"]
    worker = [s for s in spans if s["name"] == "worker_task"]
    assert len(dispatch) == 1 and len(worker) == 1
    assert dispatch[0]["status"] == "ok"
    assert any(e["name"] == "reported" for e in dispatch[0]["events"])
    # one tree: worker span parents under the dispatch span
    assert worker[0]["parent_span_id"] == dispatch[0]["span_id"]
    assert trace_roots(spans)[0]["name"] == "task_dispatch"


def test_master_redispatch_seals_previous_task_span():
    from elasticdl_tpu.master.servicer import MasterServicer

    recorder().clear()
    servicer = MasterServicer(32, _FakeDispatcher())
    first = servicer.get_task(pb.GetTaskRequest(worker_id=0))
    second = servicer.get_task(pb.GetTaskRequest(worker_id=1))
    assert first.trace_id != second.trace_id
    sealed = [s for s in recorder().snapshot()
              if s.trace_id == first.trace_id]
    assert len(sealed) == 1 and sealed[0].status == "redispatched"
    # a late report for the sealed dispatch is simply untraced
    servicer.report_task_result(
        pb.ReportTaskResultRequest(task_id=first.task_id)
    )
