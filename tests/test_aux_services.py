"""ODPS reader (fake table), TensorBoard event writer (byte-level
verification of the TFRecord/Event encoding), TensorBoard service, and
the collective communicator contract."""

import glob
import struct

import numpy as np
import pytest

from elasticdl_tpu.common.tb_events import (
    EventFileWriter,
    crc32c,
    encode_scalar_event,
    frame_record,
)
from elasticdl_tpu.data.reader.odps_reader import ODPSDataReader, ODPSReader
from elasticdl_tpu.master.tensorboard_service import TensorboardService
from elasticdl_tpu.parallel.collective import (
    CollectiveCommunicator,
    CollectiveCommunicatorStatus,
)


# ------------------------------------------------------------- fake ODPS


class _FakeColumn(object):
    def __init__(self, name, type_):
        self.name = name
        self.type = type_


class _FakeSchema(object):
    def __init__(self):
        self.columns = [
            _FakeColumn("age", "bigint"), _FakeColumn("wage", "double"),
        ]


class _FakeReaderCtx(object):
    def __init__(self, rows, fail_times=None):
        self._rows = rows
        self._fail = fail_times
        self.count = len(rows)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def read(self, start, count):
        if self._fail and self._fail[0] > 0:
            self._fail[0] -= 1
            raise IOError("transient")
        return self._rows[start:start + count]


class _FakeTable(object):
    name = "census"
    schema = _FakeSchema()

    def __init__(self, rows, fail_times=None):
        self._rows = rows
        self._fail = fail_times

    def open_reader(self):
        return _FakeReaderCtx(self._rows, self._fail)


class _Task(object):
    def __init__(self, start, end):
        self.start, self.end = start, end


def test_odps_create_shards():
    table = _FakeTable([(i, i * 2.0) for i in range(25)])
    reader = ODPSDataReader(table=table, records_per_task=10)
    shards = reader.create_shards()
    assert shards == {
        "census:0": (0, 10), "census:10": (10, 10), "census:20": (20, 5),
    }


def test_odps_read_records_with_windows():
    rows = [(i, float(i)) for i in range(57)]
    table = _FakeTable(rows)
    reader = ODPSDataReader(table=table, records_per_task=100,
                            window_size=8)
    got = list(reader.read_records(_Task(5, 41)))
    assert got == rows[5:41]


def test_odps_window_retry():
    rows = [(i,) for i in range(20)]
    table = _FakeTable(rows, fail_times=[2])  # first two opens fail
    reader = ODPSReader(table, window_size=50)
    assert list(reader.read_range(0, 20)) == rows


def test_odps_parse_fn_and_metadata():
    rows = [(30, 1000.0), (40, 2000.0)]
    table = _FakeTable(rows)
    reader = ODPSDataReader(
        table=table, records_per_task=10,
        parse_fn=lambda row: {"age": row[0]},
    )
    assert list(reader.read_records(_Task(0, 2))) == [
        {"age": 30}, {"age": 40},
    ]
    meta = reader.metadata
    assert meta.column_names == ["age", "wage"]


def test_factory_odps_env(monkeypatch, tmp_path):
    from elasticdl_tpu.data.reader import data_reader_factory

    monkeypatch.setenv("MAXCOMPUTE_AK", "ak")
    monkeypatch.setenv("MAXCOMPUTE_SK", "sk")
    monkeypatch.setenv("MAXCOMPUTE_PROJECT", "proj")
    # table name (not a local path) + creds -> ODPS reader; no pyodps
    # installed -> a clear gating error, not a crash elsewhere
    with pytest.raises(RuntimeError, match="odps package"):
        data_reader_factory.create_data_reader("some_table", 10)


# ------------------------------------------------------------ tb events


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8a9136aa
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283


def test_frame_record_roundtrip():
    payload = b"hello world"
    rec = frame_record(payload)
    (length,) = struct.unpack("<Q", rec[:8])
    assert length == len(payload)
    assert rec[12:12 + length] == payload


def test_scalar_event_contains_tag():
    event = encode_scalar_event("loss", 1.5, step=7)
    assert b"loss" in event
    assert struct.pack("<f", 1.5) in event


def test_event_file_writer(tmp_path):
    writer = EventFileWriter(str(tmp_path))
    writer.add_scalar("accuracy", 0.93, 12)
    writer.close()
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    data = open(files[0], "rb").read()
    assert b"brain.Event:2" in data
    assert b"accuracy" in data


def test_tensorboard_service_writes_metrics(tmp_path):
    service = TensorboardService(str(tmp_path))
    service.write_dict_to_summary({"auc": 0.8, "loss": 0.1}, version=5)
    service.write_dict_to_summary({"auc": "not-a-number"}, version=6)
    service.stop()
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert files
    data = open(files[0], "rb").read()
    assert b"auc" in data and b"loss" in data


def test_tier_health_counters_reach_tensorboard(tmp_path):
    """Worker-reported tier/ exec counters (host-tier dropped-row
    gauges) become TensorBoard scalars through the master servicer —
    the observability contract for the by-design 'rows miss one
    update' degradation of the host embedding tier."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    task_d = TaskDispatcher(
        {"shard": (0, 8)}, {}, {}, records_per_task=8, num_epochs=1
    )
    tb = TensorboardService(str(tmp_path))
    servicer = MasterServicer(4, task_d, tensorboard_service=tb)
    task = servicer.get_task(pb.GetTaskRequest(worker_id=0))
    req = pb.ReportTaskResultRequest(task_id=task.task_id)
    req.exec_counters["tier/host_dropped_row_updates"] = 37
    req.exec_counters["tier/host_failed_cycles"] = 2
    req.exec_counters["unrelated"] = 5
    servicer.report_task_result(req)
    tb.stop()
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert files
    data = open(files[0], "rb").read()
    # per-worker tags: cumulative counters from different workers must
    # not interleave on one scalar
    assert b"tier/host_dropped_row_updates/worker-0" in data
    assert b"tier/host_failed_cycles/worker-0" in data
    assert b"unrelated" not in data


def test_tier_gauges_distinct_steps_no_data_loss(tmp_path):
    """Every report's cumulative counters land at a strictly
    increasing per-worker step: no duplicate points at one step (the
    sawtooth/overwrite artifact some TB backends render), and the tail
    of a cumulative counter is never dropped — the last report between
    version bumps is the freshest value."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    class SpyTB(object):
        def __init__(self):
            self.writes = []

        def write_dict_to_summary(self, gauges, version):
            self.writes.append((dict(gauges), version))

    task_d = TaskDispatcher(
        {"shard": (0, 32)}, {}, {}, records_per_task=8, num_epochs=1
    )
    tb = SpyTB()
    servicer = MasterServicer(4, task_d, tensorboard_service=tb)
    for value in (1, 2, 6):  # cumulative counter grows within a version
        servicer._write_tier_gauges(
            {"tier/host_failed_cycles": value}, worker_id=0)
    servicer._write_tier_gauges(
        {"tier/host_failed_cycles": 9}, worker_id=1)
    assert len(tb.writes) == 4  # nothing dropped
    w0 = [(g, s) for g, s in tb.writes
          if "tier/host_failed_cycles/worker-0" in g]
    assert [s for _, s in w0] == [0, 1, 2]  # distinct increasing steps
    assert w0[-1][0]["tier/host_failed_cycles/worker-0"] == 6
    w1 = [(g, s) for g, s in tb.writes
          if "tier/host_failed_cycles/worker-1" in g]
    assert [s for _, s in w1] == [0]  # independent per-worker counter


# ----------------------------------------------------------- collective


def test_collective_single_process_identity():
    comm = CollectiveCommunicator()
    assert not comm.has_backend()
    data = np.arange(4.0)
    status, out = comm.allreduce(data)
    assert status == CollectiveCommunicatorStatus.SUCCEEDED
    np.testing.assert_array_equal(out, data)
    status, out = comm.broadcast(data, 0)
    assert status == CollectiveCommunicatorStatus.SUCCEEDED
    assert comm.barrier() == CollectiveCommunicatorStatus.SUCCEEDED


def test_collective_rejects_bad_op():
    comm = CollectiveCommunicator()
    status, _ = comm.allreduce(np.ones(2), op="MAX")
    assert status == CollectiveCommunicatorStatus.FAILED
    status, _ = comm.allreduce(None)
    assert status == CollectiveCommunicatorStatus.FAILED


# ------------------------------------------------------------ profiler


def test_profile_trace_writes_trace(tmp_path):
    import glob as _glob

    import jax.numpy as jnp

    from elasticdl_tpu.common.profiler import (
        profile_trace,
        step_annotation,
    )

    with profile_trace(str(tmp_path)):
        with step_annotation(0):
            jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    files = _glob.glob(str(tmp_path / "**" / "*.xplane.pb"),
                       recursive=True)
    assert files, "no xplane trace written"


def test_validate_job_status_fake_api():
    from scripts.validate_job_status import validate

    class FakeApi(object):
        def __init__(self, phases):
            self._phases = phases

        def read_namespaced_pod(self, namespace, name):
            phase = (
                self._phases.pop(0) if len(self._phases) > 1
                else self._phases[0]
            )
            return {"status": {"phase": phase}}

    ok = validate("j", core_api=FakeApi(["Running", "Succeeded"]),
                  poll_interval=0)
    assert ok == 0
    bad = validate("j", core_api=FakeApi(["Failed"]), poll_interval=0)
    assert bad == 1
