"""Sequence-packing data helper + end-to-end packed training.

The model-side contract (segment-confined attention, restarting
positions) is tested in tests/test_attention.py; here: the packing
layout itself, label masking at boundaries, and a packed Trainer step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data.packing import (
    IGNORE_LABEL,
    pack_sequences,
    packing_efficiency,
)
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


def test_pack_layout_and_label_masking():
    seqs = [
        np.arange(1, 9),       # 8 tokens
        np.arange(10, 16),     # 6 tokens
        np.arange(20, 23),     # 3 tokens
    ]
    tokens, seg, labels = pack_sequences(seqs, row_len=16, pad_id=0)
    assert tokens.shape == seg.shape == labels.shape
    assert tokens.shape[1] == 16
    # every real target is the next token of the SAME segment
    for r in range(tokens.shape[0]):
        for i in range(15):
            if labels[r, i] != IGNORE_LABEL:
                assert seg[r, i] == seg[r, i + 1]
                assert labels[r, i] == tokens[r, i + 1]
        # last position never carries a target
        assert labels[r, 15] == IGNORE_LABEL
    # per-segment last positions are masked
    total_targets = int((labels != IGNORE_LABEL).sum())
    assert total_targets == (8 - 1) + (6 - 1) + (3 - 1)
    # segments are contiguous and start at 0 per row
    for r in range(tokens.shape[0]):
        sids = seg[r]
        assert sids[0] == 0
        assert (np.diff(sids) >= 0).all()
        assert (np.diff(sids) <= 1).all()


def test_pack_splits_long_sequences():
    tokens, seg, labels = pack_sequences(
        [np.arange(40)], row_len=16
    )
    # 40 tokens -> chunks 16, 16, 8 -> 39 - 2 boundary drops... each
    # chunk carries len-1 targets: 15 + 15 + 7
    assert int((labels != IGNORE_LABEL).sum()) == 15 + 15 + 7


def test_pack_rejects_unpackable():
    with pytest.raises(ValueError, match="no packable"):
        pack_sequences([[5]], row_len=8)


def test_packing_efficiency_beats_padding():
    rs = np.random.RandomState(0)
    seqs = [rs.randint(1, 50, size=rs.randint(4, 17)) for _ in range(40)]
    eff = packing_efficiency(seqs, row_len=32)
    # pad-to-32 efficiency of these short docs is ~10/32 = 0.3
    assert eff > 0.8
    pad_eff = sum(len(s) for s in seqs) / (len(seqs) * 32)
    assert eff > pad_eff


def test_packed_trainer_step_learns():
    """A packed batch drives the full jit train step: loss decreases on
    a deterministic next=(tok+1) pattern, and boundary targets do not
    leak (the masked loss stays finite with IGNORE_LABEL present)."""
    rs = np.random.RandomState(3)
    seqs = [
        (np.arange(m) + s) % 16
        for m, s in zip(rs.randint(6, 15, size=24),
                        rs.randint(0, 16, size=24))
    ]
    tokens, seg, labels = pack_sequences(seqs, row_len=32, pad_id=0)
    n = (len(tokens) // 2) * 2  # even batch for the dp=1 mesh
    batch = (
        {
            "tokens": jnp.asarray(tokens[:n]),
            "segment_ids": jnp.asarray(seg[:n]),
        },
        jnp.asarray(labels[:n]),
    )
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=("vocab_size=16; seq_len=32; embed_dim=32; "
                      "num_heads=2; num_layers=1"),
    )
    state = trainer.init_state(batch)
    losses = []
    for _ in range(30):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7


def test_bert_packed_rows_match_unpacked():
    """Packing contract on the bidirectional encoder: a packed row must
    reproduce the separate-row logits (non-causal segment masking +
    restarting learned positions)."""
    import os
    os.environ["ELASTICDL_TPU_FORCE_INTERPRET"] = "1"
    try:
        from model_zoo.bert.bert import BertEncoder

        model = BertEncoder(
            vocab_size=32, seq_len=32, embed_dim=32, num_heads=2,
            num_layers=2, tp_shard=False,
        )
        rs = np.random.RandomState(2)
        seq_a = rs.randint(0, 32, size=(1, 16)).astype(np.int32)
        seq_b = rs.randint(0, 32, size=(1, 16)).astype(np.int32)
        packed = jnp.asarray(np.concatenate([seq_a, seq_b], axis=1))
        seg = jnp.asarray([[0] * 16 + [1] * 16], jnp.int32)
        params = model.init(jax.random.PRNGKey(0), {"tokens": packed})
        lp = model.apply(
            params, {"tokens": packed, "segment_ids": seg}
        )
        la = model.apply(params, {"tokens": jnp.asarray(seq_a)})
        lb = model.apply(params, {"tokens": jnp.asarray(seq_b)})
        np.testing.assert_allclose(
            np.asarray(lp[:, :16]), np.asarray(la), rtol=2e-4,
            atol=2e-5,
        )
        np.testing.assert_allclose(
            np.asarray(lp[:, 16:]), np.asarray(lb), rtol=2e-4,
            atol=2e-5,
        )
    finally:
        os.environ.pop("ELASTICDL_TPU_FORCE_INTERPRET", None)


def test_pack_dataset_streaming():
    """The streaming Dataset packer: every emitted row obeys the packed
    layout invariants, all targets are preserved, and .batch() yields
    model-ready packed batches."""
    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.packing import pack_dataset

    rs = np.random.RandomState(7)
    seqs = [rs.randint(1, 99, size=rs.randint(2, 40)).astype(np.int32)
            for _ in range(60)]
    ds = pack_dataset(Dataset.from_list(list(seqs)), row_len=32)
    rows = list(ds)
    assert rows, "packer emitted nothing"
    total_targets = 0
    for features, labels in rows:
        tokens, seg = features["tokens"], features["segment_ids"]
        assert tokens.shape == seg.shape == labels.shape == (32,)
        for i in range(31):
            if labels[i] != -100:
                assert seg[i] == seg[i + 1]
                assert labels[i] == tokens[i + 1]
        assert labels[31] == -100
        total_targets += int((labels != -100).sum())
    # every sequence chunk of length m contributes m-1 targets
    expect = 0
    for s in seqs:
        for start in range(0, len(s), 32):
            m = len(s[start:start + 32])
            if m >= 2:
                expect += m - 1
    assert total_targets == expect
    # batched rows feed the packed Trainer contract
    batches = list(
        pack_dataset(Dataset.from_list(list(seqs)), row_len=32)
        .batch(4, drop_remainder=True)
    )
    feats, labels = batches[0]
    assert feats["tokens"].shape == (4, 32)
    assert feats["segment_ids"].shape == (4, 32)
    assert labels.shape == (4, 32)


def test_pack_dataset_bounded_open_rows():
    """A pathological order (big chunk after many small open rows) must
    emit rows to make room rather than grow without bound."""
    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.packing import pack_dataset

    seqs = [np.arange(2)] * 6 + [np.arange(30)] * 4
    rows = list(
        pack_dataset(Dataset.from_list(list(seqs)), row_len=32,
                     open_rows=2)
    )
    total_targets = sum(int((lab != -100).sum()) for _, lab in rows)
    assert total_targets == 6 * 1 + 4 * 29


def test_packed_zoo_family_local_executor(tmp_path):
    """End-to-end worker path: variable-length cyclic documents ->
    streaming packer inside dataset_fn -> packed train steps via
    LocalExecutor; loss must fall on the learnable cycle data."""
    from elasticdl_tpu.api.local_executor import LocalExecutor
    from elasticdl_tpu.data import recordio_gen
    from model_zoo.transformer_lm_packed import (
        transformer_lm_packed as packed_zoo,
    )

    train_dir = str(tmp_path / "train")
    val_dir = str(tmp_path / "val")
    recordio_gen.gen_docs_like(train_dir, num_files=2,
                               records_per_file=96, vocab_size=16,
                               cyclic=True)
    recordio_gen.gen_docs_like(val_dir, num_files=1,
                               records_per_file=32, vocab_size=16,
                               cyclic=True, seed=9)
    spec = load_model_spec_from_module(packed_zoo)
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=4,
        num_epochs=4,
        records_per_task=48,
        model_params=("vocab_size=16; seq_len=128; embed_dim=64; "
                      "num_heads=2; num_layers=1"),
    )
    state, metrics = executor.run()
    losses = np.asarray(executor.losses)
    assert np.isfinite(losses).all()
    assert losses[-3:].mean() < losses[:3].mean() * 0.7
    assert 0.0 <= metrics["token_accuracy"] <= 1.0


def test_packed_training_on_sharded_mesh():
    """Packed batches (segment_ids riding in features) shard over the
    8-device dp*fsdp mesh: parity with the single-device trainer on the
    same packed data, step for step."""
    from elasticdl_tpu.data.packing import pack_sequences

    rs = np.random.RandomState(5)
    seqs = [
        (np.arange(m) + s) % 16
        for m, s in zip(rs.randint(6, 15, size=40),
                        rs.randint(0, 16, size=40))
    ]
    tokens, seg, labels = pack_sequences(seqs, row_len=32, pad_id=0)
    n = 8  # divisible by dp*fsdp
    batch = (
        {
            "tokens": jnp.asarray(tokens[:n]),
            "segment_ids": jnp.asarray(seg[:n]),
        },
        jnp.asarray(labels[:n]),
    )
    params = ("vocab_size=16; seq_len=32; embed_dim=32; num_heads=2; "
              "num_layers=1")
    spec1 = load_model_spec_from_module(zoo)
    mesh1 = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    t1 = Trainer(spec1, mesh=mesh1, model_params=params)
    s1 = t1.init_state(batch)
    mesh8 = mesh_lib.build_mesh({"dp": 4, "fsdp": 2})
    t8 = Trainer(load_model_spec_from_module(zoo), mesh=mesh8,
                 model_params=params)
    s8 = t8.init_state(batch)
    for _ in range(5):
        s1, l1 = t1.train_step(s1, batch)
        s8, l8 = t8.train_step(s8, batch)
        np.testing.assert_allclose(float(l1), float(l8), rtol=1e-4)


def test_packed_training_on_sp_mesh():
    """Packed long-context path: segment ids flow through RING
    attention over the sp axis (k-side ids rotate with their shard);
    loss parity with the single-device packed trainer."""
    from elasticdl_tpu.data.packing import pack_sequences

    rs = np.random.RandomState(11)
    seqs = [
        (np.arange(m) + s) % 16
        for m, s in zip(rs.randint(6, 15, size=40),
                        rs.randint(0, 16, size=40))
    ]
    tokens, seg, labels = pack_sequences(seqs, row_len=32, pad_id=0)
    n = 4
    batch = (
        {
            "tokens": jnp.asarray(tokens[:n]),
            "segment_ids": jnp.asarray(seg[:n]),
        },
        jnp.asarray(labels[:n]),
    )
    params = ("vocab_size=16; seq_len=32; embed_dim=32; num_heads=2; "
              "num_layers=1")
    mesh1 = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    t1 = Trainer(load_model_spec_from_module(zoo), mesh=mesh1,
                 model_params=params)
    s1 = t1.init_state(batch)
    mesh_sp = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    tsp = Trainer(load_model_spec_from_module(zoo), mesh=mesh_sp,
                  model_params=params)
    ssp = tsp.init_state(batch)
    for _ in range(5):
        s1, l1 = t1.train_step(s1, batch)
        ssp, lsp = tsp.train_step(ssp, batch)
        np.testing.assert_allclose(float(l1), float(lsp), rtol=1e-4)


def test_packed_family_through_master_worker():
    """The packed zoo family through the DISTRIBUTED path: master task
    queue + in-process servicer + task-driven worker, variable-length
    document records packed inside the worker's dataset_fn stream."""
    import tempfile

    from elasticdl_tpu.data import recordio_gen
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.worker.worker import JobType, Worker
    from model_zoo.transformer_lm_packed import (
        transformer_lm_packed as packed_zoo,
    )

    train_dir = tempfile.mkdtemp()
    recordio_gen.gen_docs_like(train_dir, num_files=2,
                               records_per_file=64, vocab_size=16,
                               cyclic=True)
    params = ("vocab_size=16; seq_len=128; embed_dim=32; "
              "num_heads=2; num_layers=1")
    master = Master(
        load_model_spec_from_module(packed_zoo),
        training_data=train_dir,
        minibatch_size=4,
        records_per_task=32,
        num_epochs=2,
    )
    worker = Worker(
        0,
        load_model_spec_from_module(packed_zoo),
        master_servicer=master.servicer,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=4,
        training_data=train_dir,
        wait_sleep_secs=0.05,
        model_params=params,
    )
    state = worker.run()
    assert master.task_d.finished()
    assert state is not None and int(state.step) >= 1
    losses = np.asarray(worker.losses)
    assert np.isfinite(losses).all()
    # cyclic docs: the packed stream is learnable
    assert losses[-3:].mean() < losses[:3].mean()
