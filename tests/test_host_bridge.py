"""Host-spill embedding tier, integrated end-to-end (VERDICT.md round-1
item #5): deepfm trains with tables in the host store, loss matches the
HBM path on the same data, and engine state rides the checkpoint."""

import numpy as np
import pytest

import jax

from elasticdl_tpu.api.local_executor import LocalExecutor
from elasticdl_tpu.common.model_utils import (
    format_params_str,
    get_model_spec,
    load_model_spec_from_module,
)
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.embedding.host_bridge import (
    HostEmbeddingManager,
    build_manager_from_spec,
    restore_host_state,
)
from elasticdl_tpu.embedding.host_spill import HostSpillEmbeddingEngine
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer

MODEL_ZOO = "model_zoo"
VOCAB, DIM, LENGTH, FC = 100, 8, 5, 4


def _batches(n, batch=8, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = rng.randint(0, VOCAB, size=(batch, LENGTH)).astype(np.int32)
        labels = rng.randint(0, 2, size=(batch,)).astype(np.int32)
        out.append(({"feature": ids}, labels))
    return out


def _host_trainer():
    from model_zoo.deepfm_host_embedding import deepfm_host_embedding as zoo

    spec = load_model_spec_from_module(zoo)
    trainer = Trainer(
        spec,
        mesh=mesh_lib.local_mesh(),
        model_params=format_params_str(
            dict(input_length=LENGTH, fc_unit=FC)
        ),
    )
    manager = HostEmbeddingManager()
    manager.register(
        "edl_embedding", "feature",
        HostSpillEmbeddingEngine(DIM, optimizer="sgd", lr=0.1),
    )
    manager.register(
        "edl_id_bias", "feature",
        HostSpillEmbeddingEngine(1, optimizer="sgd", lr=0.1),
    )
    trainer.attach_host_embeddings(manager)
    return trainer, manager


def _hbm_trainer():
    from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo

    spec = load_model_spec_from_module(zoo)
    return Trainer(
        spec,
        mesh=mesh_lib.local_mesh(),
        model_params=format_params_str(
            dict(input_dim=VOCAB, embedding_dim=DIM,
                 input_length=LENGTH, fc_unit=FC)
        ),
    )


def test_parity_with_hbm_path():
    """Same data, same init, same optimizer: host-tier deepfm's loss
    trajectory matches the HBM-tier deepfm (the reference proved its PS
    path this way — worker_ps_interaction_test.py:197-265 trains against
    a local baseline)."""
    batches = _batches(6)

    hbm = _hbm_trainer()
    hbm_state = hbm.init_state(batches[0])
    hbm_params = jax.tree.map(np.asarray, jax.device_get(hbm_state.params))

    host, manager = _host_trainer()
    host_state = host.init_state(batches[0])

    # Seed the host engines with the HBM model's initial tables, and copy
    # the dense (Dense_*) params so both models start identically.
    all_ids = np.arange(VOCAB, dtype=np.int64)
    tables = manager.tables()
    tables["edl_embedding"].engine.param.set_rows(
        all_ids, hbm_params["edl_embedding"]["embedding_table"]
    )
    tables["edl_id_bias"].engine.param.set_rows(
        all_ids, hbm_params["edl_id_bias"]["embedding_table"]
    )
    new_params = {
        k: hbm_params[k] for k in host_state.params
    }
    host_state = host_state.replace(
        params=jax.device_put(
            new_params,
            jax.tree.map(lambda x: x.sharding, dict(host_state.params)),
        )
    )

    hbm_losses, host_losses = [], []
    for b in batches:
        hbm_state, l1 = hbm.train_step(hbm_state, b)
        host_state, l2 = host.train_step(host_state, b)
        hbm_losses.append(float(l1))
        host_losses.append(float(l2))
    np.testing.assert_allclose(host_losses, hbm_losses, rtol=2e-4,
                               atol=2e-5)

    # and the trained tables themselves match
    ids, values = tables["edl_embedding"].engine.param.export_rows()
    order = np.argsort(ids)
    final_hbm = np.asarray(
        jax.device_get(hbm_state.params["edl_embedding"]["embedding_table"])
    )
    np.testing.assert_allclose(
        values[order], final_hbm[np.sort(ids)], rtol=2e-4, atol=2e-5
    )


def test_gradients_only_touch_pulled_rows():
    """Untouched host rows never move (reference OptimizerWrapper
    semantics: only looked-up rows and slots are written back)."""
    host, manager = _host_trainer()
    batches = _batches(1)
    state = host.init_state(batches[0])
    engine = manager.tables()["edl_embedding"].engine

    all_ids = np.arange(VOCAB, dtype=np.int64)
    before = engine.param.lookup(all_ids).copy()
    state, _ = host.train_step(state, batches[0])
    after = engine.param.lookup(all_ids)

    touched = np.unique(batches[0][0]["feature"])
    untouched = np.setdiff1d(all_ids, touched)
    np.testing.assert_array_equal(after[untouched], before[untouched])
    assert np.abs(after[touched] - before[touched]).max() > 0


def test_engine_failure_counts_dropped_rows():
    """A host-engine apply failure is contained (the step still
    completes — the state was donated, so there is no retry) AND
    observable: tier_health counts the failed cycle and the row updates
    that were dropped, and a recovered engine stops the counters."""
    host, manager = _host_trainer()
    batches = _batches(3)
    state = host.init_state(batches[0])
    state, _ = host.train_step(state, batches[0])
    assert host.tier_health == {
        "host_failed_cycles": 0, "host_dropped_row_updates": 0,
    }

    engine = manager.tables()["edl_embedding"].engine
    real_apply = engine.apply_gradients

    def broken(*a, **kw):
        raise RuntimeError("injected engine failure")

    engine.apply_gradients = broken
    state, loss = host.train_step(state, batches[1])
    assert np.isfinite(float(loss))  # contained, not propagated
    assert host.tier_health["host_failed_cycles"] == 1
    expect_rows = manager.pending_row_count()
    assert expect_rows > 0
    assert host.tier_health["host_dropped_row_updates"] == expect_rows

    engine.apply_gradients = real_apply
    state, _ = host.train_step(state, batches[2])
    assert host.tier_health["host_failed_cycles"] == 1


def test_engine_failure_in_accum_cycle_counts_all_staged_rows():
    """With gradient accumulation, a macro-boundary apply_staged
    failure drops EVERY staged microbatch's row updates — the counter
    must cover the whole cycle, not just the last microbatch."""
    from model_zoo.deepfm_host_embedding import deepfm_host_embedding as zoo

    spec = load_model_spec_from_module(zoo)
    host = Trainer(
        spec,
        mesh=mesh_lib.local_mesh(),
        model_params=format_params_str(
            dict(input_length=LENGTH, fc_unit=FC)
        ),
        grad_accum_steps=2,
    )
    manager = HostEmbeddingManager()
    for name, dim in (("edl_embedding", DIM), ("edl_id_bias", 1)):
        manager.register(
            name, "feature",
            HostSpillEmbeddingEngine(dim, optimizer="sgd", lr=0.1),
        )
    host.attach_host_embeddings(manager)
    batches = _batches(2)
    state = host.init_state(batches[0])

    for t in manager.tables().values():
        t.engine.apply_gradients = lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("injected")
        )
    state, _ = host.train_step(state, batches[0])  # microbatch 1: stages
    rows_mb1 = manager.staged_row_count()
    assert rows_mb1 > 0
    assert host.tier_health["host_failed_cycles"] == 0  # no apply yet
    state, _ = host.train_step(state, batches[1])  # boundary: fails
    assert host.tier_health["host_failed_cycles"] == 1
    # both microbatches' staged rows counted, not just the last pull
    assert (host.tier_health["host_dropped_row_updates"]
            > manager.pending_row_count())
    assert (host.tier_health["host_dropped_row_updates"]
            >= rows_mb1 + manager.pending_row_count())


def test_zoo_e2e_local_executor(tmp_path):
    """The deepfm_host_embedding zoo family trains + evaluates through
    the LocalExecutor like every other family (test_model_zoo pattern)."""
    train_dir, val_dir = str(tmp_path / "train"), str(tmp_path / "val")
    recordio_gen.gen_frappe_like(train_dir, num_files=1,
                                 records_per_file=32)
    recordio_gen.gen_frappe_like(val_dir, num_files=1,
                                 records_per_file=32, seed=7)
    spec = get_model_spec(
        MODEL_ZOO, "deepfm_host_embedding.deepfm_host_embedding.custom_model"
    )
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=8,
        num_epochs=1,
        records_per_task=32,
    )
    state, metrics = executor.run()
    assert int(state.step) == 4
    assert np.isfinite(executor.losses).all()
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0
    # the engines actually hold trained rows
    ids, _ = (
        executor._host_manager.tables()["edl_embedding"]
        .engine.param.export_rows()
    )
    assert ids.size > 0


def test_checkpoint_roundtrip_and_resume(tmp_path):
    """Engine state rides the sharded checkpoint: a fresh manager
    restored from disk equals the trained one, and a resumed executor
    continues from the saved version."""
    train_dir = str(tmp_path / "train")
    ckpt_dir = str(tmp_path / "ckpt")
    recordio_gen.gen_frappe_like(train_dir, num_files=1,
                                 records_per_file=32)
    spec = get_model_spec(
        MODEL_ZOO, "deepfm_host_embedding.deepfm_host_embedding.custom_model"
    )
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        minibatch_size=8,
        num_epochs=1,
        records_per_task=32,
        checkpoint_dir=ckpt_dir,
        checkpoint_steps=4,  # == final step: last save captures the end
    )
    executor.run()
    trained_flat = executor._host_manager.flat_state()

    manager2 = build_manager_from_spec(spec)
    version = restore_host_state(manager2, ckpt_dir)
    assert version == 4
    restored_flat = manager2.flat_state()
    assert set(restored_flat) == set(trained_flat)

    def rows_by_id(flat, base):
        ids = np.asarray(flat[base + ".ids"])
        values = np.asarray(flat[base + ".values"])
        return values[np.argsort(ids)], np.sort(ids)

    for key in trained_flat:
        if key.endswith(".values"):
            continue  # compared id-aligned below
        if key.endswith(".ids"):
            base = key[: -len(".ids")]
            got_v, got_i = rows_by_id(restored_flat, base)
            want_v, want_i = rows_by_id(trained_flat, base)
            np.testing.assert_array_equal(got_i, want_i)
            # id-aligned row compare: catches restores that re-associate
            # rows with the wrong ids (column-wise sorting would not)
            np.testing.assert_allclose(got_v, want_v)
        else:
            assert restored_flat[key] == trained_flat[key]

    resumed = LocalExecutor(
        spec,
        training_data=train_dir,
        minibatch_size=8,
        num_epochs=1,
        records_per_task=32,
        checkpoint_dir_for_init=ckpt_dir,
    )
    resumed.run()
    assert int(resumed.state.step) > 4  # continued past the restore
    assert np.isfinite(resumed.losses).all()


def test_lr_scale_reaches_engine():
    """The scheduler multiplier scales host-row updates (Trainer passes
    lr_scale so every parameter tier sees the same schedule)."""
    eng_a = HostSpillEmbeddingEngine(4, optimizer="sgd", lr=0.5)
    eng_b = HostSpillEmbeddingEngine(4, optimizer="sgd", lr=0.5)
    ids = np.array([1, 2], np.int64)
    _, rows_a, _ = eng_a.pull(ids)
    eng_b.pull(ids)
    grads = np.ones((2, 4), np.float32)
    eng_a.apply_gradients(ids, grads, lr_scale=1.0)
    eng_b.apply_gradients(ids, grads, lr_scale=0.5)
    np.testing.assert_allclose(
        eng_a.param.lookup(ids), rows_a - 0.5, atol=1e-6
    )
    np.testing.assert_allclose(
        eng_b.param.lookup(ids), rows_a - 0.25, atol=1e-6
    )


class _FakeSPMDCtx(object):
    """Emulates a 2-host SPMDContext inside one process: the test sets
    `gathered` to the stacked per-host id tensors before each prepare,
    and rows_positions pretends host p's rows occupy the contiguous
    block [p*cap, (p+1)*cap) — consistent with how the test assembles
    the global rows feature by concatenation."""

    def __init__(self, process_index, num_processes=2):
        self.num_processes = num_processes
        self.process_index = process_index
        self.is_multiprocess = True
        self.batch_partitions = 1
        self.gathered = None

    def allgather(self, local_np):
        return self.gathered

    def rows_positions(self, global_len):
        cap = global_len // self.num_processes
        return {
            p: np.arange(p * cap, (p + 1) * cap)
            for p in range(self.num_processes)
        }


def _spmd_host_manager(ctx):
    manager = HostEmbeddingManager()
    manager.register(
        "edl_embedding", "feature",
        HostSpillEmbeddingEngine(DIM, optimizer="sgd", lr=0.1),
    )
    manager.register(
        "edl_id_bias", "feature",
        HostSpillEmbeddingEngine(1, optimizer="sgd", lr=0.1),
    )
    manager.enable_spmd(ctx)
    return manager


def test_spmd_host_embedding_parity():
    """Two emulated hosts with id-partitioned host tables train to
    exactly the single-process result: same per-step losses, and the
    union of the hosts' owned rows equals the single-store table (the
    reference's PS scatter — each id lives on one pod — reproduced as
    owner_of partitioning)."""
    from model_zoo.deepfm_host_embedding import deepfm_host_embedding as zoo
    from elasticdl_tpu.embedding.host_bridge import (
        IDX_SUFFIX,
        ROWS_SUFFIX,
        owner_of,
    )

    spec = load_model_spec_from_module(zoo)
    mp = format_params_str(dict(input_length=LENGTH, fc_unit=FC))
    batches = _batches(5, batch=8)

    # ---- baseline: one process, one store
    base = Trainer(spec, mesh=mesh_lib.local_mesh(), model_params=mp)
    base_mgr = HostEmbeddingManager()
    base_mgr.register(
        "edl_embedding", "feature",
        HostSpillEmbeddingEngine(DIM, optimizer="sgd", lr=0.1),
    )
    base_mgr.register(
        "edl_id_bias", "feature",
        HostSpillEmbeddingEngine(1, optimizer="sgd", lr=0.1),
    )
    base.attach_host_embeddings(base_mgr)
    base_state = base.init_state(batches[0])
    base_losses = []
    for b in batches:
        base_state, loss = base.train_step(base_state, b)
        base_losses.append(float(loss))

    # ---- emulated 2-host SPMD over the same global batches
    ctxs = [_FakeSPMDCtx(0), _FakeSPMDCtx(1)]
    mgrs = [_spmd_host_manager(c) for c in ctxs]
    spmd = Trainer(spec, mesh=mesh_lib.local_mesh(), model_params=mp)
    spmd.attach_host_embeddings(mgrs[0])

    def run_round(state, batch, init_only=False):
        (features, labels) = batch
        ids = np.asarray(features["feature"])
        half = ids.shape[0] // 2
        locals_ = [ids[:half], ids[half:]]
        stacked = np.stack(locals_)
        prepped = []
        for p in range(2):
            ctxs[p].gathered = stacked
            prepped.append(mgrs[p].prepare({"feature": locals_[p]}))
        cap = prepped[0]["edl_embedding" + ROWS_SUFFIX].shape[0]
        gf = {
            "feature": ids,
        }
        for key in ("edl_embedding", "edl_id_bias"):
            gf[key + ROWS_SUFFIX] = np.concatenate(
                [pr[key + ROWS_SUFFIX] for pr in prepped]
            )
            gf[key + IDX_SUFFIX] = np.concatenate(
                [pr[key + IDX_SUFFIX] for pr in prepped]
            )
        if init_only:
            return gf
        gw = np.ones((ids.shape[0],), np.float32)
        state, loss, host_grads, _ = spmd._run_train_step(
            state, gf, labels, gw
        )
        for p in range(2):
            mgrs[p].apply(host_grads)
        return state, float(loss)

    gf0 = run_round(None, batches[0], init_only=True)
    spmd_state = spmd.init_state((gf0, batches[0][1]))
    spmd_losses = []
    for b in batches:
        spmd_state, loss = run_round(spmd_state, b)
        spmd_losses.append(loss)

    np.testing.assert_allclose(spmd_losses, base_losses, rtol=1e-5,
                               atol=1e-6)

    # ownership is disjoint+exhaustive and the union matches the baseline
    for table in ("edl_embedding", "edl_id_bias"):
        base_ids, base_vals = (
            base_mgr.tables()[table].engine.param.export_rows()
        )
        merged = {}
        for p in range(2):
            ids_p, vals_p = (
                mgrs[p].tables()[table].engine.param.export_rows()
            )
            assert np.all(owner_of(ids_p, 2) == p)
            merged.update(zip(ids_p.tolist(), vals_p))
        assert sorted(merged) == sorted(base_ids.tolist())
        base_map = dict(zip(base_ids.tolist(), base_vals))
        for i in merged:
            np.testing.assert_allclose(
                merged[i], base_map[i], rtol=1e-5, atol=1e-6
            )


def test_spmd_host_state_repartitions_on_load():
    """A checkpoint written by 2 partitioned hosts restores onto 1 host
    (merge) and back onto a 2-host manager (filter to owned) — the
    host-tier analogue of the re-shardable dense checkpoint."""
    ctxs = [_FakeSPMDCtx(0), _FakeSPMDCtx(1)]
    mgrs = [_spmd_host_manager(c) for c in ctxs]
    # touch disjoint owned rows on each "host"
    for p, mgr in enumerate(mgrs):
        eng = mgr.tables()["edl_embedding"].engine
        ids = np.asarray([i for i in range(20) if i % 2 == p], np.int64)
        eng.pull(ids)
        eng.apply_gradients(ids, np.ones((ids.size, DIM), np.float32))
    flat = {}
    for mgr in mgrs:
        flat.update(mgr.flat_state())

    # restore into a single-process manager: gets ALL rows
    single = HostEmbeddingManager()
    single.register(
        "edl_embedding", "feature",
        HostSpillEmbeddingEngine(DIM, optimizer="sgd", lr=0.1),
    )
    single.register(
        "edl_id_bias", "feature",
        HostSpillEmbeddingEngine(1, optimizer="sgd", lr=0.1),
    )
    single.load_flat_state(flat)
    ids, vals = single.tables()["edl_embedding"].engine.param.export_rows()
    assert sorted(ids.tolist()) == list(range(20))

    # restore the single-process state back into partitioned managers:
    # each keeps only its owned ids
    single_flat = single.flat_state()
    for p in range(2):
        fresh = _spmd_host_manager(_FakeSPMDCtx(p))
        fresh.load_flat_state(single_flat)
        got, _ = fresh.tables()["edl_embedding"].engine.param.export_rows()
        assert sorted(got.tolist()) == [i for i in range(20) if i % 2 == p]


def test_apply_before_prepare_raises():
    manager = HostEmbeddingManager()
    manager.register(
        "t", "feature", HostSpillEmbeddingEngine(4, optimizer="sgd")
    )
    with pytest.raises(RuntimeError):
        manager.apply({"t.rows": np.zeros((8, 4), np.float32)})
