"""Checkpoint subsystem: versioned sharded save/restore with resharding.

Mirrors reference tests/save_utils_test.py concerns: round-trip equality,
latest-valid-version discovery, keep-max pruning, restore with a different
shard count, and resume continuing training bit-exactly.
"""

import os

import numpy as np
import pytest

from elasticdl_tpu.checkpoint import (
    CheckpointSaver,
    get_latest_checkpoint_version,
    load_checkpoint,
    restore_state_from_checkpoint,
)


@pytest.fixture(scope="module")
def trainer_and_batch():
    from elasticdl_tpu.common.model_utils import load_model_spec_from_module
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    spec = load_model_spec_from_module(zoo)
    trainer = Trainer(spec, mesh=mesh_lib.build_mesh({"dp": -1, "fsdp": 2}))
    rng = np.random.RandomState(0)
    batch = (
        {"image": rng.rand(16, 28, 28).astype(np.float32)},
        rng.randint(10, size=(16,)).astype(np.int32),
    )
    return trainer, batch


@pytest.fixture
def trainer_and_state(trainer_and_batch):
    # train_step donates its input state, so every test gets a fresh one
    trainer, batch = trainer_and_batch
    return trainer, trainer.init_state(batch), batch


def _flat_np(state):
    from elasticdl_tpu.checkpoint.saver import flatten_state

    return flatten_state(state)


def test_save_load_roundtrip(tmp_path, trainer_and_state):
    _, state, _ = trainer_and_state
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=1, num_shards=3)
    saver.save(state, version=5)

    assert get_latest_checkpoint_version(str(tmp_path)) == 5
    vdir = tmp_path / "version-5"
    shard_files = sorted(
        f for f in os.listdir(vdir) if f.startswith("variables-")
    )
    assert shard_files == [
        "variables-%d-of-3.ckpt" % i for i in range(3)
    ]

    flat, version = load_checkpoint(str(tmp_path))
    assert version == 5
    expect = _flat_np(state)
    assert set(flat) == set(expect)
    for k in expect:
        np.testing.assert_array_equal(flat[k], expect[k])


@pytest.mark.slow
def test_restore_reshards_onto_state(tmp_path, trainer_and_state):
    trainer, state, batch = trainer_and_state
    # advance one step so restored != fresh
    state1, _ = trainer.train_step(state, batch)
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=1, num_shards=7)
    saver.save(state1, version=1)

    fresh = trainer.init_state(batch)
    restored, version = restore_state_from_checkpoint(fresh, str(tmp_path))
    assert version == 1
    got, expect = _flat_np(restored), _flat_np(state1)
    for k in expect:
        np.testing.assert_array_equal(got[k], expect[k])
    # restored leaves keep the target sharding → training continues bit-exact
    s_a, loss_a = trainer.train_step(state1, batch)
    s_b, loss_b = trainer.train_step(restored, batch)
    assert float(loss_a) == pytest.approx(float(loss_b), abs=0)
    flat_a, flat_b = _flat_np(s_a), _flat_np(s_b)
    for k in flat_a:
        np.testing.assert_array_equal(flat_a[k], flat_b[k])


def test_keep_max_pruning(tmp_path, trainer_and_state):
    _, state, _ = trainer_and_state
    saver = CheckpointSaver(
        str(tmp_path), checkpoint_steps=1, keep_max_version=2, num_shards=1
    )
    for v in (1, 2, 3, 4):
        saver.save(state, version=v)
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("version-"))
    assert kept == ["version-3", "version-4"]


def test_invalid_dir_skipped(tmp_path, trainer_and_state):
    _, state, _ = trainer_and_state
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=1, num_shards=2)
    saver.save(state, version=1)
    saver.save(state, version=2)
    # corrupt version-2: delete one of its two shard files
    os.remove(tmp_path / "version-2" / "variables-1-of-2.ckpt")
    assert get_latest_checkpoint_version(str(tmp_path)) == 1


def test_maybe_save_cadence(tmp_path, trainer_and_state):
    _, state, _ = trainer_and_state
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=3, num_shards=1)
    assert not saver.maybe_save(state, version=1)
    assert not saver.maybe_save(state, version=2)
    assert saver.maybe_save(state, version=3)
    assert not saver.maybe_save(state, version=3)  # no double-save
    assert saver.maybe_save(state, version=6)
    assert get_latest_checkpoint_version(str(tmp_path)) == 6


def test_no_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(tmp_path))
    assert get_latest_checkpoint_version(str(tmp_path)) == -1


@pytest.mark.slow
def test_local_executor_checkpoint_and_resume(tmp_path):
    """Train with checkpointing, then resume from the checkpoint and verify
    the step counter and params carry over (reference: PS writes checkpoints
    every checkpoint_steps; --checkpoint_dir_for_init resumes)."""
    from elasticdl_tpu.api.local_executor import LocalExecutor
    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.data import recordio_gen

    train_dir = str(tmp_path / "train")
    ckpt_dir = str(tmp_path / "ckpt")
    recordio_gen.gen_mnist_like(train_dir, num_files=1, records_per_file=64)
    spec = get_model_spec(
        "model_zoo", "mnist_functional_api.mnist_functional_api.custom_model"
    )
    ex1 = LocalExecutor(
        spec,
        training_data=train_dir,
        minibatch_size=16,
        num_epochs=1,
        records_per_task=32,
        checkpoint_dir=ckpt_dir,
        checkpoint_steps=2,
        keep_checkpoint_max=1,
    )
    state1, _ = ex1.run()
    assert int(state1.step) == 4
    assert get_latest_checkpoint_version(ckpt_dir) == 4
    # keep_max=1: only the newest survives
    kept = sorted(
        d for d in os.listdir(ckpt_dir) if d.startswith("version-")
    )
    assert kept == ["version-4"]

    ex2 = LocalExecutor(
        spec,
        training_data=train_dir,
        minibatch_size=16,
        num_epochs=1,
        records_per_task=32,
        checkpoint_dir_for_init=ckpt_dir,
    )
    state2, _ = ex2.run()
    # resumed from step 4, trained one more epoch of 4 steps
    assert int(state2.step) == 8


def test_async_save_roundtrip(tmp_path, trainer_and_state):
    """async_save: save() returns after materializing; wait() makes the
    artifact durable and byte-equivalent to a sync save; a snapshot taken
    before further training is immune to donated-buffer reuse."""
    trainer, state, batch = trainer_and_state
    saver = CheckpointSaver(
        str(tmp_path / "async"), checkpoint_steps=1, num_shards=2,
        async_save=True,
    )
    want = _flat_np(state)
    saver.save(state, version=1)
    # train ON while the write is (possibly) still in flight: the step
    # donates the old buffers — the snapshot must not be affected
    state2, _ = trainer.train_step(state, batch)
    saver.wait()
    assert get_latest_checkpoint_version(str(tmp_path / "async")) == 1

    restored, version = restore_state_from_checkpoint(
        state2, str(tmp_path / "async")
    )
    assert version == 1
    got = _flat_np(restored)
    for key, arr in want.items():
        np.testing.assert_array_equal(got[key], arr)


def test_async_save_serializes_inflight_writes(tmp_path, trainer_and_state):
    trainer, state, batch = trainer_and_state
    saver = CheckpointSaver(
        str(tmp_path / "seq"), checkpoint_steps=1, keep_max_version=1,
        async_save=True,
    )
    saver.save(state, version=1)
    state, _ = trainer.train_step(state, batch)
    saver.save(state, version=2)  # joins v1's write first
    saver.wait()
    import os as _os

    kept = sorted(
        d for d in _os.listdir(str(tmp_path / "seq"))
        if d.startswith("version-")
    )
    assert kept == ["version-2"]  # pruning still applies in order


def test_async_save_failure_surfaces_and_retries(tmp_path,
                                                 trainer_and_state):
    """A failed background write re-raises in wait() and resets the
    saved-version marker so the next cadence retries."""
    _, state, _ = trainer_and_state
    saver = CheckpointSaver(
        str(tmp_path / "fail"), checkpoint_steps=1, async_save=True
    )

    real_write = saver._write_and_log
    calls = {"n": 0}

    def flaky(flat, extra, version):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        return real_write(flat, extra, version)

    saver._write_and_log = flaky
    saver.save(state, version=1)
    with pytest.raises(OSError, match="disk full"):
        saver.wait()
    # the failed version is NOT marked saved: maybe_save retries it
    assert saver.maybe_save(state, version=1)
    saver.wait()
    assert get_latest_checkpoint_version(str(tmp_path / "fail")) == 1


@pytest.mark.slow
def test_orbax_roundtrip_and_reshard(tmp_path, trainer_and_state):
    """Orbax interop: save on a (dp, fsdp=2) mesh, restore onto a
    single-device template; values identical, shardings follow the
    template (the ecosystem-exchange path, checkpoint/orbax_io.py)."""
    pytest.importorskip("orbax.checkpoint")
    from elasticdl_tpu.checkpoint import orbax_io
    from elasticdl_tpu.parallel import mesh as mesh_lib

    trainer, state, batch = trainer_and_state
    want = _flat_np(state)
    path = str(tmp_path / "orbax_ck")
    orbax_io.save_with_orbax(state, path)

    import jax as _jax

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    single = Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=_jax.devices()[:1]),
    )
    template = single.init_state(batch)
    restored = orbax_io.restore_with_orbax(template, path)
    got = _flat_np(restored)
    for key, arr in want.items():
        np.testing.assert_array_equal(got[key], arr)
    # and the restored state actually trains on the new mesh
    restored, loss = single.train_step(restored, batch)
    assert np.isfinite(float(loss))


def test_native_to_orbax_conversion(tmp_path, trainer_and_state):
    pytest.importorskip("orbax.checkpoint")
    from elasticdl_tpu.checkpoint import orbax_io

    _, state, _ = trainer_and_state
    native = str(tmp_path / "native")
    CheckpointSaver(native, checkpoint_steps=1).save(state, version=3)
    opath, version = orbax_io.export_native_to_orbax(
        native, str(tmp_path / "as_orbax")
    )
    assert version == 3
    restored = orbax_io.restore_with_orbax(state, opath)
    got, want = _flat_np(restored), _flat_np(state)
    for key, arr in want.items():
        np.testing.assert_array_equal(got[key], arr)


def test_import_orbax_to_native(tmp_path, trainer_and_state):
    """orbax -> native direction, through an ASYNC saver (the wait()
    branch): the written native checkpoint round-trips the values."""
    pytest.importorskip("orbax.checkpoint")
    from elasticdl_tpu.checkpoint import orbax_io

    _, state, _ = trainer_and_state
    want = _flat_np(state)
    opath = str(tmp_path / "orbax_src")
    orbax_io.save_with_orbax(state, opath)

    native_dir = str(tmp_path / "native_dst")
    saver = CheckpointSaver(native_dir, checkpoint_steps=1,
                            async_save=True)
    restored = orbax_io.import_orbax_to_native(
        state, opath, saver, version=9
    )
    assert get_latest_checkpoint_version(native_dir) == 9
    again, version = restore_state_from_checkpoint(restored, native_dir)
    assert version == 9
    got = _flat_np(again)
    for key, arr in want.items():
        np.testing.assert_array_equal(got[key], arr)
