"""Tail-latency forensics battery: exemplar-linked histograms
(record/merge/wire vs a brute-force oracle, OpenMetrics render +
independent parse, malformed-exemplar rejection), tail-based trace
retention (a breaching root survives ring pressure that evicts healthy
siblings), cause attribution pinned on hand-built span trees, the
replica's slow_cause counter family, and the fleet collector's
scrape -> merge -> re-evaluate -> join -> attribute -> report pipeline
over a real 2-endpoint in-process rig (real exposition HTTP servers,
real independent parser, real span exports on disk)."""

import json
import os
import threading
import time

import pytest

from elasticdl_tpu.observability import collector, forensics
from elasticdl_tpu.observability.dump import drops_by_service, merge_dir
from elasticdl_tpu.observability.histogram import (
    EXEMPLAR_SLOTS,
    LogLinearHistogram,
    bucket_index,
)
from elasticdl_tpu.observability.metrics import (
    MetricsServer,
    TimeSeriesRing,
    hist_family,
    merge_window_deltas,
    render_prometheus,
)
from elasticdl_tpu.observability.promparse import parse_prometheus_text
from elasticdl_tpu.observability.slo import default_router_slos
from elasticdl_tpu.observability.tracing import SpanRecorder
from elasticdl_tpu.serving.admission import RequestQueue, ServingRequest
from elasticdl_tpu.serving.server import (
    ServingServicer,
    _Scheduler,
    serve_span_classifier,
)
from elasticdl_tpu.serving.telemetry import ServingTelemetry


# ------------------------------------------------------------ exemplars


def _exemplar_oracle(samples):
    """Brute force: best (max-value) exemplar per bucket, then keep
    only the EXEMPLAR_SLOTS highest buckets."""
    best = {}
    for tid, value, ts in samples:
        idx = bucket_index(value)
        cur = best.get(idx)
        if cur is None or value >= cur[1]:
            best[idx] = (tid, value, ts)
    keep = sorted(best)[-EXEMPLAR_SLOTS:]
    return {i: best[i] for i in keep}


def test_exemplar_record_and_merge_match_bruteforce_oracle():
    import random

    rng = random.Random(7)
    samples = [
        ("t%04d" % i, rng.uniform(0.05, 5000.0), 1000.0 + i)
        for i in range(400)
    ]
    # one histogram recording everything...
    whole = LogLinearHistogram()
    for tid, value, ts in samples:
        whole.record(value, trace_id=tid, ts=ts)
    assert whole.exemplars == _exemplar_oracle(samples)
    # ...must agree with a merge of disjoint shards (associativity —
    # the property fleet bucket-addition relies on). The shard split
    # can transiently evict a bucket one shard would have kept, so
    # compare against the oracle of what the SHARDS retained.
    shards = [LogLinearHistogram() for _ in range(4)]
    for n, (tid, value, ts) in enumerate(samples):
        shards[n % 4].record(value, trace_id=tid, ts=ts)
    merged = LogLinearHistogram()
    for s in shards:
        merged.merge(s)
    surviving = [
        ex for s in shards for ex in
        ((tid, value, ts)
         for tid, value, ts in s.exemplars.values())
    ]
    assert merged.exemplars == _exemplar_oracle(surviving)
    # bounded, highest buckets win, max-value-per-bucket wins
    assert len(whole.exemplars) <= EXEMPLAR_SLOTS
    assert min(whole.exemplars) >= sorted(
        {bucket_index(v) for _t, v, _s in samples}
    )[-EXEMPLAR_SLOTS]


def test_exemplar_wire_round_trip():
    h = LogLinearHistogram()
    h.record(3.0, trace_id="aa", ts=10.0)
    h.record(700.0, trace_id="bb", ts=11.0)
    h.record(0.5)  # no trace: counts, no exemplar
    wire_counts = h.to_counts()
    wire_ex = h.exemplars_wire()
    # JSON round trip stringifies the keys; from_counts re-accepts
    wire_ex = json.loads(json.dumps(wire_ex))
    back = LogLinearHistogram.from_counts(wire_counts, wire_ex)
    assert back.count == 3
    assert back.exemplars == h.exemplars


def test_exemplar_renders_and_reparses_through_independent_parser():
    h = LogLinearHistogram()
    h.record(12.3, trace_id="abc", ts=1722800000.0)
    h.record(456.0, trace_id="tail", ts=1722800001.0)
    text = render_prometheus([hist_family(
        "edl_serving_ttft_ms", "ttft",
        [({}, h.to_counts(), h.sum, h.exemplars)],
    )])
    assert "# {" in text.split("\n")[2]
    fams = parse_prometheus_text(text)
    exes = fams["edl_serving_ttft_ms"]["exemplars"]
    got = {ex_labels["trace_id"]: (value, ts)
           for _n, _l, ex_labels, value, ts in exes}
    assert got == {"abc": (12.3, 1722800000.0),
                   "tail": (456.0, 1722800001.0)}
    # exemplar value must sit inside its bucket's bound
    for _n, labels, _el, value, _ts in exes:
        assert value <= float(labels["le"])


@pytest.mark.parametrize("bad, why", [
    # exemplar on a counter sample
    ('# TYPE edl_x_total counter\nedl_x_total 1 '
     '# {trace_id="t"} 1 1\n', "counter"),
    # exemplar on a gauge sample
    ('# TYPE edl_g gauge\nedl_g 1 # {trace_id="t"} 1 1\n', "gauge"),
    # empty label set
    ('# TYPE h histogram\nh_bucket{le="+Inf"} 1 # {} 0.5 1\n'
     'h_sum 1\nh_count 1\n', "no labels"),
    # value above the bucket bound
    ('# TYPE h histogram\nh_bucket{le="1"} 1 # {trace_id="t"} 5 1\n'
     'h_bucket{le="+Inf"} 1\nh_sum 1\nh_count 1\n', "above le"),
    # non-finite exemplar value
    ('# TYPE h histogram\nh_bucket{le="+Inf"} 1 '
     '# {trace_id="t"} +Inf 1\nh_sum 1\nh_count 1\n', "not finite"),
    # junk after the exemplar timestamp
    ('# TYPE h histogram\nh_bucket{le="+Inf"} 1 '
     '# {trace_id="t"} 0.5 1 junk\nh_sum 1\nh_count 1\n', "junk"),
    # missing value
    ('# TYPE h histogram\nh_bucket{le="+Inf"} 1 '
     '# {trace_id="t"}\nh_sum 1\nh_count 1\n', "no value"),
    # bad label grammar inside the exemplar
    ('# TYPE h histogram\nh_bucket{le="+Inf"} 1 '
     '# {trace id="t"} 0.5\nh_sum 1\nh_count 1\n', "bad label"),
])
def test_promparse_rejects_malformed_exemplars(bad, why):
    with pytest.raises(ValueError):
        parse_prometheus_text(bad)


def test_promparse_hash_inside_label_value_is_not_an_exemplar():
    text = ('# TYPE g gauge\ng{tag="a # b"} 1\n')
    fams = parse_prometheus_text(text)
    assert fams["g"]["samples"] == [("g", {"tag": "a # b"}, 1.0)]
    assert fams["g"]["exemplars"] == []


def test_ring_windows_carry_new_exemplars_and_merge_keeps_max():
    clock = [0.0]
    ring = TimeSeriesRing(interval_secs=1.0, clock=lambda: clock[0])
    ring.observe(hists={"ttft_ms": [1]},
                 exemplars={"ttft_ms": {3: ("a", 0.03, 1.0)}})
    clock[0] = 1.1
    ring.observe(hists={"ttft_ms": [1, 1]},
                 exemplars={"ttft_ms": {3: ("a", 0.03, 1.0),
                                        9: ("b", 0.09, 2.0)}})
    w1 = ring.windows()[0]
    # the first window carries the exemplars recorded up to its close
    # (the boundary observation folds in, same as the counter deltas)
    assert w1["exemplars"]["ttft_ms"] == {3: ("a", 0.03, 1.0),
                                          9: ("b", 0.09, 2.0)}
    clock[0] = 2.2
    ring.observe(hists={"ttft_ms": [1, 1, 1]},
                 exemplars={"ttft_ms": {9: ("c", 0.095, 3.0)}})
    w2 = ring.windows()[1]
    # only the CHANGED exemplar (bucket 9's new max) is in window 2
    assert w2["exemplars"]["ttft_ms"] == {9: ("c", 0.095, 3.0)}
    merged = merge_window_deltas(w1, w2)
    assert merged["exemplars"]["ttft_ms"] == {
        3: ("a", 0.03, 1.0), 9: ("c", 0.095, 3.0),
    }
    # horizon query merges max-value per bucket
    got = ring.merged_exemplars("ttft_ms", now=clock[0])
    assert got[9] == ("c", 0.095, 3.0) or got[9] == ("b", 0.09, 2.0)


# ------------------------------------------------- tail-based retention


def test_tail_retention_keeps_breaching_root_under_ring_pressure():
    rec = SpanRecorder(service="t", capacity=8, retained_capacity=16)

    def classify(span):
        if span.name != "root":
            return None
        return span.status != "ok"

    rec.add_classifier(classify)
    # one breaching trace with a child, finished EARLY
    child = rec.start_span("serve", trace_id="bad1",
                           parent_span_id="x")
    child.finish("ok")
    bad = rec.start_span("root", trace_id="bad1")
    bad.finish("DEADLINE_EXCEEDED")
    # flood with healthy siblings far past the ring bound
    for i in range(50):
        s = rec.start_span("root", trace_id="h%d" % i)
        s.finish("ok")
    assert rec.dropped > 0  # the ring DID evict
    kept = {s.trace_id for s in rec.snapshot()}
    assert "bad1" in kept  # ...but the breaching trace survived
    # the WHOLE trace moved: both its spans are present
    assert sum(1 for s in rec.snapshot()
               if s.trace_id == "bad1") == 2
    doc = rec.export()
    assert doc["retained"] == 2
    assert doc["dropped"] == rec.dropped


def test_tail_retention_straggler_spans_follow_their_trace():
    rec = SpanRecorder(service="t", capacity=4, retained_capacity=8)
    rec.add_classifier(
        lambda s: (s.status != "ok") if s.name == "root" else None
    )
    root = rec.start_span("root", trace_id="late")
    root.finish("error")
    # a child finishing AFTER the root was retained pins to the tier
    child = rec.start_span("serve", trace_id="late",
                           parent_span_id=root.span_id)
    child.finish("ok")
    for i in range(10):
        rec.start_span("root", trace_id="h%d" % i).finish("ok")
    assert sum(1 for s in rec.snapshot()
               if s.trace_id == "late") == 2


def test_probabilistic_sampling_drops_healthy_roots():
    rec = SpanRecorder(service="t", capacity=64, sample_rate=0.0,
                       seed=1)
    rec.add_classifier(
        lambda s: (s.status != "ok") if s.name == "root" else None
    )
    for i in range(10):
        rec.start_span("root", trace_id="h%d" % i).finish("ok")
    bad = rec.start_span("root", trace_id="bad")
    bad.finish("error")
    kept = {s.trace_id for s in rec.snapshot()}
    assert kept == {"bad"}  # every healthy root sampled out
    assert rec.sampled_out == 10


def test_classifier_exception_never_loses_the_span():
    rec = SpanRecorder(service="t", capacity=8)

    def broken(_span):
        raise RuntimeError("hook bug")

    rec.add_classifier(broken)
    rec.start_span("root", trace_id="x").finish("ok")
    assert len(rec) == 1  # abstained, landed in the plain ring


# ------------------------------------------------------ attribute()


def _span(name, trace_id, start, end, status="ok", parent="",
          span_id=None, events=(), attrs=None):
    return {
        "name": name, "trace_id": trace_id,
        "span_id": span_id or ("%s-%s" % (name, start)),
        "parent_span_id": parent, "service": "t",
        "start": start, "end": end, "status": status,
        "attrs": attrs or {},
        "events": [
            {"ts": ts, "name": n, "attrs": a} for ts, n, a in events
        ],
    }


def _serve(trace_id="T", start=10.0, end=10.5, queued=10.0,
           seated=10.1, first=10.2, parent="", blocked=0.0,
           revive_ms=0.0, status="ok"):
    events = [
        (queued, "queued", {}),
        (seated, "seated", {
            "queue_wait_ms": (seated - queued) * 1000.0,
            "prefill_blocked_ms": blocked,
        }),
    ]
    if revive_ms:
        events.append((seated, "revive_upload", {"ms": revive_ms}))
    events.append((first, "first_token", {}))
    events.append((end, "completed", {}))
    return _span("serve", trace_id, start, end, status=status,
                 parent=parent, events=events)


def test_attribute_queue_wait_dominant():
    v = forensics.attribute([_serve(
        start=10.0, end=10.65, queued=10.0, seated=10.5,
        first=10.55,
    )])
    assert v["dominant_cause"] == "queue_wait"
    by = {p["cause"]: p["ms"] for p in v["breakdown"]}
    assert by["queue_wait"] == pytest.approx(500.0, abs=1.0)
    assert v["evidence_complete"]


def test_attribute_prefill_blocked_by_other_dominant():
    # 400ms queued, 380 of them while another slot's prefill ran
    v = forensics.attribute([_serve(
        start=10.0, end=10.5, queued=10.0, seated=10.4,
        first=10.45, blocked=380.0,
    )])
    assert v["dominant_cause"] == "prefill_blocked_by_other"
    by = {p["cause"]: p["ms"] for p in v["breakdown"]}
    assert by["prefill_blocked_by_other"] == pytest.approx(380.0)
    assert by["queue_wait"] == pytest.approx(20.0, abs=1.0)


def test_attribute_prefill_own_dominant():
    v = forensics.attribute([_serve(
        start=10.0, end=10.75, queued=10.0, seated=10.01,
        first=10.7,
    )])
    assert v["dominant_cause"] == "prefill_own"


def test_attribute_revive_upload_split_from_prefill():
    v = forensics.attribute([_serve(
        start=10.0, end=10.8, queued=10.0, seated=10.01,
        first=10.7, revive_ms=600.0,
    )])
    assert v["dominant_cause"] == "revive_upload"
    by = {p["cause"]: p["ms"] for p in v["breakdown"]}
    assert by["revive_upload"] == pytest.approx(600.0)
    assert by["prefill_own"] == pytest.approx(90.0, abs=2.0)


def test_attribute_decode_dominant():
    v = forensics.attribute([_serve(
        start=10.0, end=11.0, queued=10.0, seated=10.01,
        first=10.05,
    )])
    assert v["dominant_cause"] == "decode"


def test_attribute_dispatch_retries_and_stream_stall():
    # router tree: root with a failed leg, then the winning leg whose
    # serve span is much shorter than the dispatch (transport stall)
    root = _span("router_generate", "T", 10.0, 11.5, span_id="root",
                 events=[(10.4, "redispatched", {})])
    failed = _span("dispatch", "T", 10.0, 10.4, status="error",
                   parent="root", span_id="d0")
    win = _span("dispatch", "T", 10.6, 11.5, parent="root",
                span_id="d1")
    serve = _serve(start=10.6, end=10.9, queued=10.6, seated=10.61,
                   first=10.65, parent="d1")
    v = forensics.attribute([root, failed, win, serve])
    by = {p["cause"]: p["ms"] for p in v["breakdown"]}
    assert by["dispatch_retries"] == pytest.approx(600.0, abs=1.0)
    assert by["stream_stall"] == pytest.approx(600.0, abs=1.0)
    assert v["dominant_cause"] in ("dispatch_retries", "stream_stall")
    assert v["total_ms"] == pytest.approx(1500.0)


def test_attribute_expired_in_queue():
    # queued, never seated, expired: the whole wait is queue_wait
    # (minus the blocked share stamped on the expired event)
    span = _span("serve", "T", 10.0, 10.4,
                 status="DEADLINE_EXCEEDED", events=[
                     (10.0, "queued", {}),
                     (10.4, "expired", {"where": "queued",
                                        "prefill_blocked_ms": 150.0}),
                 ])
    v = forensics.attribute([span])
    by = {p["cause"]: p["ms"] for p in v["breakdown"]}
    assert by["queue_wait"] == pytest.approx(250.0, abs=1.0)
    assert by["prefill_blocked_by_other"] == pytest.approx(150.0)
    assert v["dominant_cause"] == "queue_wait"


def test_attribute_degrades_without_serve_span():
    root = _span("router_generate", "T", 10.0, 10.3, span_id="root",
                 status="UNAVAILABLE")
    v = forensics.attribute([root])
    assert not v["evidence_complete"]
    assert v["total_ms"] == pytest.approx(300.0)
    v_empty = forensics.attribute([])
    assert v_empty["dominant_cause"] is None


def test_is_terminally_slow():
    assert forensics.is_terminally_slow("DEADLINE_EXCEEDED", 10.0, 0)
    assert forensics.is_terminally_slow("ok", 90.0, 100.0)
    assert not forensics.is_terminally_slow("ok", 10.0, 100.0)
    assert not forensics.is_terminally_slow("ok", 90.0, 0)
    # errors are fast-and-wrong, not slow
    assert not forensics.is_terminally_slow("RESOURCE_EXHAUSTED",
                                            90.0, 100.0)


# -------------------------------------- replica slow_cause integration


class _SlowSeatEngine(object):
    """Stub engine whose insert() seats instantly; the slowness under
    test comes from the queue (a single slot + a held first request)."""

    num_slots = 1
    model_version = 0
    seq_len = 64
    draft_k = 0
    draft_proposed = 0
    draft_accepted = 0

    def __init__(self):
        self._slots = {}
        self.prefill_busy_ms = 0.0

    def free_slots(self):
        return [] if self._slots else [0]

    def can_seat(self, _req):
        return True

    def insert(self, request):
        self._slots[0] = request
        return 0, 11, False

    def evict_expired(self, now):
        out = [r for r in self._slots.values() if r.expired(now)]
        self._slots = {s: r for s, r in self._slots.items()
                       if not r.expired(now)}
        return out

    def active_count(self):
        return len(self._slots)

    def active_requests(self):
        return list(self._slots.values())

    def step(self):
        out = []
        for slot, req in list(self._slots.items()):
            req.generated.append(12)
            finished = len(req.generated) >= req.max_new_tokens
            if finished:
                del self._slots[slot]
            out.append((slot, req, [12], finished))
        return out

    def max_cached_tokens(self):
        return self.seq_len

    def kv_stats(self):
        return {"kv_paged": False, "kv_shared": False,
                "kv_cache_dtype": "", "kv_block_size": 0,
                "kv_blocks_total": 0, "kv_blocks_free": 0,
                "kv_blocks_cached": 0, "kv_blocks_shared": 0,
                "kv_bytes_total": 0, "kv_bytes_in_use": 0,
                "prefix_hit_tokens": 0, "cow_copies": 0}


def test_scheduler_counts_slow_cause_for_expired_queued_request():
    from elasticdl_tpu.observability.tracing import recorder

    recorder().clear()
    engine = _SlowSeatEngine()
    queue = RequestQueue(capacity=8, seq_len=64)
    telemetry = ServingTelemetry(log_dir=None)
    sched = _Scheduler(engine, queue, telemetry,
                       idle_wait_secs=0.001, forensics_on=True)
    servicer = ServingServicer(
        queue, engine, telemetry, scheduler_alive=lambda: True,
        handler_poll_secs=0.02, draining=lambda: False,
    )
    import elasticdl_tpu.proto.elasticdl_pb2 as pb

    # request 1 occupies the single slot for a while
    done = {}

    def call(key, deadline_ms):
        try:
            done[key] = servicer.generate(pb.GenerateRequest(
                prompt=[1, 2], max_new_tokens=50,
                deadline_ms=deadline_ms,
            ))
        except Exception as e:  # noqa: BLE001 - the datum
            done[key] = e
    t1 = threading.Thread(target=call, args=("a", 0))
    t1.start()
    deadline = time.monotonic() + 5.0
    while not engine.active_count() and time.monotonic() < deadline:
        sched._iterate()
    # request 2 has a deadline too short to outlive the queue
    t2 = threading.Thread(target=call, args=("b", 60))
    t2.start()
    while "b" not in done and time.monotonic() < deadline:
        time.sleep(0.08)  # let the deadline lapse while queued
        sched._iterate()
    while "a" not in done and time.monotonic() < deadline:
        sched._iterate()
    t1.join(timeout=5)
    t2.join(timeout=5)
    snap = telemetry.snapshot()
    assert snap["expired"] >= 1
    causes = dict(zip(ServingTelemetry.SLOW_CAUSES,
                      snap["slow_cause_counts"]))
    assert causes["queue_wait"] >= 1, causes
    assert snap["slow_requests"] >= 1
    # the slow_cause family renders and re-parses as labeled counters
    fams = parse_prometheus_text(
        render_prometheus(telemetry.prometheus())
    )
    samples = {
        labels["cause"]: value
        for _n, labels, value in (
            fams["edl_serving_slow_cause_total"]["samples"]
        )
    }
    assert set(samples) == set(ServingTelemetry.SLOW_CAUSES)
    assert samples["queue_wait"] >= 1


def test_serve_span_classifier_retains_breach_and_slow_completion():
    class S(object):
        pass

    ok = S()
    ok.name, ok.status = "serve", "ok"
    ok.attrs = {"deadline_ms": 1000}
    ok.start, ok.end = 10.0, 10.1
    assert serve_span_classifier(ok) is False
    slow = S()
    slow.name, slow.status = "serve", "ok"
    slow.attrs = {"deadline_ms": 1000}
    slow.start, slow.end = 10.0, 10.9
    assert serve_span_classifier(slow) is True
    breach = S()
    breach.name, breach.status = "serve", "DEADLINE_EXCEEDED"
    breach.attrs = {}
    breach.start, breach.end = 10.0, 10.1
    assert serve_span_classifier(breach) is True
    other = S()
    other.name = "dispatch"
    assert serve_span_classifier(other) is None


# ------------------------------------------------------- dump drops


def test_dump_surfaces_drops_by_service(tmp_path):
    rec = SpanRecorder(service="tiny", capacity=2)
    for i in range(5):
        rec.start_span("root", trace_id="t%d" % i).finish("ok")
    rec.flush(str(tmp_path))
    rec2 = SpanRecorder(service="fine", capacity=64)
    rec2.start_span("root", trace_id="x").finish("ok")
    rec2.flush(str(tmp_path))
    spans, meta = merge_dir(str(tmp_path))
    drops = drops_by_service(meta)
    assert drops == {"tiny": 3}
    # the CLI embeds the accounting in the artifact
    from elasticdl_tpu.observability.dump import main as dump_main

    out = str(tmp_path / "trace.json")
    assert dump_main(["--dir", str(tmp_path), "--out", out]) == 0
    doc = json.load(open(out))
    assert doc["otherData"]["drops_by_service"] == {"tiny": 3}
    assert doc["otherData"]["evidence_complete"] is False


# ------------------------------------------------------- the collector


class _Req(object):
    def __init__(self, tid, ago, clock=time.monotonic):
        self.trace_id = tid
        self.submitted_at = clock() - ago


def _fleet_rig(tmp_path, n=2):
    """Two 'replicas': real ServingTelemetry + real MetricsServer +
    real span exports on disk — everything the collector consumes,
    minus the jax engine it never talks to anyway."""
    servers, tels = [], []
    for k in range(n):
        tel = ServingTelemetry(log_dir=None, ring_secs=0.05)
        rec = SpanRecorder(service="replica%d" % k)
        for i in range(15):
            tid = "r%d_%04d" % (k, i)
            sp = rec.start_span("serve", trace_id=tid,
                                deadline_ms=200)
            sp.event("queued")
            sp.event("seated", queue_wait_ms=2.0,
                     prefill_blocked_ms=1.0)
            sp.event("first_token")
            sp.event("completed")
            sp.finish("ok")
            tel.record_ttft(_Req(tid, 0.010 + 0.015 * i))
            tel.count("admitted")
            tel.count("completed")
            tel.record_e2e(30.0 + 15 * i, trace_id=tid)
        rec.flush(str(tmp_path))
        srv = MetricsServer(tel.prometheus, port=0, host="127.0.0.1")
        servers.append(srv)
        tels.append(tel)
    return servers, tels


def test_collector_scrape_merge_report_two_replica_rig(tmp_path):
    servers, tels = _fleet_rig(tmp_path)
    try:
        endpoints = ["127.0.0.1:%d" % s.port for s in servers]

        def sleep_and_feed(secs):
            time.sleep(secs)
            for tel in tels:
                tel.count("admitted")
                tel.record_ttft(_Req("hot", 0.450))
                tel.record_e2e(600.0, trace_id="hot")

        bundle = collector.scrape_fleet(
            endpoints, scrapes=3, interval_secs=0.15,
            sleep=sleep_and_feed,
        )
        assert len(bundle["rounds"]) == 3
        # fleet merge: round counters are the SUM across endpoints
        assert bundle["rounds"][0]["counters"]["admitted"] == 30
        specs = default_router_slos(50.0, 100.0, 0.02,
                                    latency_goal=0.01)
        report = collector.build_report(bundle, specs,
                                        trace_dir=str(tmp_path))
        collector.validate_report(report)
        # the tight thresholds + between-scrape hot traffic alert
        assert "ttft_p99" in report["alerting"]
        # exemplars resolved against the on-disk span exports and
        # attributed through the cause taxonomy
        resolved = [e for e in report["exemplars"] if e["resolved"]]
        assert resolved
        assert report["cause_histogram"]
        for cause in report["cause_histogram"]:
            assert cause in forensics.CAUSES
        assert report["span_evidence"]["complete"]
        # the renderer produces a summary naming the dominant cause
        text = collector.render_text(report)
        assert "ALERTING" in text
        assert report["dominant_cause"] in text
        # schema gate rejects tampering
        broken = dict(report, schema="bogus/9")
        with pytest.raises(ValueError):
            collector.validate_report(broken)
        broken = json.loads(json.dumps(report))
        broken["cause_histogram"] = {"made_up_cause": 3}
        with pytest.raises(ValueError):
            collector.validate_report(broken)
    finally:
        for s in servers:
            s.close()


def test_collector_main_cli(tmp_path):
    servers, _tels = _fleet_rig(tmp_path, n=1)
    try:
        out = str(tmp_path / "incident.json")
        txt = str(tmp_path / "incident.txt")
        rc = collector.main([
            "--endpoints", "127.0.0.1:%d" % servers[0].port,
            "--scrapes", "2", "--interval", "0.1",
            "--trace_dir", str(tmp_path),
            "--out", out, "--text", txt,
            "--slo_ttft_p99_ms", "50",
        ])
        assert rc == 0
        report = json.load(open(out))
        collector.validate_report(report)
        assert os.path.exists(txt)
    finally:
        for s in servers:
            s.close()


def test_collector_requires_two_scrapes():
    with pytest.raises(ValueError):
        collector.scrape_fleet(["x"], scrapes=1)
