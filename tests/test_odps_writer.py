"""ODPS write path + k-v table tools (VERDICT.md round-1 missing #3):
writer round-trips a table through the reader; flattening tools match the
reference UDTF protocol."""

import threading

import pytest

from elasticdl_tpu.data.odps_writer import ODPSWriter
from elasticdl_tpu.data.reader.odps_reader import ODPSDataReader
from elasticdl_tpu.tools import odps_table_tools as kv


# ----------------------------------------------------------- fake ODPS


class _FakeColumn(object):
    def __init__(self, name, type_):
        self.name = name
        self.type = type_


class _FakeSchema(object):
    def __init__(self, names):
        self.columns = [_FakeColumn(n, "string") for n in names]


class _FakeWriterCtx(object):
    def __init__(self, store, fail_times, lock):
        self._store = store
        self._fail = fail_times
        self._lock = lock

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def write(self, records):
        with self._lock:
            if self._fail and self._fail[0] > 0:
                self._fail[0] -= 1
                raise IOError("transient write failure")
            self._store.extend(records)


class _FakeReaderCtx(object):
    def __init__(self, rows):
        self._rows = rows
        self.count = len(rows)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def read(self, start, count):
        return self._rows[start:start + count]


class _FakeTable(object):
    name = "sink"

    def __init__(self, fail_times=None):
        self.schema = _FakeSchema(["a", "b"])
        self.partitions = {}  # partition spec -> record list
        self._fail = fail_times
        self._lock = threading.Lock()

    def open_writer(self, partition=None, create_partition=False):
        assert create_partition
        store = self.partitions.setdefault(partition, [])
        return _FakeWriterCtx(store, self._fail, self._lock)

    def open_reader(self):
        rows = []
        for part in sorted(self.partitions):
            rows.extend(self.partitions[part])
        return _FakeReaderCtx(rows)


# -------------------------------------------------------------- writer


def test_from_iterator_writes_worker_partition():
    table = _FakeTable()
    writer = ODPSWriter(table=table)
    writer.from_iterator(
        iter([[(1, "x")], [(2, "y"), (3, "z")]]), worker_index=7
    )
    assert table.partitions == {"worker=7": [(1, "x"), (2, "y"), (3, "z")]}


def test_write_records_windows_and_parallel():
    table = _FakeTable()
    writer = ODPSWriter(table=table, window_size=10, num_parallel=3)
    records = [(i, str(i)) for i in range(95)]
    n = writer.write_records(records, worker_index=0)
    assert n == 95
    written = table.partitions["worker=0"]
    # parallel threads interleave windows; content must be complete
    assert sorted(written) == sorted(records)


def test_write_retry_recovers_transient_failures():
    table = _FakeTable(fail_times=[2])
    writer = ODPSWriter(table=table, window_size=5, num_parallel=2)
    records = [(i, "v") for i in range(20)]
    writer.write_records(records)
    assert sorted(table.partitions["worker=0"]) == sorted(records)


def test_write_permanent_failure_raises():
    table = _FakeTable(fail_times=[10_000])
    writer = ODPSWriter(table=table, window_size=5, num_parallel=1,
                        max_retries=2)
    with pytest.raises(IOError):
        writer.write_records([(1, "v")] * 8)


def test_round_trip_through_reader():
    """Writer -> reader round-trip (the env-gated integration the
    reference exercised on a real cluster, run here on the fake)."""
    table = _FakeTable()
    ODPSWriter(table=table, window_size=4).write_records(
        [(i, i * 2) for i in range(30)]
    )
    reader = ODPSDataReader(table=table, records_per_task=10)
    shards = reader.create_shards()
    assert sum(n for _, n in shards.values()) == 30

    class _Task(object):
        def __init__(self, start, end):
            self.start, self.end = start, end

    rows = list(reader.read_records(_Task(0, 30)))
    # parallel writer sessions interleave windows: row ORDER across
    # sessions is not part of the contract (shards re-slice by range,
    # training shuffles); content completeness is.
    assert sorted(rows) == [(i, i * 2) for i in range(30)]


def test_missing_pyodps_raises():
    writer = ODPSWriter(table_name="proj.t", columns=["a"],
                        column_types=["string"])
    assert writer._project == "proj"
    with pytest.raises(RuntimeError, match="odps package"):
        writer.write_records([("x",)])


# ------------------------------------------------------------ kv tools


def test_parse_and_flatten():
    assert kv.parse_kv_string("k1:v1,k2:v2") == {"k1": "v1", "k2": "v2"}
    # malformed pairs skipped
    assert kv.parse_kv_string("k1:v1,junk,k3:v3:x") == {"k1": "v1"}
    assert kv.flatten_kv_record("b:2,a:1", ["a", "b", "c"]) == ["1", "2", ""]


def test_analyze_feature_names():
    records = [
        {"kv": "f2:1,f1:2"},
        {"kv": "f3:9"},
        {"kv": "f1:0"},
    ]
    names = kv.analyze_feature_names(records, kv_value_fn=lambda r: r["kv"])
    assert names == ["f1", "f2", "f3"]
    # max_records honored
    assert kv.analyze_feature_names(
        records, kv_value_fn=lambda r: r["kv"], max_records=1
    ) == ["f1", "f2"]


def test_kv_flatter_udtf_protocol():
    """args = (kv value, *append columns, names csv, pair sep, kv sep) —
    the reference normalize_kv_udf.KVFlatter contract."""
    f = kv.KVFlatter()
    f.process("age:30,wage:10.5", 1, "age,wage,unknown", ",", ":")
    assert f.collected == [["30", "10.5", "", "1"]]
    with pytest.raises(ValueError):
        f.process("a:1", ",", ":")


def test_generate_transform_sql():
    sql = kv.generate_transform_sql(
        input_table="src",
        output_table="dst",
        feature_names=["f1", "f2"],
        kv_column="features",
        udf_function="my_udf",
        append_columns=["label"],
        input_table_partition="dt=20200101",
    )
    assert sql.startswith("CREATE TABLE IF NOT EXISTS dst")
    assert 'my_udf(features,label,\n    "f1,f2", ",", ":")' in sql
    assert "as (f1,f2,label)" in sql
    assert "FROM src" in sql
    assert sql.endswith("where dt=20200101")


def test_transform_kv_table_end_to_end_fake():
    """Driver wiring against a fake ODPS entry: analyze -> register UDTF
    -> run SQL -> cleanup, including cleanup on SQL failure."""

    class _FakeInstance(object):
        def wait_for_success(self):
            pass

    class _FakeSrcTable(object):
        def head(self, n, partition=None):
            return [{"features": "f1:1,f2:2"}, {"features": "f2:3,f3:4"}]

    class _FakeEntry(object):
        def __init__(self):
            self.resources = set()
            self.functions = set()
            self.sql = []

        def get_table(self, name):
            return _FakeSrcTable()

        def create_resource(self, name, type=None, file_obj=None):
            self.resources.add(name)
            self.resource_content = file_obj.read()
            return name

        def delete_resource(self, name):
            self.resources.discard(name)

        def create_function(self, name, class_type=None, resources=None):
            self.functions.add(name)
            return name

        def delete_function(self, name):
            self.functions.discard(name)

        def run_sql(self, sql):
            self.sql.append(sql)
            return _FakeInstance()

    entry = _FakeEntry()
    names = kv.transform_kv_table(
        entry, "src", "dst", kv_column="features", append_columns=["label"]
    )
    assert names == ["f1", "f2", "f3"]
    assert len(entry.sql) == 1 and "FROM src" in entry.sql[0]
    # the uploaded resource is a real cluster-side UDTF (BaseUDTF with
    # a forwarding process()), not the local test twin
    assert "BaseUDTF" in entry.resource_content
    assert "def process" in entry.resource_content
    # temporaries cleaned up
    assert not entry.resources and not entry.functions


# ------------------------------- round-2 depth: import seam, schema fn


class _NumFakeTable(_FakeTable):
    """Fake table with a numeric schema and preloaded rows."""

    def __init__(self, names, rows):
        super().__init__()
        self.schema = _FakeSchema(names)
        self.partitions = {"worker=0": list(rows)}


def _install_fake_pyodps(monkeypatch, table):
    """Inject a fake `odps` package into sys.modules so the REAL import
    seams (`from odps import ODPS`, `from odps.models import Schema`)
    execute — the paths a live pyodps install would take."""
    import sys
    import types

    created = {}

    class _Client(object):
        def __init__(self, access_id, access_key, project, endpoint):
            self.args = (access_id, access_key, project, endpoint)

        def get_table(self, name, project=None):
            return table

        def exist_table(self, name, project=None):
            return False

        def create_table(self, name, schema):
            created["name"] = name
            created["schema"] = schema
            return table

    class _Schema(object):
        @staticmethod
        def from_lists(cols, types, part_cols, part_types):
            return ("schema", tuple(cols), tuple(types),
                    tuple(part_cols), tuple(part_types))

    odps_mod = types.ModuleType("odps")
    odps_mod.ODPS = _Client
    models_mod = types.ModuleType("odps.models")
    models_mod.Schema = _Schema
    odps_mod.models = models_mod
    monkeypatch.setitem(sys.modules, "odps", odps_mod)
    monkeypatch.setitem(sys.modules, "odps.models", models_mod)
    return created


def test_reader_import_seam_with_fake_pyodps(monkeypatch):
    """ODPSDataReader given credentials (no table object) must go
    through the real `from odps import ODPS` seam."""
    table = _NumFakeTable(["a", "b"], [(1, 2), (3, 4)])
    _install_fake_pyodps(monkeypatch, table)
    reader = ODPSDataReader(
        table="mytable", project="p", access_id="id", access_key="key",
        endpoint="http://e", records_per_task=1,
    )
    assert reader.create_shards() == {
        "sink:0": (0, 1), "sink:1": (1, 1)
    }
    from elasticdl_tpu.master.task_dispatcher import Task, TaskType

    rows = list(reader.read_records(
        Task("sink:0", 0, 2, TaskType.TRAINING)
    ))
    assert rows == [(1, 2), (3, 4)]


def test_writer_import_seam_creates_table(monkeypatch):
    """ODPSWriter without a table object exercises the real pyodps
    import + Schema.from_lists + create_table path (reference
    _initialize_table, odps_io.py:490-506)."""
    table = _FakeTable()
    created = _install_fake_pyodps(monkeypatch, table)
    writer = ODPSWriter(
        table_name="proj.sink", access_id="i", access_key="k",
        endpoint="http://e", columns=["a", "b"],
        column_types=["bigint", "string"],
    )
    writer.write_records([(1, "x"), (2, "y")])
    assert created["name"] == "sink"
    assert created["schema"][1] == ("a", "b")
    assert created["schema"][3] == ("worker",)
    assert sorted(table.partitions["worker=0"]) == [(1, "x"), (2, "y")]


def test_default_dataset_fn_schema_driven():
    """Reader-derived dataset_fn (reference odps_reader.py:140-192):
    label_col becomes the label, remaining columns the float32 feature
    vector; prediction mode drops the label; a missing label column
    fails loudly in training."""
    import numpy as np

    from elasticdl_tpu.common.constants import Mode
    from elasticdl_tpu.data.dataset import Dataset

    table = _NumFakeTable(
        ["f0", "label", "f1"],
        [(0.5, 1, 2.0), (1.5, 0, 3.0)],
    )
    reader = ODPSDataReader(table=table, label_col="label")
    fn = reader.default_dataset_fn()

    ds = fn(
        Dataset.from_list(list(table.partitions["worker=0"])),
        Mode.EVALUATION, reader.metadata,
    )
    got = list(ds)
    assert len(got) == 2
    feats, label = got[0]
    np.testing.assert_allclose(feats["feature"], [0.5, 2.0])
    assert label == 1.0

    ds = fn(
        Dataset.from_list(list(table.partitions["worker=0"])),
        Mode.PREDICTION, reader.metadata,
    )
    pred = list(ds)[0]
    np.testing.assert_allclose(pred["feature"], [0.5, 2.0])

    bad = ODPSDataReader(
        table=_NumFakeTable(["f0", "f1"], [(1.0, 2.0)]),
        label_col="label",
    )
    with pytest.raises(ValueError, match="label"):
        bad.default_dataset_fn()(
            Dataset.from_list([(1.0, 2.0)]), Mode.TRAINING, bad.metadata
        )

    with pytest.raises(ValueError, match="label_col"):
        ODPSDataReader(table=table).default_dataset_fn()


def test_spec_falls_back_to_reader_default_dataset_fn():
    """Specs may omit dataset_fn when the reader derives one
    (reference worker.py:194-205)."""
    from elasticdl_tpu.common.model_utils import (
        ModelSpec,
        resolve_dataset_fn,
    )

    table = _NumFakeTable(["x", "label"], [(1.0, 0)])
    reader = ODPSDataReader(table=table, label_col="label")
    spec = ModelSpec(
        model_fn=lambda: None, dataset_fn=None, loss=lambda y, p: 0,
        optimizer=lambda: None, eval_metrics_fn=lambda: {},
    )
    fn = resolve_dataset_fn(spec, reader)
    assert callable(fn)
    assert resolve_dataset_fn(spec, reader) is fn  # cached on the spec

    class _NoDefault(object):
        pass

    spec2 = ModelSpec(
        model_fn=lambda: None, dataset_fn=None, loss=lambda y, p: 0,
        optimizer=lambda: None, eval_metrics_fn=lambda: {},
    )
    with pytest.raises(ValueError, match="dataset_fn is required"):
        resolve_dataset_fn(spec2, _NoDefault())


def test_to_iterator_covers_table_across_workers():
    """The standalone consumption surface (reference odps_io.py
    to_iterator): two workers' batch streams together cover every row
    exactly once per epoch."""
    rows = [(i,) for i in range(100)]
    table = _NumFakeTable(["v"], rows)
    from elasticdl_tpu.data.reader.odps_reader import ODPSReader

    seen = []
    for w in range(2):
        r = ODPSReader(table, window_size=16)
        for batch in r.to_iterator(2, w, batch_size=7):
            assert len(batch) <= 7
            seen.extend(batch)
    assert sorted(seen) == sorted(rows)

    r = ODPSReader(table, window_size=16)
    two_epochs = []
    for batch in r.to_iterator(1, 0, batch_size=10, epochs=2):
        two_epochs.extend(batch)
    assert len(two_epochs) == 200

    with pytest.raises(ValueError, match="worker"):
        next(r.to_iterator(2, 5, batch_size=4))
