"""ODPS write path + k-v table tools (VERDICT.md round-1 missing #3):
writer round-trips a table through the reader; flattening tools match the
reference UDTF protocol."""

import threading

import pytest

from elasticdl_tpu.data.odps_writer import ODPSWriter
from elasticdl_tpu.data.reader.odps_reader import ODPSDataReader
from elasticdl_tpu.tools import odps_table_tools as kv


# ----------------------------------------------------------- fake ODPS


class _FakeColumn(object):
    def __init__(self, name, type_):
        self.name = name
        self.type = type_


class _FakeSchema(object):
    def __init__(self, names):
        self.columns = [_FakeColumn(n, "string") for n in names]


class _FakeWriterCtx(object):
    def __init__(self, store, fail_times, lock):
        self._store = store
        self._fail = fail_times
        self._lock = lock

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def write(self, records):
        with self._lock:
            if self._fail and self._fail[0] > 0:
                self._fail[0] -= 1
                raise IOError("transient write failure")
            self._store.extend(records)


class _FakeReaderCtx(object):
    def __init__(self, rows):
        self._rows = rows
        self.count = len(rows)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def read(self, start, count):
        return self._rows[start:start + count]


class _FakeTable(object):
    name = "sink"

    def __init__(self, fail_times=None):
        self.schema = _FakeSchema(["a", "b"])
        self.partitions = {}  # partition spec -> record list
        self._fail = fail_times
        self._lock = threading.Lock()

    def open_writer(self, partition=None, create_partition=False):
        assert create_partition
        store = self.partitions.setdefault(partition, [])
        return _FakeWriterCtx(store, self._fail, self._lock)

    def open_reader(self):
        rows = []
        for part in sorted(self.partitions):
            rows.extend(self.partitions[part])
        return _FakeReaderCtx(rows)


# -------------------------------------------------------------- writer


def test_from_iterator_writes_worker_partition():
    table = _FakeTable()
    writer = ODPSWriter(table=table)
    writer.from_iterator(
        iter([[(1, "x")], [(2, "y"), (3, "z")]]), worker_index=7
    )
    assert table.partitions == {"worker=7": [(1, "x"), (2, "y"), (3, "z")]}


def test_write_records_windows_and_parallel():
    table = _FakeTable()
    writer = ODPSWriter(table=table, window_size=10, num_parallel=3)
    records = [(i, str(i)) for i in range(95)]
    n = writer.write_records(records, worker_index=0)
    assert n == 95
    written = table.partitions["worker=0"]
    # parallel threads interleave windows; content must be complete
    assert sorted(written) == sorted(records)


def test_write_retry_recovers_transient_failures():
    table = _FakeTable(fail_times=[2])
    writer = ODPSWriter(table=table, window_size=5, num_parallel=2)
    records = [(i, "v") for i in range(20)]
    writer.write_records(records)
    assert sorted(table.partitions["worker=0"]) == sorted(records)


def test_write_permanent_failure_raises():
    table = _FakeTable(fail_times=[10_000])
    writer = ODPSWriter(table=table, window_size=5, num_parallel=1,
                        max_retries=2)
    with pytest.raises(IOError):
        writer.write_records([(1, "v")] * 8)


def test_round_trip_through_reader():
    """Writer -> reader round-trip (the env-gated integration the
    reference exercised on a real cluster, run here on the fake)."""
    table = _FakeTable()
    ODPSWriter(table=table, window_size=4).write_records(
        [(i, i * 2) for i in range(30)]
    )
    reader = ODPSDataReader(table=table, records_per_task=10)
    shards = reader.create_shards()
    assert sum(n for _, n in shards.values()) == 30

    class _Task(object):
        def __init__(self, start, end):
            self.start, self.end = start, end

    rows = list(reader.read_records(_Task(0, 30)))
    # parallel writer sessions interleave windows: row ORDER across
    # sessions is not part of the contract (shards re-slice by range,
    # training shuffles); content completeness is.
    assert sorted(rows) == [(i, i * 2) for i in range(30)]


def test_missing_pyodps_raises():
    writer = ODPSWriter(table_name="proj.t", columns=["a"],
                        column_types=["string"])
    assert writer._project == "proj"
    with pytest.raises(RuntimeError, match="odps package"):
        writer.write_records([("x",)])


# ------------------------------------------------------------ kv tools


def test_parse_and_flatten():
    assert kv.parse_kv_string("k1:v1,k2:v2") == {"k1": "v1", "k2": "v2"}
    # malformed pairs skipped
    assert kv.parse_kv_string("k1:v1,junk,k3:v3:x") == {"k1": "v1"}
    assert kv.flatten_kv_record("b:2,a:1", ["a", "b", "c"]) == ["1", "2", ""]


def test_analyze_feature_names():
    records = [
        {"kv": "f2:1,f1:2"},
        {"kv": "f3:9"},
        {"kv": "f1:0"},
    ]
    names = kv.analyze_feature_names(records, kv_value_fn=lambda r: r["kv"])
    assert names == ["f1", "f2", "f3"]
    # max_records honored
    assert kv.analyze_feature_names(
        records, kv_value_fn=lambda r: r["kv"], max_records=1
    ) == ["f1", "f2"]


def test_kv_flatter_udtf_protocol():
    """args = (kv value, *append columns, names csv, pair sep, kv sep) —
    the reference normalize_kv_udf.KVFlatter contract."""
    f = kv.KVFlatter()
    f.process("age:30,wage:10.5", 1, "age,wage,unknown", ",", ":")
    assert f.collected == [["30", "10.5", "", "1"]]
    with pytest.raises(ValueError):
        f.process("a:1", ",", ":")


def test_generate_transform_sql():
    sql = kv.generate_transform_sql(
        input_table="src",
        output_table="dst",
        feature_names=["f1", "f2"],
        kv_column="features",
        udf_function="my_udf",
        append_columns=["label"],
        input_table_partition="dt=20200101",
    )
    assert sql.startswith("CREATE TABLE IF NOT EXISTS dst")
    assert 'my_udf(features,label,\n    "f1,f2", ",", ":")' in sql
    assert "as (f1,f2,label)" in sql
    assert "FROM src" in sql
    assert sql.endswith("where dt=20200101")


def test_transform_kv_table_end_to_end_fake():
    """Driver wiring against a fake ODPS entry: analyze -> register UDTF
    -> run SQL -> cleanup, including cleanup on SQL failure."""

    class _FakeInstance(object):
        def wait_for_success(self):
            pass

    class _FakeSrcTable(object):
        def head(self, n, partition=None):
            return [{"features": "f1:1,f2:2"}, {"features": "f2:3,f3:4"}]

    class _FakeEntry(object):
        def __init__(self):
            self.resources = set()
            self.functions = set()
            self.sql = []

        def get_table(self, name):
            return _FakeSrcTable()

        def create_resource(self, name, type=None, file_obj=None):
            self.resources.add(name)
            self.resource_content = file_obj.read()
            return name

        def delete_resource(self, name):
            self.resources.discard(name)

        def create_function(self, name, class_type=None, resources=None):
            self.functions.add(name)
            return name

        def delete_function(self, name):
            self.functions.discard(name)

        def run_sql(self, sql):
            self.sql.append(sql)
            return _FakeInstance()

    entry = _FakeEntry()
    names = kv.transform_kv_table(
        entry, "src", "dst", kv_column="features", append_columns=["label"]
    )
    assert names == ["f1", "f2", "f3"]
    assert len(entry.sql) == 1 and "FROM src" in entry.sql[0]
    # the uploaded resource is a real cluster-side UDTF (BaseUDTF with
    # a forwarding process()), not the local test twin
    assert "BaseUDTF" in entry.resource_content
    assert "def process" in entry.resource_content
    # temporaries cleaned up
    assert not entry.resources and not entry.functions
