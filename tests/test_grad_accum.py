"""Gradient accumulation (the reference worker's local-update mode,
--get_model_steps: accumulate minibatch gradients, sync every Nth —
reference worker.py:1007-1089). TPU-native form: optax.MultiSteps inside
the compiled step — N train_step calls, one averaged dense update."""

import numpy as np

import jax

from elasticdl_tpu.common.args import parse_worker_args
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

PARAMS = (
    "vocab_size=32; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _tokens(bsz, seed):
    rs = np.random.RandomState(seed)
    return rs.randint(0, 32, size=(bsz, 17)).astype(np.int32)


def _as_batch(tokens):
    return {"tokens": tokens[:, :-1]}, tokens[:, 1:]


def test_two_microbatches_match_one_big_batch():
    import optax

    spec = load_model_spec_from_module(zoo)
    # SGD is linear in the gradient, so mean-of-microbatch-grads must
    # reproduce the big-batch update exactly (adamw's rsqrt normalization
    # amplifies fp32 reassociation noise on near-zero gradients).
    spec.optimizer = lambda: optax.sgd(0.1)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    tokens = _tokens(8, seed=0)

    big = Trainer(spec, mesh=mesh, model_params=PARAMS)
    s_big = big.init_state(_as_batch(tokens))
    s_big, _ = big.train_step(s_big, _as_batch(tokens))

    accum = Trainer(spec, mesh=mesh, model_params=PARAMS,
                    grad_accum_steps=2)
    s_acc = accum.init_state(_as_batch(tokens[:4]))
    params0 = jax.tree.map(np.asarray, s_acc.params)
    s_acc, _ = accum.train_step(s_acc, _as_batch(tokens[:4]))
    # non-boundary microbatch: dense params must not move
    for a, b in zip(
        jax.tree.leaves(params0), jax.tree.leaves(s_acc.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s_acc, _ = accum.train_step(s_acc, _as_batch(tokens[4:]))

    # boundary: averaged-gradient update == one big-batch update
    for a, b in zip(
        jax.tree.leaves(s_big.params), jax.tree.leaves(s_acc.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_accum_training_reduces_loss():
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=PARAMS,
                      grad_accum_steps=4)
    batch = _as_batch(_tokens(8, seed=1))
    state = trainer.init_state(batch)
    first = None
    for _ in range(24):
        state, loss = trainer.train_step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))
    assert int(state.step) == 24


def _sparse_spec():
    import optax
    from flax import linen as nn

    from elasticdl_tpu.common.model_utils import ModelSpec
    from elasticdl_tpu.embedding.layer import Embedding

    class Rec(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            emb = Embedding(
                input_dim=64, output_dim=8, sparse_grads=True, name="cat"
            )(features["ids"])
            return nn.Dense(1, name="out")(emb.mean(axis=1))[:, 0]

    return ModelSpec(
        model_fn=Rec,
        dataset_fn=lambda ds, mode, meta: ds,
        loss=lambda y, p, w: (w * (p - y) ** 2).sum() / w.sum(),
        optimizer=lambda: optax.sgd(0.1),
        eval_metrics_fn=lambda: {},
    )


def test_accum_sparse_row_parity():
    """Sparse-tapped tables under accumulation: k microbatches stage
    their dedup'd row grads and apply once per macro step — the final
    table, dense params, AND row-optimizer slots must equal the one
    big-batch update (VERDICT round-2 item #6; reference local-update
    semantics, worker.py:822-828)."""
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 16, size=(8, 4)).astype(np.int32)
    labels = rs.rand(8).astype(np.float32)

    big = Trainer(_sparse_spec(), mesh=mesh_lib.local_mesh())
    s_big = big.init_state(({"ids": ids}, labels))
    s_big, _ = big.train_step(s_big, ({"ids": ids}, labels))

    acc = Trainer(_sparse_spec(), mesh=mesh_lib.local_mesh(),
                  grad_accum_steps=2)
    s_acc = acc.init_state(({"ids": ids[:4]}, labels[:4]))
    table0 = np.asarray(
        jax.tree.leaves(s_acc.params["cat"])[0]
    ).copy()
    s_acc, _ = acc.train_step(s_acc, ({"ids": ids[:4]}, labels[:4]))
    # non-boundary microbatch: the embedding table must not move
    np.testing.assert_array_equal(
        table0, np.asarray(jax.tree.leaves(s_acc.params["cat"])[0])
    )
    s_acc, _ = acc.train_step(s_acc, ({"ids": ids[4:]}, labels[4:]))

    for a, b in zip(
        jax.tree.leaves(s_big.params), jax.tree.leaves(s_acc.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )
    for a, b in zip(
        jax.tree.leaves(s_big.embed_opt_state),
        jax.tree.leaves(s_acc.embed_opt_state),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_accum_host_spill_parity():
    """Host-spill tables under accumulation: staged row grads (weighted
    1/k) apply through the engines once per macro step; the trained
    host rows must equal one big-batch step's."""
    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.embedding.host_bridge import (
        HostEmbeddingManager,
    )
    from elasticdl_tpu.embedding.host_spill import HostSpillEmbeddingEngine
    from model_zoo.deepfm_host_embedding import deepfm_host_embedding as z

    def build(accum):
        spec = load_model_spec_from_module(z)
        tr = Trainer(
            spec, mesh=mesh_lib.local_mesh(),
            model_params=format_params_str(
                dict(input_length=5, fc_unit=4)
            ),
            grad_accum_steps=accum,
        )
        mgr = HostEmbeddingManager()
        mgr.register(
            "edl_embedding", "feature",
            HostSpillEmbeddingEngine(8, optimizer="sgd", lr=0.1),
        )
        mgr.register(
            "edl_id_bias", "feature",
            HostSpillEmbeddingEngine(1, optimizer="sgd", lr=0.1),
        )
        tr.attach_host_embeddings(mgr)
        return tr, mgr

    rs = np.random.RandomState(3)
    ids = rs.randint(0, 40, size=(8, 5)).astype(np.int32)
    labels = rs.randint(0, 2, size=(8,)).astype(np.int32)

    big, big_mgr = build(1)
    s_big = big.init_state(({"feature": ids}, labels))
    s_big, _ = big.train_step(s_big, ({"feature": ids}, labels))

    acc, acc_mgr = build(2)
    s_acc = acc.init_state(({"feature": ids[:4]}, labels[:4]))
    s_acc, _ = acc.train_step(s_acc, ({"feature": ids[:4]}, labels[:4]))
    # mid-cycle: engines untouched, step counters unmoved
    assert acc_mgr.tables()["edl_embedding"].engine._step == 0
    s_acc, _ = acc.train_step(s_acc, ({"feature": ids[4:]}, labels[4:]))
    assert acc_mgr.tables()["edl_embedding"].engine._step == 1

    for table in ("edl_embedding", "edl_id_bias"):
        bids, bvals = big_mgr.tables()[table].engine.param.export_rows()
        aids, avals = acc_mgr.tables()[table].engine.param.export_rows()
        bmap = dict(zip(bids.tolist(), bvals))
        amap = dict(zip(aids.tolist(), avals))
        assert sorted(bmap) == sorted(amap)
        for i in bmap:
            np.testing.assert_allclose(
                amap[i], bmap[i], rtol=1e-5, atol=1e-7
            )
    for a, b in zip(
        jax.tree.leaves(s_big.params), jax.tree.leaves(s_acc.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_get_model_steps_cli_alias():
    base = [
        "--worker_id", "0", "--model_zoo", "model_zoo",
        "--model_def", "m.m.custom_model", "--master_addr", "x:1",
    ]
    args = parse_worker_args(base + ["--grad_accum_steps", "4"])
    assert args.grad_accum_steps == 4
    args = parse_worker_args(base + ["--get_model_steps", "3"])
    assert args.grad_accum_steps == 3
    args = parse_worker_args(base)
    assert args.grad_accum_steps == 1
