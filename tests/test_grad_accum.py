"""Gradient accumulation (the reference worker's local-update mode,
--get_model_steps: accumulate minibatch gradients, sync every Nth —
reference worker.py:1007-1089). TPU-native form: optax.MultiSteps inside
the compiled step — N train_step calls, one averaged dense update."""

import numpy as np

import jax

from elasticdl_tpu.common.args import parse_worker_args
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

PARAMS = (
    "vocab_size=32; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _tokens(bsz, seed):
    rs = np.random.RandomState(seed)
    return rs.randint(0, 32, size=(bsz, 17)).astype(np.int32)


def _as_batch(tokens):
    return {"tokens": tokens[:, :-1]}, tokens[:, 1:]


def test_two_microbatches_match_one_big_batch():
    import optax

    spec = load_model_spec_from_module(zoo)
    # SGD is linear in the gradient, so mean-of-microbatch-grads must
    # reproduce the big-batch update exactly (adamw's rsqrt normalization
    # amplifies fp32 reassociation noise on near-zero gradients).
    spec.optimizer = lambda: optax.sgd(0.1)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    tokens = _tokens(8, seed=0)

    big = Trainer(spec, mesh=mesh, model_params=PARAMS)
    s_big = big.init_state(_as_batch(tokens))
    s_big, _ = big.train_step(s_big, _as_batch(tokens))

    accum = Trainer(spec, mesh=mesh, model_params=PARAMS,
                    grad_accum_steps=2)
    s_acc = accum.init_state(_as_batch(tokens[:4]))
    params0 = jax.tree.map(np.asarray, s_acc.params)
    s_acc, _ = accum.train_step(s_acc, _as_batch(tokens[:4]))
    # non-boundary microbatch: dense params must not move
    for a, b in zip(
        jax.tree.leaves(params0), jax.tree.leaves(s_acc.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s_acc, _ = accum.train_step(s_acc, _as_batch(tokens[4:]))

    # boundary: averaged-gradient update == one big-batch update
    for a, b in zip(
        jax.tree.leaves(s_big.params), jax.tree.leaves(s_acc.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_accum_training_reduces_loss():
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=PARAMS,
                      grad_accum_steps=4)
    batch = _as_batch(_tokens(8, seed=1))
    state = trainer.init_state(batch)
    first = None
    for _ in range(24):
        state, loss = trainer.train_step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))
    assert int(state.step) == 24


def test_accum_rejects_sparse_tapped_models():
    """Sparse-row tables update every microbatch; combining them with a
    deferred dense update would train tiers on divergent schedules, so
    init_state must fail fast (reference forces get_model_steps=1 outside
    plain async dense training, common/args.py:156)."""
    import optax
    import pytest
    from flax import linen as nn

    from elasticdl_tpu.common.model_utils import ModelSpec
    from elasticdl_tpu.embedding.layer import Embedding

    class Rec(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            emb = Embedding(
                input_dim=64, output_dim=8, sparse_grads=True, name="cat"
            )(features["ids"])
            return nn.Dense(1, name="out")(emb)[:, 0]

    spec = ModelSpec(
        model_fn=Rec,
        dataset_fn=lambda ds, mode, meta: ds,
        loss=lambda y, p: ((p - y) ** 2).mean(),
        optimizer=lambda: optax.sgd(0.1),
        eval_metrics_fn=lambda: {},
    )
    trainer = Trainer(
        spec, mesh=mesh_lib.local_mesh(), grad_accum_steps=2
    )
    rs = np.random.RandomState(0)
    batch = (
        {"ids": rs.randint(0, 16, size=(8, 4)).astype(np.int32)},
        rs.rand(8).astype(np.float32),
    )
    with pytest.raises(ValueError, match="dense-only"):
        trainer.init_state(batch)


def test_get_model_steps_cli_alias():
    base = [
        "--worker_id", "0", "--model_zoo", "model_zoo",
        "--model_def", "m.m.custom_model", "--master_addr", "x:1",
    ]
    args = parse_worker_args(base + ["--grad_accum_steps", "4"])
    assert args.grad_accum_steps == 4
    args = parse_worker_args(base + ["--get_model_steps", "3"])
    assert args.grad_accum_steps == 3
    args = parse_worker_args(base)
    assert args.grad_accum_steps == 1
