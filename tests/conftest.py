"""Test env: force an 8-device virtual CPU platform BEFORE jax initializes,
so multi-chip sharding tests run anywhere (the driver's dryrun does the same).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
