"""Test env: force an 8-device virtual CPU platform BEFORE any XLA client
initializes, so multi-chip sharding tests run anywhere (the driver's
multichip dryrun uses the same mechanism).

Note: the ambient TPU plugin may override JAX_PLATFORMS at `import jax`
time, so we must also set the config knob after import — env vars alone are
not enough in this environment.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

_platform = os.environ.get("EDL_TPU_TEST_PLATFORM", "cpu")
if _platform in ("tpu", "ambient"):
    # Hardware rig (tests/test_tpu_smoke.py): let jax pick the ambient
    # accelerator. Pinning JAX_PLATFORMS=tpu here can select a local
    # libtpu registration instead of the tunneled plugin and fail with
    # "No jellyfish device found".
    os.environ.pop("JAX_PLATFORMS", None)

    import jax  # noqa: E402
else:
    os.environ["JAX_PLATFORMS"] = _platform

    import jax  # noqa: E402

    jax.config.update("jax_platforms", _platform)
