"""Elasticity core tests: k8s client manifests, instance-manager event
machine (relaunch policy, preemption, task recovery), watchdog wiring,
args round-trip — the same boundaries the reference mocks
(k8s_client_test.py, k8s_instance_manager_test.py)."""



from elasticdl_tpu.common.args import (
    MASTER_ONLY_ARGS,
    build_arguments_from_parsed_result,
    parse_master_args,
    parse_resource_spec,
    parse_worker_args,
    wrap_args_with_string,
)
from elasticdl_tpu.common.k8s_client import Client
from elasticdl_tpu.master.instance_manager import (
    K8sInstanceManager,
    parse_worker_pod_priority,
)


class FakeCoreApi(object):
    """Records API calls; returns dict pods like the real API would."""

    def __init__(self):
        self.created_pods = []
        self.deleted = []
        self.services = []

    def create_namespaced_pod(self, namespace, manifest):
        self.created_pods.append((namespace, manifest))
        return manifest

    def delete_namespaced_pod(self, name, namespace, body=None):
        self.deleted.append(name)

    def read_namespaced_pod(self, namespace, name):
        return {
            "metadata": {"name": name, "uid": "uid-%s" % name},
        }

    def create_namespaced_service(self, namespace, manifest):
        self.services.append((namespace, manifest))
        return manifest

    def patch_namespaced_pod(self, name, namespace, body):
        return body


class FakeTaskDispatcher(object):
    def __init__(self):
        self.recovered = []

    def recover_tasks(self, worker_id):
        self.recovered.append(worker_id)


def _client(api=None):
    return Client(
        image_name="img:latest",
        namespace="ns",
        job_name="testjob",
        core_api=api or FakeCoreApi(),
    )


def _manager(api=None, task_d=None, **kwargs):
    api = api or FakeCoreApi()
    task_d = task_d or FakeTaskDispatcher()
    manager = K8sInstanceManager(
        task_d,
        num_workers=kwargs.pop("num_workers", 2),
        worker_command=["python", "-m", "elasticdl_tpu.worker.main"],
        worker_args=["--master_addr", "localhost:1234"],
        k8s_client=_client(api),
        resource_request={"cpu": "1", "memory": "4096Mi"},
        **kwargs,
    )
    return manager, api, task_d


def _event(worker_id, phase, evt_type="MODIFIED", exit_code=None,
           reason=None):
    pod = {
        "metadata": {
            "labels": {
                "elasticdl-replica-type": "worker",
                "elasticdl-replica-index": str(worker_id),
            }
        },
        "status": {"phase": phase},
    }
    if exit_code is not None:
        pod["status"]["containerStatuses"] = [
            {"state": {"terminated": {"exitCode": exit_code,
                                      "reason": reason}}}
        ]
    return {"type": evt_type, "object": pod}


# ------------------------------------------------------------- k8s client


def test_worker_pod_manifest():
    api = FakeCoreApi()
    client = _client(api)
    client.create_worker_pod(
        3,
        command=["python"],
        args=["--worker_id", "3"],
        resource_requests={"cpu": "2", "google.com/tpu": "8"},
        resource_limits=None,
        priority_class="high",
    )
    ns, manifest = api.created_pods[0]
    assert ns == "ns"
    assert manifest["metadata"]["name"] == "elasticdl-testjob-worker-3"
    labels = manifest["metadata"]["labels"]
    assert labels["elasticdl-job-name"] == "testjob"
    assert labels["elasticdl-replica-type"] == "worker"
    assert labels["elasticdl-replica-index"] == "3"
    # owner ref ties worker GC to the master pod
    assert manifest["metadata"]["ownerReferences"][0]["name"] == (
        "elasticdl-testjob-master"
    )
    container = manifest["spec"]["containers"][0]
    assert container["resources"]["requests"]["google.com/tpu"] == "8"
    # limits default to requests
    assert container["resources"]["limits"]["cpu"] == "2"
    assert manifest["spec"]["priorityClassName"] == "high"


def test_delete_worker():
    api = FakeCoreApi()
    client = _client(api)
    client.delete_worker(5)
    assert api.deleted == ["elasticdl-testjob-worker-5"]


def test_worker_service_manifest():
    api = FakeCoreApi()
    client = _client(api)
    client.create_worker_service(1)
    _, manifest = api.services[0]
    sel = manifest["spec"]["selector"]
    assert sel["elasticdl-replica-index"] == "1"
    assert manifest["spec"]["ports"][0]["port"] == 3333


# ------------------------------------------------------ instance manager


def test_start_workers_launches_pods():
    manager, api, _ = _manager()
    manager.start_workers()
    assert len(api.created_pods) == 2
    args = api.created_pods[0][1]["spec"]["containers"][0]["args"]
    assert args[-2:] == ["--worker_id", "0"]


def test_failed_worker_recovers_tasks_and_relaunches():
    manager, api, task_d = _manager()
    manager.start_workers()
    manager.event_cb(_event(0, "Failed", exit_code=1))
    assert task_d.recovered == [0]
    # relaunched with a NEW worker id (reference :369-378)
    assert len(api.created_pods) == 3
    args = api.created_pods[2][1]["spec"]["containers"][0]["args"]
    assert args[-2:] == ["--worker_id", "2"]


def test_relaunch_budget_exhausted():
    manager, api, task_d = _manager(relaunch_on_worker_failure=2)
    manager.start_workers()
    current = 0
    for round_ in range(2):
        manager.event_cb(_event(current, "Failed", exit_code=1))
        current = 2 + round_  # relaunched id
    assert len(api.created_pods) == 4  # 2 initial + 2 relaunches
    # third failure: budget burned, no relaunch
    manager.event_cb(_event(current, "Failed", exit_code=1))
    assert len(api.created_pods) == 4


def test_preemption_exit_137_does_not_burn_retry():
    manager, api, task_d = _manager(relaunch_on_worker_failure=1)
    manager.start_workers()
    # preempted twice (137, not OOM): always relaunched
    manager.event_cb(_event(0, "Failed", exit_code=137))
    manager.event_cb(_event(2, "Failed", exit_code=137))
    assert len(api.created_pods) == 4
    # a real failure burns the single retry...
    manager.event_cb(_event(3, "Failed", exit_code=1))
    assert len(api.created_pods) == 5
    # ...and the next one is terminal
    manager.event_cb(_event(4, "Failed", exit_code=1))
    assert len(api.created_pods) == 5


def test_oom_137_burns_retry():
    manager, api, _ = _manager(relaunch_on_worker_failure=1)
    manager.start_workers()
    manager.event_cb(_event(0, "Failed", exit_code=137, reason="OOMKilled"))
    assert len(api.created_pods) == 3
    manager.event_cb(_event(2, "Failed", exit_code=137, reason="OOMKilled"))
    assert len(api.created_pods) == 3  # budget exhausted


def test_deleted_pod_relaunches():
    manager, api, task_d = _manager()
    manager.start_workers()
    manager.event_cb(_event(1, "Running", evt_type="DELETED"))
    assert task_d.recovered == [1]
    assert len(api.created_pods) == 3


def test_succeeded_worker_not_relaunched():
    manager, api, _ = _manager()
    manager.start_workers()
    manager.event_cb(_event(0, "Succeeded"))
    assert len(api.created_pods) == 2
    assert manager.worker_phase(0) == "Succeeded"


def test_all_workers_failed():
    manager, _, _ = _manager(num_workers=2, disable_relaunch=True)
    manager.start_workers()
    assert not manager.all_workers_failed()
    manager.event_cb(_event(0, "Failed", exit_code=1))
    assert not manager.all_workers_failed()
    manager.event_cb(_event(1, "Failed", exit_code=1))
    assert manager.all_workers_failed()


def test_disable_relaunch():
    manager, api, _ = _manager(disable_relaunch=True)
    manager.start_workers()
    manager.event_cb(_event(0, "Failed", exit_code=137))
    assert len(api.created_pods) == 2


def test_remove_worker_deletes_pod():
    manager, api, _ = _manager()
    manager.start_workers()
    manager.remove_worker(1)
    assert api.deleted == ["elasticdl-testjob-worker-1"]


def test_non_worker_events_ignored():
    manager, api, task_d = _manager()
    manager.start_workers()
    event = {
        "type": "MODIFIED",
        "object": {
            "metadata": {"labels": {"elasticdl-replica-type": "master"}},
            "status": {"phase": "Failed"},
        },
    }
    manager.event_cb(event)
    assert task_d.recovered == []


# --------------------------------------------------------------- priority


def test_priority_fraction():
    pri = parse_worker_pod_priority(4, "high=0.5")
    assert pri == {0: "high", 1: "high", 2: None, 3: None}


def test_priority_uniform_and_empty():
    assert parse_worker_pod_priority(2, "low") == {0: "low", 1: "low"}
    assert parse_worker_pod_priority(2, "") == {0: None, 1: None}


# ------------------------------------------------------------------- args


def test_args_roundtrip():
    argv = [
        "--model_zoo", "model_zoo",
        "--model_def", "mnist_functional_api.mnist_functional_api."
                       "custom_model",
        "--training_data", "/data/train",
        "--num_workers", "2",
        "--minibatch_size", "64",
        "--worker_pod_priority", "high=0.5",
    ]
    args = parse_master_args(argv)
    rebuilt = build_arguments_from_parsed_result(
        args, filter_args=MASTER_ONLY_ARGS
    )
    # a worker parses the rebuilt line (plus its own flags)
    worker_args = parse_worker_args(
        rebuilt + ["--worker_id", "0", "--master_addr", "h:1"]
    )
    assert worker_args.minibatch_size == 64
    assert worker_args.training_data == "/data/train"
    assert worker_args.model_zoo == "model_zoo"
    assert "--num_workers" not in rebuilt


def test_wrap_args_quotes():
    assert wrap_args_with_string(["--a", "x y"]) == "--a 'x y'"


def test_parse_resource_spec():
    assert parse_resource_spec("cpu=1,memory=4096Mi,google.com/tpu=8") == {
        "cpu": "1", "memory": "4096Mi", "google.com/tpu": "8",
    }


# --------------------------------------------------------------- watchdog


def test_watchdog_removes_straggler():
    """Master.check_timeout_tasks recovers 3x-average stragglers and
    removes the worker (reference master.py:536-558)."""
    import time

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.master.master import Master
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    class FakeManager(object):
        def __init__(self):
            self.removed = []

        def start_workers(self):
            pass

        def all_workers_failed(self):
            return False

        def remove_worker(self, worker_id):
            self.removed.append(worker_id)

        def stop(self):
            pass

    class FakeReader(object):
        def __init__(self, *a, **k):
            pass

        def create_shards(self):
            return {"shard": (0, 512)}

    spec = load_model_spec_from_module(zoo)
    manager = FakeManager()
    master = Master(
        spec,
        training_data="unused",
        create_data_reader_fn=lambda *a, **k: FakeReader(),
        instance_manager=manager,
    )
    # worker 7 takes a task; averages say tasks complete in ~0.01s
    task_id, task = master.task_d.get(7)
    assert task is not None
    master.servicer._task_complete_times[task.type] = [0.01] * 25
    # backdate the doing-task start time beyond 3x average
    worker_id, t, start = master.task_d._doing[task_id]
    master.task_d._doing[task_id] = (worker_id, t, start - 600.0)
    master.check_timeout_tasks()
    assert manager.removed == [7]
    assert task_id not in master.task_d.doing_tasks()


def test_stop_does_not_relaunch_killed_workers():
    """stop() kills the fleet; the resulting exit/DELETED events must NOT
    trigger relaunches (shutdown, not preemption)."""
    manager, api, task_d = _manager()
    manager.start_workers()
    manager.stop()
    # watch events for the deliberate deletions arrive after stop()
    manager.event_cb(_event(0, "Running", evt_type="DELETED"))
    manager.event_cb(_event(1, "Failed", exit_code=137))
    assert len(api.created_pods) == 2  # no relaunches
    assert task_d.recovered == []


def test_tensorboard_service_exposure():
    """TB k8s exposure parity (reference k8s_tensorboard_client.py):
    create_tensorboard_service builds a LoadBalancer in front of the
    master pod, and TensorBoardClient polls until the ingress IP
    appears."""
    from elasticdl_tpu.common.k8s_tensorboard_client import (
        TensorBoardClient,
    )

    class _TBFakeApi(FakeCoreApi):
        def __init__(self):
            super().__init__()
            self.reads = 0

        def read_namespaced_service(self, name, namespace):
            self.reads += 1
            ingress = (
                [{"ip": "203.0.113.7"}] if self.reads >= 2 else None
            )
            return {
                "metadata": {"name": name, "namespace": namespace},
                "status": {"load_balancer": {"ingress": ingress}},
            }

    api = _TBFakeApi()
    tb = TensorBoardClient(client=_client(api))
    url = tb.start_tensorboard_service(check_interval=0, wait_timeout=5)
    assert url == "203.0.113.7"
    assert api.reads >= 2  # first poll saw no ingress, second did

    (namespace, manifest), = api.services
    assert namespace == "ns"
    assert manifest["metadata"]["name"] == "testjob-tensorboard"
    assert manifest["spec"]["type"] == "LoadBalancer"
    assert manifest["spec"]["ports"] == [
        {"port": 80, "targetPort": 6006, "protocol": "TCP"}
    ]
    sel = manifest["spec"]["selector"]
    assert sel["elasticdl-replica-type"] == "master"


def test_tensorboard_url_timeout_returns_none():
    from elasticdl_tpu.common.k8s_tensorboard_client import (
        TensorBoardClient,
    )

    class _NoIngressApi(FakeCoreApi):
        def read_namespaced_service(self, name, namespace):
            return {"status": {"load_balancer": {"ingress": None}}}

    tb = TensorBoardClient(client=_client(_NoIngressApi()))
    assert tb.start_tensorboard_service(
        check_interval=0, wait_timeout=0.2
    ) is None


def test_master_main_exposes_tensorboard_via_manager():
    """_run_master's cluster branch publishes TB through the instance
    manager's k8s client (wiring check for _expose_tensorboard)."""
    import time

    from elasticdl_tpu.master import main as master_main

    class _IngressApi(FakeCoreApi):
        def read_namespaced_service(self, name, namespace):
            return {"status": {"load_balancer": {
                "ingress": [{"ip": "198.51.100.1"}]}}}

    api = _IngressApi()

    class _Manager(object):
        _client = _client(api)

    master_main._expose_tensorboard(_Manager())
    deadline = time.time() + 5
    while not api.services and time.time() < deadline:
        time.sleep(0.05)
    (_, manifest), = api.services
    assert manifest["metadata"]["name"] == "testjob-tensorboard"


def test_master_validates_missing_dataset_fn_at_submission():
    """A spec without dataset_fn and a reader that derives none must
    fail at master submission, not on the workers' first task."""
    import argparse

    import pytest as _pytest

    from elasticdl_tpu.common.model_utils import ModelSpec
    from elasticdl_tpu.master import main as master_main

    spec = ModelSpec(
        model_fn=lambda: None, dataset_fn=None, loss=lambda y, p: 0,
        optimizer=lambda: None, eval_metrics_fn=lambda: {},
    )
    args = argparse.Namespace(
        training_data="/tmp/nope", validation_data="", prediction_data="",
        records_per_task=16, data_reader_params="",
    )
    with _pytest.raises(ValueError, match="dataset_fn is required"):
        master_main._validate_dataset_fn(spec, args)
