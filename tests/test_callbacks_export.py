"""Callbacks + export/import + model handler.

Mirrors the reference's callbacks coverage (callbacks.py:25-154) and the
model-handler export path (model_handler_test.py)."""

import json
import os

import numpy as np
import pytest

from elasticdl_tpu.api.callbacks import (
    CallbackList,
    LearningRateScheduler,
    MaxStepsStopping,
    SavedModelExporter,
)
from elasticdl_tpu.api.exporter import load_exported, make_serving_fn
from elasticdl_tpu.common.constants import DistributionStrategy
from elasticdl_tpu.common.model_handler import (
    LocalModelHandler,
    MeshModelHandler,
    ModelHandler,
)
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.master.task_dispatcher import Task, TaskDispatcher, TaskType

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


@pytest.fixture(scope="module")
def batch():
    rng = np.random.RandomState(0)
    return (
        {"image": rng.rand(8, 28, 28).astype(np.float32)},
        rng.randint(10, size=(8,)).astype(np.int32),
    )


def _trainer(spec, **kw):
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer

    return Trainer(spec, mesh=mesh_lib.local_mesh(), **kw)


class TestMaxStepsStopping:
    def test_stops_dispatcher(self):
        dispatcher = TaskDispatcher(
            {"f": (0, 1000)}, {}, {}, records_per_task=100, num_epochs=10
        )
        cb = MaxStepsStopping(max_steps=5, minibatch_size=50)
        cb.set_task_dispatcher(dispatcher)
        # each task = 100 records = 2 steps of 50
        for i in range(3):
            cb.on_task_end(Task("f", 0, 100, TaskType.TRAINING))
        assert dispatcher.stop_training  # 6 steps >= 5

    def test_ignores_eval_tasks(self):
        dispatcher = TaskDispatcher(
            {"f": (0, 100)}, {}, {}, records_per_task=100, num_epochs=1
        )
        cb = MaxStepsStopping(max_steps=1, minibatch_size=10)
        cb.set_task_dispatcher(dispatcher)
        cb.on_task_end(Task("f", 0, 100, TaskType.EVALUATION))
        assert not dispatcher.stop_training

    def test_resume_seeds_completed_steps(self, tmp_path):
        """The master seeds MaxStepsStopping with the checkpoint version
        on resume, so max_steps counts TOTAL job steps (reference
        _set_completed_steps_by_checkpoint, master.py:176-192)."""
        from elasticdl_tpu.api.callbacks import CallbackList
        from elasticdl_tpu.master.master import Master

        # a valid version-7 checkpoint dir (content irrelevant here)
        vdir = tmp_path / "ckpt" / "version-7"
        vdir.mkdir(parents=True)
        (vdir / "variables-0-of-1.ckpt").write_bytes(b"")

        from model_zoo.mnist_functional_api import (
            mnist_functional_api as zoo,
        )

        cb = MaxStepsStopping(max_steps=8, minibatch_size=100)
        master = Master(
            load_model_spec_from_module(zoo),
            training_data=None,
            create_data_reader_fn=lambda *a, **k: None,
            callbacks_list=CallbackList([cb]),
            checkpoint_dir_for_init=str(tmp_path / "ckpt"),
        )
        assert cb._completed_steps == 7
        # one more 100-record task crosses max_steps=8
        cb.on_task_end(Task("f", 0, 100, TaskType.TRAINING))
        assert master.task_d.stop_training

        with pytest.raises(ValueError, match="Invalid checkpoint"):
            Master(
                load_model_spec_from_module(zoo),
                training_data=None,
                create_data_reader_fn=lambda *a, **k: None,
                callbacks_list=CallbackList([MaxStepsStopping(1)]),
                checkpoint_dir_for_init=str(tmp_path / "nope"),
            )


class TestLearningRateScheduler:
    def test_schedule_compiled_into_step(self, spec, batch):
        """multiplier 0 ⇒ params must not move; multiplier 1 ⇒ they do."""
        frozen = _trainer(
            spec, callbacks=[LearningRateScheduler(lambda v: 0.0)]
        )
        s0 = frozen.init_state(batch)
        import jax

        p_before = jax.tree.map(np.asarray, s0.params)
        s1, _ = frozen.train_step(s0, batch)
        p_after = jax.tree.map(np.asarray, s1.params)
        for a, b in zip(jax.tree.leaves(p_before), jax.tree.leaves(p_after)):
            np.testing.assert_array_equal(a, b)

        moving = _trainer(
            spec, callbacks=[LearningRateScheduler(lambda v: 1.0)]
        )
        m0 = moving.init_state(batch)
        m_before = jax.tree.map(np.asarray, m0.params)
        m1, _ = moving.train_step(m0, batch)
        changed = any(
            not np.array_equal(a, np.asarray(b))
            for a, b in zip(
                jax.tree.leaves(m_before), jax.tree.leaves(m1.params)
            )
        )
        assert changed


class TestExport:
    def test_export_load_serve_roundtrip(self, spec, batch, tmp_path):
        trainer = _trainer(spec)
        state = trainer.init_state(batch)
        state, _ = trainer.train_step(state, batch)
        export_dir = str(tmp_path / "export")

        from elasticdl_tpu.api.exporter import export_model

        export_model(trainer.model, state, export_dir)
        assert os.path.exists(os.path.join(export_dir, "params.msgpack"))
        with open(os.path.join(export_dir, "meta.json")) as f:
            meta = json.load(f)
        assert meta["version"] == 1

        payload, meta2 = load_exported(export_dir)
        assert meta2 == meta
        serve = make_serving_fn(trainer.model, payload)
        preds = serve(batch[0])
        assert np.asarray(preds).shape == (8, 10)
        # serving output matches the trainer's own forward pass
        expect = trainer.forward(state, batch[0])
        np.testing.assert_allclose(
            np.asarray(preds), np.asarray(expect), rtol=1e-5
        )

    def test_saved_model_exporter_callback(self, spec, batch, tmp_path):
        class FakeWorker:
            pass

        trainer = _trainer(spec)
        w = FakeWorker()
        w.trainer = trainer
        w.state = trainer.init_state(batch)
        export_dir = str(tmp_path / "cb_export")
        SavedModelExporter(export_dir).on_train_end(w)
        payload, _ = load_exported(export_dir)
        assert "params" in payload

    def test_model_handler_prefers_checkpoint(self, spec, batch, tmp_path):
        from elasticdl_tpu.checkpoint import CheckpointSaver

        trainer = _trainer(spec)
        state = trainer.init_state(batch)
        trained, _ = trainer.train_step(state, batch)
        ckpt_dir = str(tmp_path / "ckpt")
        CheckpointSaver(ckpt_dir, checkpoint_steps=1).save(
            trained, version=1
        )
        handler = ModelHandler.get_model_handler(
            DistributionStrategy.PARAMETER_SERVER, checkpoint_dir=ckpt_dir
        )
        assert isinstance(handler, MeshModelHandler)
        export_dir = str(tmp_path / "export")
        # hand the handler a FRESH state: the export must reflect the
        # checkpoint (trained) weights, proving it read the checkpoint
        fresh = trainer.init_state(batch)
        handler.get_model_to_export(trainer.model, fresh, export_dir)
        payload, meta = load_exported(export_dir)
        assert meta["version"] == 1
        serve = make_serving_fn(trainer.model, payload)
        expect = trainer.forward(trained, batch[0])
        np.testing.assert_allclose(
            np.asarray(serve(batch[0])), np.asarray(expect), rtol=1e-5
        )

    def test_get_model_handler_strategies(self):
        assert isinstance(
            ModelHandler.get_model_handler(DistributionStrategy.LOCAL),
            LocalModelHandler,
        )
        assert isinstance(
            ModelHandler.get_model_handler(None), LocalModelHandler
        )
        assert isinstance(
            ModelHandler.get_model_handler(DistributionStrategy.MESH),
            MeshModelHandler,
        )


class TestCallbackList:
    def test_dispatcher_invokes_on_task_end(self):
        seen = []

        class Spy:
            def on_task_end(self, task):
                seen.append(task.task_id if hasattr(task, "task_id") else task)

        dispatcher = TaskDispatcher(
            {"f": (0, 64)}, {}, {}, records_per_task=64, num_epochs=1,
            callbacks_list=CallbackList([Spy()]),
        )
        tid, task = dispatcher.get("w0")
        dispatcher.report(tid, True)
        assert len(seen) == 1
