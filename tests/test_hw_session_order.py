"""Orchestration-order contract for scripts/hw_session.py.

The tunnel's observed windows are minutes long (TUNNEL_LOG.md), so the
session's VALUE ORDER is load-bearing: once a prior sweep has persisted
tuned flash blocks (elasticdl_tpu/ops/flash_tuning.json, committed),
the prelim flagship run IS the tuned headline and family baselines must
run BEFORE the redundant re-sweep; without a tuning file the sweep
stays ahead of the families. These tests pin that ordering by stubbing
the per-step subprocess runner — no jax, no subprocesses.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import scripts.hw_session as hs  # noqa: E402

TUNING = os.path.join(hs.REPO, "elasticdl_tpu", "ops",
                      "flash_tuning.json")


def _run_session(monkeypatch, tmp_path, tuned_exists,
                 prelim_platform="tpu"):
    calls = []

    def fake_run(cmd, timeout, env_extra=None, tag="", base_env=None):
        calls.append(tag)
        if tag == "probe":
            out = "PROBE_OK axon [FakeTpu]"
        elif tag == "bench_flagship_prelim":
            out = json.dumps({"metric": "m", "value": 1.0, "unit": "u",
                              "platform": prelim_platform})
        else:
            out = ""
        return {"tag": tag, "cmd": cmd, "rc": 0, "secs": 0.0,
                "stdout": out, "stderr": ""}

    monkeypatch.setattr(hs, "run", fake_run)
    # the baseline policy must never see the fake run records (it
    # would treat the toy identity as a config change and persist it)
    monkeypatch.setattr(hs.bench_mod, "_maybe_persist_baseline",
                        lambda *a, **k: None)
    monkeypatch.setattr(hs.bench_mod, "_baseline_path",
                        lambda fam="transformer":
                        str(tmp_path / ("b_%s.json" % fam)))
    real_exists = os.path.exists

    def fake_exists(path):
        if os.path.abspath(path) == os.path.abspath(TUNING):
            return tuned_exists
        return real_exists(path)

    monkeypatch.setattr(hs.os.path, "exists", fake_exists)
    monkeypatch.setattr(sys, "argv", [
        "hw_session.py", "--out", str(tmp_path / "out.json")])
    assert hs.main() == 0
    assert json.load(open(tmp_path / "out.json"))["steps"]
    return calls


@pytest.mark.parametrize("tuned_exists", [True, False])
def test_family_benches_vs_sweep_order(monkeypatch, tmp_path,
                                       tuned_exists):
    calls = _run_session(monkeypatch, tmp_path, tuned_exists)
    # invariants of every session
    assert calls[0] == "probe"
    assert calls.index("bench_flagship_prelim") < calls.index(
        "attention_sweep")
    sweep = calls.index("attention_sweep")
    families = [calls.index("bench_%s" % m) for m in
                ("resnet50", "vit", "deepfm", "decode", "dlrm", "bert",
                 "moe")]
    if tuned_exists:
        # tuned prelim already measured the headline: families beat
        # the re-sweep to the (short) window
        assert max(families) < sweep, calls
    else:
        # no tuned default yet: the sweep IS the highest-value step
        # after the insurance prelim
        assert sweep < min(families), calls
    # family benches run exactly once either way
    assert len([c for c in calls if c.startswith("bench_")]) == len(
        set(c for c in calls if c.startswith("bench_")))


def test_flagship_affecting_abs_precede_decode_abs(monkeypatch,
                                                   tmp_path):
    calls = _run_session(monkeypatch, tmp_path, True)
    for early in ("condmask_flagship", "fused_head_flagship",
                  "remat_dots_batch64", "gqa2_flagship"):
        assert calls.index(early) < calls.index("decode_gqa2"), calls


def test_cpu_fallback_prelim_keeps_flagship_first(monkeypatch,
                                                  tmp_path):
    """A tuned session whose prelim fell back to CPU (tunnel wedged
    right after the probe) must NOT spend the next contact window on
    seven family benches before step-3's flagship re-try."""
    calls = _run_session(monkeypatch, tmp_path, True,
                         prelim_platform="cpu")
    sweep = calls.index("attention_sweep")
    assert sweep < calls.index("bench_resnet50"), calls
