#!/usr/bin/env python
"""Microbench pin: the paged blockwise INT8 scan vs the dense
deferred-dequantize int8 decode step.

BENCHNOTES round 6 explained the residual offline `decode_kv_int8`
gap (int8 ~0.85-0.95x fp after the deferred-dequantize fix: the
per-step int8->f32 cast feeding the score matmul plus the two [*, L]
scale multiplies). This PR folds the SAME deferral into the paged
pool's streaming scan (ops.attention.paged_decode_attention: k-scales
into the per-block score tile, v-scales into the weights), and this
bench pins that the blockwise formulation does not REGRESS the dense
deferred path — the scan adds block bookkeeping (table gather, online
softmax merges) but the dequantize work per cache row is identical.

Timed legs over the SAME logical cache (one decode step,
steady-state, jit-compiled):

  dense_deferred_int8  the model's dense int8 decode attention
                       (transformer_lm._decode_step shape): one
                       [*, L] score softmax with scales folded in
  paged_int8           paged_decode_attention over int8 block arenas
                       with the deferred scan (use_kernel=False)
  paged_fp             the same scan over fp arenas (the int8 delta
                       WITHIN the paged formulation)
  fused_int8/fused_fp  the FUSED Pallas kernel (use_kernel=True) on
                       the same arenas — the PR 18 leg. On TPU this
                       is the streaming VMEM kernel and the
                       acceptance number is fused_int8_vs_dense
                       <= 1.0; off-TPU the kernel INTERPRETS
                       (fused_interpreted=true in the record), which
                       checks the path end to end but times the
                       Pallas interpreter, not Mosaic — interpreted
                       ratios are reported for trajectory only.
  tile_*               the verify-k [b, h, t, d] variants of all four
                       paged legs (t = --verify_k: the speculative
                       verify tile / suffix-prefill shape)

Emits one JSON line; `--out` also writes it to a file. Defaults are
CPU-smoke sized; on hardware raise --seq_len/--batch and the dims.

Usage: python scripts/bench_int8_scan.py [--iters 50]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv_heads", type=int, default=0,
                   help="0 = --heads (MHA)")
    p.add_argument("--head_dim", type=int, default=64)
    p.add_argument("--seq_len", type=int, default=512)
    p.add_argument("--block_size", type=int, default=16)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--verify_k", type=int, default=4,
                   help="query-tile rows for the tile_* legs")
    p.add_argument("--fused_iters", type=int, default=0,
                   help="iters for the fused legs; 0 = --iters on "
                        "TPU, min(--iters, 10) when the kernel can "
                        "only run interpreted (the interpreter is "
                        "~100x XLA, full iters would dominate the "
                        "bench wall clock)")
    p.add_argument("--no-fused", dest="fused", action="store_false",
                   help="skip the fused-kernel legs (pre-PR-18 "
                        "record shape)")
    p.add_argument("--out", default="")
    return p.parse_args(argv)


def time_fn(fn, args, iters):
    """Steady-state per-call seconds: one warm call pays the compile,
    then `iters` dispatches with a single block at the end (the async
    dispatch overhead amortizes exactly like the serving step loop)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    args = parse_args(argv)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.ops.attention import paged_decode_attention

    b, h, d = args.batch, args.heads, args.head_dim
    hkv = args.kv_heads or h
    L, bs = args.seq_len, args.block_size
    if L % bs:
        raise SystemExit("seq_len must be a multiple of block_size")
    group = h // hkv
    rs = np.random.RandomState(0)

    def q8(rows):
        amax = np.abs(rows).max(-1, keepdims=True)
        sc = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        return (np.clip(np.round(rows / sc), -127, 127)
                .astype(np.int8), sc)

    # one logical cache, three physical layouts
    kf = rs.randn(b, hkv, L, d).astype(np.float32)
    vf = rs.randn(b, hkv, L, d).astype(np.float32)
    k8, ks = q8(kf)
    v8, vs = q8(vf)
    q = rs.randn(b, h, d).astype(np.float32)
    kc = rs.randn(b, hkv, 1, d).astype(np.float32)
    vc = rs.randn(b, hkv, 1, d).astype(np.float32)
    kc8, kcs = q8(kc)
    vc8, vcs = q8(vc)
    length = np.full((b,), L, np.int32)

    # ---- dense deferred int8 (the offline decode_kv_int8 shape)
    @jax.jit
    def dense_deferred(qx, ck, csk, cv, csv):
        qg = (qx * d ** -0.5).reshape(b, hkv, group, 1, d)
        s = jnp.einsum(
            "bhgtd,bhkd->bhgtk", qg, ck.astype(jnp.float32)
        ) * csk[..., 0][:, :, None, None]
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bhgtk,bhkd->bhgtd",
            w * csv[..., 0][:, :, None, None],
            cv.astype(jnp.float32),
        )

    # ---- paged layouts: b private chains through shared arenas
    nb = b * (L // bs)
    table = np.arange(nb, dtype=np.int32).reshape(b, L // bs)
    k_pool8 = np.zeros((nb, bs, hkv, d), np.int8)
    v_pool8 = np.zeros((nb, bs, hkv, d), np.int8)
    ks_pool = np.zeros((nb, bs, hkv, 1), np.float32)
    vs_pool = np.zeros((nb, bs, hkv, 1), np.float32)
    k_poolf = np.zeros((nb, bs, hkv, d), np.float32)
    v_poolf = np.zeros((nb, bs, hkv, d), np.float32)
    for i in range(b):
        for j in range(L // bs):
            rows = slice(j * bs, (j + 1) * bs)
            bid = table[i, j]
            k_pool8[bid] = k8[i, :, rows].transpose(1, 0, 2)
            v_pool8[bid] = v8[i, :, rows].transpose(1, 0, 2)
            ks_pool[bid] = ks[i, :, rows].transpose(1, 0, 2)
            vs_pool[bid] = vs[i, :, rows].transpose(1, 0, 2)
            k_poolf[bid] = kf[i, :, rows].transpose(1, 0, 2)
            v_poolf[bid] = vf[i, :, rows].transpose(1, 0, 2)

    def paged_call(kernel):
        def call_int8(*a):
            return paged_decode_attention(
                a[0], a[1], a[2], a[3], a[4], a[5], a[6],
                k_scale_pool=a[7], v_scale_pool=a[8],
                k_cur_scale=a[9], v_cur_scale=a[10],
                use_kernel=kernel,
            )
        def call_fp(*a):
            return paged_decode_attention(*a, use_kernel=kernel)
        return jax.jit(call_int8), jax.jit(call_fp)

    scan_int8, scan_fp_fn = paged_call(False)
    fused_int8_fn, fused_fp_fn = paged_call(True)

    int8_args = (
        jnp.asarray(q), jnp.asarray(kc8[:, :, 0]),
        jnp.asarray(vc8[:, :, 0]), jnp.asarray(k_pool8),
        jnp.asarray(v_pool8), jnp.asarray(table),
        jnp.asarray(length), jnp.asarray(ks_pool),
        jnp.asarray(vs_pool), jnp.asarray(kcs[:, :, 0]),
        jnp.asarray(vcs[:, :, 0]),
    )
    fp_args = (
        jnp.asarray(q), jnp.asarray(kc[:, :, 0]),
        jnp.asarray(vc[:, :, 0]), jnp.asarray(k_poolf),
        jnp.asarray(v_poolf), jnp.asarray(table),
        jnp.asarray(length),
    )
    # the verify-k tile ([b, h, t, d]): same cache, t query rows
    t = args.verify_k
    q_t = rs.randn(b, h, t, d).astype(np.float32)
    kct = rs.randn(b, hkv, t, d).astype(np.float32)
    vct = rs.randn(b, hkv, t, d).astype(np.float32)
    kct8, kcts = q8(kct)
    vct8, vcts = q8(vct)
    tile_int8_args = (
        jnp.asarray(q_t), jnp.asarray(kct8), jnp.asarray(vct8),
        jnp.asarray(k_pool8), jnp.asarray(v_pool8),
        jnp.asarray(table), jnp.asarray(length),
        jnp.asarray(ks_pool), jnp.asarray(vs_pool),
        jnp.asarray(kcts), jnp.asarray(vcts),
    )
    tile_fp_args = (
        jnp.asarray(q_t), jnp.asarray(kct), jnp.asarray(vct),
        jnp.asarray(k_poolf), jnp.asarray(v_poolf),
        jnp.asarray(table), jnp.asarray(length),
    )

    dense_s = time_fn(
        dense_deferred,
        (jnp.asarray(q), jnp.asarray(k8), jnp.asarray(ks),
         jnp.asarray(v8), jnp.asarray(vs)),
        args.iters,
    )
    i8_s = time_fn(scan_int8, int8_args, args.iters)
    fp_s = time_fn(scan_fp_fn, fp_args, args.iters)
    tile_i8_s = time_fn(scan_int8, tile_int8_args, args.iters)
    tile_fp_s = time_fn(scan_fp_fn, tile_fp_args, args.iters)
    record = {
        "metric": "paged_int8_scan_vs_dense_deferred",
        "platform": jax.default_backend(),
        "batch": b, "heads": h, "kv_heads": hkv, "head_dim": d,
        "seq_len": L, "block_size": bs, "iters": args.iters,
        "verify_k": t,
        "dense_deferred_int8_us": round(dense_s * 1e6, 1),
        "paged_int8_us": round(i8_s * 1e6, 1),
        "paged_fp_us": round(fp_s * 1e6, 1),
        "tile_paged_int8_us": round(tile_i8_s * 1e6, 1),
        "tile_paged_fp_us": round(tile_fp_s * 1e6, 1),
        # the pin: the blockwise deferral vs the dense deferral
        "paged_int8_vs_dense_deferred": round(i8_s / dense_s, 3),
        # the int8 cost WITHIN the paged formulation
        "paged_int8_vs_paged_fp": round(i8_s / fp_s, 3),
    }
    if args.fused:
        from elasticdl_tpu.ops.dispatch import interpret_mode

        interpreted = interpret_mode()
        fi = args.fused_iters or (
            min(args.iters, 10) if interpreted else args.iters
        )
        f8_s = time_fn(fused_int8_fn, int8_args, fi)
        ffp_s = time_fn(fused_fp_fn, fp_args, fi)
        tile_f8_s = time_fn(fused_int8_fn, tile_int8_args, fi)
        tile_ffp_s = time_fn(fused_fp_fn, tile_fp_args, fi)
        record.update({
            "fused_interpreted": interpreted,
            "fused_iters": fi,
            "fused_int8_us": round(f8_s * 1e6, 1),
            "fused_fp_us": round(ffp_s * 1e6, 1),
            "tile_fused_int8_us": round(tile_f8_s * 1e6, 1),
            "tile_fused_fp_us": round(tile_ffp_s * 1e6, 1),
            # the PR 18 acceptance number (meaningful on TPU; the
            # interpreter's python-loop timings only track trajectory)
            "fused_int8_vs_dense_deferred": round(f8_s / dense_s, 3),
            "fused_int8_vs_paged_int8": round(f8_s / i8_s, 3),
            "fused_fp_vs_paged_fp": round(ffp_s / fp_s, 3),
            "tile_fused_int8_vs_tile_paged_int8":
                round(tile_f8_s / tile_i8_s, 3),
        })
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
