#!/usr/bin/env bash
# Install the repo's git hooks: a pre-commit hook that runs
# `make lint-changed` (edl-lint --changed-only over the files of the
# commit — sub-second on typical diffs; full-tree enforcement stays in
# CI, where stale-baseline and unused-pragma policing need the whole
# tree). Bypass a single commit with `git commit --no-verify`.
#
# Usage: bash scripts/install-hooks.sh
set -euo pipefail

repo_root="$(git rev-parse --show-toplevel)"
hooks_dir="$(git -C "$repo_root" rev-parse --git-path hooks)"
hook="$hooks_dir/pre-commit"

if [ -e "$hook" ] && ! grep -q "edl-lint pre-commit" "$hook"; then
    echo "install-hooks: $hook exists and is not ours; not overwriting" >&2
    exit 1
fi

mkdir -p "$hooks_dir"
cat > "$hook" <<'EOF'
#!/usr/bin/env sh
# edl-lint pre-commit hook (installed by scripts/install-hooks.sh).
# Lints only the files changed vs the merge base plus untracked ones;
# skip once with --no-verify.
cd "$(git rev-parse --show-toplevel)" && make lint-changed
EOF
chmod +x "$hook"
echo "install-hooks: installed $hook (runs 'make lint-changed')"
