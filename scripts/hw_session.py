"""One-shot hardware measurement session: run EVERY pending TPU
measurement the moment the tunnel is up, saving results incrementally so
even a short window is fully exploited (the tunnel flaps: up for
minutes-to-hours, then wedged — see BENCHNOTES.md).

    python scripts/hw_session.py [--out hw_session_results.json]

Steps (each in its own bounded subprocess; a hang or crash moves on).
Value-ordered for minutes-long tunnel windows — on a session where a
prior sweep already persisted tuned blocks (flash_tuning.json), the
prelim IS the tuned headline and the family benches run BEFORE the
re-sweep:
  1. probe             — bounded accelerator init; abort if wedged
  1b. flagship prelim  — python bench.py at current tuned defaults;
                         on a tuned session this refreshes
                         BENCH_BASELINE.json immediately
  [tuned sessions only] family benches jump here (see 4./5.)
  2. attention sweep   — scripts/bench_attention.py block-size sweep;
                         the best (block_q, block_k) is persisted to
                         elasticdl_tpu/ops/flash_tuning.json (the
                         repo-wide tuned default) when it beats 128/128
  3. flagship bench    — re-run under the (re-)tuned blocks
  4./5. family benches — EDL_BENCH_MODEL=resnet50|vit|deepfm|decode|dlrm|bert|moe
                         (BASELINE.md targets + decode throughput +
                         the 1B-embedding DLRM stress config)
  5b. pipeline A/B     — gpipe vs interleaved on the virtual CPU mesh
  6. profile           — scripts/profile_step.py (attention share)
  6b. collectives      — gradient-plane all-reduce bandwidth
  7. model-knob A/Bs   — AB_QUEUE, headline-impact first (condmask,
                         fused head, remat, GQA), then the decode
                         family story, then diagnostics

Everything lands in --out (JSON, appended after each step) plus the raw
logs next to it; BENCH_BASELINE.json is updated ONLY when the flagship
run beats the committed baseline on the same config.
"""

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench as bench_mod  # noqa: E402  (the ONE baseline policy)


def run(cmd, timeout, env_extra=None, tag="", base_env=None):
    env = dict(os.environ)
    # the session is a controlled measurement: ambient bench/kernel
    # knobs left exported in the operator's shell (EDL_BENCH_MODEL,
    # EDL_BENCH_BATCH, EDL_FLASH_BLOCK_Q, ...) must not contaminate
    # steps — each step declares its own via env_extra
    for key in [k for k in env
                if k.startswith(("EDL_BENCH_", "EDL_FLASH_"))]:
        del env[key]
    # shared persistent compile cache: repeated configs across steps
    # (flagship prelim -> tuned re-run, A/B sweeps) skip their 20-40 s
    # compiles, so a short tunnel window yields more measurements.
    # Skipped for CPU-pinned children (--force dry runs): XLA:CPU AOT
    # cache entries carry host machine features and can SIGILL when
    # loaded under a different feature set (see bench.py's guard).
    if (base_env or {}).get("JAX_PLATFORMS") != "cpu":
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       os.path.join(REPO, ".jax_cache"))
    env.update(base_env or {})
    env.update(env_extra or {})
    t0 = time.time()
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            cwd=REPO, env=env,
        )
        return {
            "tag": tag, "cmd": cmd, "rc": r.returncode,
            "secs": round(time.time() - t0, 1),
            "stdout": r.stdout[-20000:], "stderr": r.stderr[-4000:],
        }
    except subprocess.TimeoutExpired:
        return {"tag": tag, "cmd": cmd, "rc": -1, "timeout": timeout,
                "secs": round(time.time() - t0, 1),
                "stdout": "", "stderr": "TIMEOUT"}


def save(results, out_path):
    # coverage summary the probe loop's exit gate reads: how many
    # results landed on the chip vs how many the session could
    # produce (prelim + flagship + collectives + FAMILIES +
    # AB_QUEUE; profile/pipeline never emit TPU JSON). The target is
    # DERIVED from the actual step rosters, so editing FAMILIES or
    # AB_QUEUE can never desynchronize the loop's exit threshold.
    results["tpu_measured"] = sum(
        1 for v in results.values()
        if isinstance(v, dict) and v.get("platform") not in (None, "cpu")
    )
    results["tpu_target"] = 3 + len(FAMILIES) + len(AB_QUEUE)
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)


def last_json_line(text):
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def parse_sweep(stdout):
    """bench_attention lines:
    'flash bq=.. bk=..            fwd  x ms (...)   fwd+bwd  y ms'
    Returns [(bq, bk, fwd_ms, fwdbwd_ms)]."""
    rows = []
    pat = re.compile(
        r"bq=(\d+)\s+bk=(\d+).*?fwd\s+([\d.]+)\s*ms.*?fwd\+bwd\s+"
        r"([\d.]+)\s*ms"
    )
    for line in (stdout or "").splitlines():
        m = pat.search(line)
        if m:
            rows.append((int(m.group(1)), int(m.group(2)),
                         float(m.group(3)), float(m.group(4))))
    return rows


# Secondary bench families (BASELINE.md targets + decode throughput +
# the 1B-embedding DLRM stress config). Module-level so save()'s
# coverage target derives from the same roster family_benches() runs.
FAMILIES = ("resnet50", "vit", "deepfm", "decode", "dlrm", "bert", "moe")


# Model-knob A/Bs. Ordered by headline impact: knobs that could
# RAISE the flagship number run first (a short tunnel window should
# die holding the most valuable unmeasured comparison), then the
# decode family story, then comparison/diagnostic points.
AB_QUEUE = (
        # branch the per-element causal mask out of interior blocks
        # (lax.cond in-kernel) — wins only if Mosaic pipelines across
        # the branch; falls back to the default straight-line select
        # if this step regresses or fails to lower
        ("condmask_flagship", {"EDL_FLASH_COND_MASK": "1"}),
        ("fused_head_flagship", {"EDL_BENCH_EXTRA_PARAMS":
                                       "fused_head=True"}),
        # per-block remat frees activation HBM -> bigger global batch,
        # bigger MXU tiles; 'dots' keeps matmul outputs (cheaper bwd).
        # Compare tokens/sec against the plain flagship: remat wins
        # exactly when the freed memory converts to throughput
        ("remat_dots_batch64", {"EDL_BENCH_EXTRA_PARAMS":
                                      "remat='dots'",
                                      "EDL_BENCH_BATCH": "64"}),
        ("gqa2_flagship", {"EDL_BENCH_EXTRA_PARAMS":
                                 "num_kv_heads=2"}),
        ("jax_flash_flagship", {"EDL_BENCH_EXTRA_PARAMS":
                                "attn_impl='jax_flash'"}),
        ("baseline_seq2048", {"EDL_BENCH_EXTRA_PARAMS": "seq_len=2048",
                              "EDL_BENCH_BATCH": "16"}),
        ("fused_head_seq2048", {"EDL_BENCH_EXTRA_PARAMS":
                                "fused_head=True; seq_len=2048",
                                "EDL_BENCH_BATCH": "16"}),
        # GQA decode A/B: 8 -> 2 kv heads = 4x smaller KV cache; decode
        # is cache-bandwidth-bound, so this measures the GQA win
        ("decode_gqa2", {"EDL_BENCH_MODEL": "decode",
                         "EDL_BENCH_EXTRA_PARAMS": "num_kv_heads=2"}),
        # batched-prefill regime: long prompt, short continuation — the
        # prefill collapses 512 single-token steps into one causal pass
        ("decode_longprompt", {"EDL_BENCH_MODEL": "decode",
                               "EDL_BENCH_EXTRA_PARAMS":
                               "prompt=512; new_tokens=128"}),
        # weight-only int8 decode: weights travel HBM->VMEM as int8
        # (dequant fused into the matmuls); vs the bf16 decode target
        ("decode_int8", {"EDL_BENCH_MODEL": "decode",
                         "EDL_BENCH_EXTRA_PARAMS": "quantize=1"}),
        # int8 KV cache: the decode path's dominant HBM stream (the
        # per-token cache re-read) halves vs bf16; combines with
        # weight int8 for the full bandwidth story
        ("decode_kv_int8", {"EDL_BENCH_MODEL": "decode",
                            "EDL_BENCH_EXTRA_PARAMS":
                            "kv_cache_dtype='int8'"}),
        ("decode_kv_plus_w_int8",
         {"EDL_BENCH_MODEL": "decode",
          "EDL_BENCH_EXTRA_PARAMS":
          "kv_cache_dtype='int8'; quantize=1"}),
        # KV-cached beam search: per-step cache gathers at width 4
        ("decode_beam4", {"EDL_BENCH_MODEL": "decode",
                          "EDL_BENCH_EXTRA_PARAMS": "beams=4"}),
        # speculative decode mechanics: ceiling (target drafts itself,
        # ~100% acceptance) and floor (random 2-layer draft)
        ("decode_spec_ceiling",
         {"EDL_BENCH_MODEL": "decode",
          "EDL_BENCH_EXTRA_PARAMS": "spec_gamma=4; spec_draft_layers=0"}),
        ("decode_spec_draft2",
         {"EDL_BENCH_MODEL": "decode",
          "EDL_BENCH_EXTRA_PARAMS": "spec_gamma=4"}),
        # trained draft (api/distill.py): warm-start + 200 KL steps on
        # the target's logits; acceptance + tokens/sec land in
        # extra_params — the real-speedup story between floor and
        # ceiling
        ("decode_spec_trained",
         {"EDL_BENCH_MODEL": "decode",
          "EDL_BENCH_EXTRA_PARAMS":
          "spec_gamma=4; spec_draft_layers=1; "
          "spec_draft_train_steps=200"}),
        ("remat_full_batch64", {"EDL_BENCH_EXTRA_PARAMS":
                                "remat='full'",
                                "EDL_BENCH_BATCH": "64"}),
        # MoE decode dispatch: dense runs EVERY expert over all tokens
        # (determinism baseline), gather is the sorted ragged_dot
        # drop-free path at k/E of the FLOPs — back-to-back so the
        # pair shares a window
        ("decode_moe_dense", {"EDL_BENCH_MODEL": "decode",
                              "EDL_BENCH_EXTRA_PARAMS": "moe=1"}),
        ("decode_moe_gather", {"EDL_BENCH_MODEL": "decode",
                               "EDL_BENCH_EXTRA_PARAMS":
                               "moe=1; moe_infer_impl='gather'"}),
        # sequence-packing overhead: same shapes, 4 segments per row
        # through the kernels' segment masks (vs the plain flagship)
        ("packed4_flagship", {"EDL_BENCH_EXTRA_PARAMS": "packed=4"}),
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, "hw_session_results.json"))
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--force", action="store_true",
                    help="continue past a failed probe (CPU dry-run of "
                         "the orchestration; benches fall back to CPU)")
    ap.add_argument("--sweep-shape", default="",
                    help="b h s d override for bench_attention (dry-run)")
    args = ap.parse_args()
    results = {"started": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                        time.gmtime()),
               "steps": []}
    # --force dry-run: pin every child to CPU and drop the tunnel
    # plugin's sitecustomize (a wedged tunnel hangs ANY ambient-env
    # python at backend init)
    dry_env = (
        {"PYTHONPATH": "", "JAX_PLATFORMS": "cpu"} if args.force else {}
    )

    def runner(cmd, timeout, env_extra=None, tag=""):
        return run(cmd, timeout, env_extra=env_extra, tag=tag,
                   base_env=dry_env)

    def record(step):
        results["steps"].append(step)
        save(results, args.out)
        print("[hw_session] %s rc=%s (%.0fs)" % (
            step.get("tag"), step.get("rc"), step.get("secs", 0)),
            flush=True)

    # 1. probe
    probe = runner(
        [sys.executable, "-c",
         "import jax, jax.numpy as jnp;"
         "x = jnp.ones((256, 256), jnp.bfloat16);"
         "(x @ x).block_until_ready();"
         "print('PROBE_OK', jax.default_backend(), jax.devices())"],
        timeout=120, tag="probe",
    )
    record(probe)
    probe_words = (probe["stdout"].split() + ["", ""])[:3]
    on_tpu = probe_words[0] == "PROBE_OK" and probe_words[1] != "cpu"
    if "PROBE_OK" not in probe["stdout"]:
        if not args.force:
            print("[hw_session] tunnel wedged; aborting")
            return 1
        print("[hw_session] probe failed but --force: continuing (CPU)")

    def maybe_update_baseline(cand, note="", family="transformer"):
        """Refresh the family's BENCH_BASELINE*.json via bench.py's
        _maybe_persist_baseline — ONE policy owns these files (update
        when no comparable record / identity changed / same-identity
        value improved; refuse A/B or ambient-knob runs, whose
        extra_params differ from the family default None)."""
        if not cand:
            return
        path = bench_mod._baseline_path(family)
        try:
            with open(path) as f:
                before = f.read()
        except OSError:
            before = None
        bench_mod._maybe_persist_baseline(family, cand)
        try:
            with open(path) as f:
                after = f.read()
        except OSError:
            after = before
        if after != before:
            print("[hw_session] %s updated%s"
                  % (os.path.basename(path),
                     " (%s)" % note if note else ""))

    def flagship_bench(tag, update_baseline):
        """Run the flagship bench and return the parsed JSON line.

        update_baseline=False for an UNTUNED session's prelim: there
        it is pre-sweep insurance only, and refreshing the baseline
        would make the post-sweep step-3 run compute vs_baseline
        against this same session's prelim instead of the prior
        round's committed number. On a TUNED session the prelim runs
        the tuned defaults — it IS the headline, persists immediately
        (update_baseline=True), and step-3 becomes a confirmation A/B
        against it by design (maybe_update_baseline only lets a
        strictly better value through)."""
        # bench.py's bare default is now the full family suite; every
        # hw_session step pins exactly one family
        bench = runner([sys.executable, "bench.py"], timeout=1800,
                       env_extra={"EDL_BENCH_MODEL": "transformer",
                                  "EDL_BENCH_PROBE_TIMEOUT": "150"},
                       tag=tag)
        record(bench)
        flag = last_json_line(bench["stdout"])
        if flag and update_baseline:
            maybe_update_baseline(flag)
        return flag

    def family_benches():
        for model in FAMILIES:
            step = runner([sys.executable, "bench.py"], timeout=1800,
                          env_extra={"EDL_BENCH_MODEL": model,
                                     "EDL_BENCH_PROBE_TIMEOUT": "150"},
                          tag="bench_%s" % model)
            record(step)
            parsed = last_json_line(step["stdout"])
            if parsed and parsed.get("platform") not in (None, "cpu"):
                results[model] = parsed
                save(results, args.out)
                maybe_update_baseline(parsed, family=model)

    # A prior session's sweep already tuned the flash blocks? Then the
    # prelim below IS the tuned flagship run, and the most valuable
    # thing a short window can add after it is family baselines — so
    # the family loop moves AHEAD of the (redundant-ish) re-sweep.
    # Observed window pattern: minutes-long (2026-08-01 contact lasted
    # ~5 min — prelim + sweep fit, nothing after did).
    tuned_at_start = os.path.exists(os.path.join(
        REPO, "elasticdl_tpu", "ops", "flash_tuning.json"))

    # 1b. flagship insurance pass BEFORE the (up to 30 min) sweep: the
    # tunnel's windows can be minutes long, and the round's headline
    # number must not be hostage to the sweep finishing. Current tuned
    # defaults are already in flash_tuning.json if a prior session swept.
    prelim = None
    if on_tpu and not args.skip_sweep:
        # with --skip-sweep nothing changes between here and step 3, so
        # the insurance pass would just duplicate the flagship run
        prelim = flagship_bench("bench_flagship_prelim",
                                update_baseline=tuned_at_start)
        if prelim:
            results["flagship_prelim"] = prelim
            save(results, args.out)

    # families jump the re-sweep ONLY once a flagship headline is in
    # hand on this chip (tuned prelim measured on tpu) — with
    # --skip-sweep or a crashed/CPU-fallback prelim, step-3 must stay
    # the next flagship chance ahead of six 30-min-bounded family runs
    if (tuned_at_start and on_tpu and prelim
            and prelim.get("platform") not in (None, "cpu")):
        family_benches()
        families_ran = True
    else:
        families_ran = False

    # 2. attention block sweep -> persist tuned default
    if not args.skip_sweep:
        sweep_cmd = [sys.executable, "scripts/bench_attention.py"]
        if args.sweep_shape:
            sweep_cmd += args.sweep_shape.split()
        sweep = runner(sweep_cmd, timeout=1800, tag="attention_sweep")
        record(sweep)
        rows = parse_sweep(sweep["stdout"])
        if rows:
            best = min(rows, key=lambda r: r[3])
            base = [r for r in rows if r[0] == 128 and r[1] == 128]
            results["sweep_best"] = {
                "block_q": best[0], "block_k": best[1],
                "fwd_bwd_ms": best[3],
                "base_128_fwd_bwd_ms": base[0][3] if base else None,
                "shape": args.sweep_shape or "flagship default",
                "on_tpu": on_tpu,
            }
            # persist ONLY real-TPU timings at the flagship shape —
            # CPU-interpret numbers or a non-flagship --sweep-shape
            # must never become the repo-wide tuned default
            persist_ok = on_tpu and not args.sweep_shape
            if persist_ok and base and best[3] < base[0][3] * 0.99:
                tuning = os.path.join(
                    REPO, "elasticdl_tpu", "ops", "flash_tuning.json")
                with open(tuning, "w") as f:
                    json.dump({"block_q": best[0], "block_k": best[1],
                               "tuned_on": "v5e flagship sweep"}, f)
                print("[hw_session] tuned blocks -> %s" % (best[:2],))
            save(results, args.out)

    # 3. flagship bench (tuned defaults now in effect via tuning file)
    flag = flagship_bench("bench_flagship", update_baseline=True)
    if flag:
        results["flagship"] = flag
        save(results, args.out)
    # the sweep can regress (tuned blocks persist only when strictly
    # better, but noise happens): if the prelim pass beat the tuned run,
    # let it refresh the committed baseline instead. A CPU-fallback
    # step-3 result (tunnel wedged mid-session) counts as "no tuned
    # run" — its toy-config value must not gate the prelim TPU number.
    flag_tpu = flag if flag and flag.get("platform") not in (
        None, "cpu") else None
    if prelim and (not flag_tpu or prelim.get("value", 0)
                   > flag_tpu.get("value", 0)):
        maybe_update_baseline(prelim, note="prelim")

    # 4./5. family benches (already ran pre-sweep on a tuned session)
    if not families_ran:
        family_benches()

    # 5b. pipeline-schedule A/B (gpipe vs interleaved) — inherently
    # multichip, so it runs on the 8-device VIRTUAL cpu mesh in a
    # CPU-pinned child even during a TPU session (single-chip pp=1
    # can't exercise the schedules; an oversubscribed virtual mesh's
    # wall-clock tracks exactly the stage-work the bubble shrink saves)
    pipe = run(
        [sys.executable, "scripts/bench_pipeline.py"], timeout=1800,
        tag="pipeline_schedules",
        base_env={"PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
                  "XLA_FLAGS":
                  "--xla_force_host_platform_device_count=8"},
    )
    record(pipe)
    parsed = last_json_line(pipe["stdout"])
    if parsed:
        results["pipeline_schedules"] = parsed
        save(results, args.out)

    # 6. step profile (attention share of step time)
    prof = runner([sys.executable, "scripts/profile_step.py"],
               timeout=1800, tag="profile_step")
    record(prof)

    # 6b. gradient-plane collective bandwidth (BASELINE.md target;
    # single-chip reports the HBM-degenerate number, multi-chip the
    # ICI all-reduce figure)
    coll = runner([sys.executable, "scripts/bench_collectives.py"],
                  timeout=900, tag="collectives")
    record(coll)
    parsed = last_json_line(coll["stdout"])
    if parsed and parsed.get("platform") not in (None, "cpu"):
        results["collectives"] = parsed
        save(results, args.out)

    # 7. model-knob A/Bs (AB_QUEUE, module level: the coverage target
    # in save() counts it)
    for tag, extra in AB_QUEUE:
        # copy: AB_QUEUE is module state shared across main() calls
        extra = dict(extra)
        extra["EDL_BENCH_PROBE_TIMEOUT"] = "150"
        # bare default is the whole suite now — A/Bs without an
        # explicit family run the flagship transformer
        extra.setdefault("EDL_BENCH_MODEL", "transformer")
        step = runner([sys.executable, "bench.py"], timeout=1800,
                   env_extra=extra, tag=tag)
        record(step)
        parsed = last_json_line(step["stdout"])
        if parsed:
            results[tag] = parsed
            save(results, args.out)

    print("[hw_session] complete -> %s" % args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
