#!/usr/bin/env python
"""Runtime-health STALL drill: a wedged scheduler is self-reported,
bundled, and replaced in seconds — not the 30 s lease heuristic.

Runs the REAL stack: an in-process Router (real gRPC transport) whose
two-replica fleet is owned by the replica supervisor
(serving/autoscaler.py), spawning `elasticdl_tpu.serving.main`
subprocesses. The FIRST replica is armed with an `engine_step` delay
fault (common/fault_injection.py HEALTH_RPCS) injected through the
environment only that seat sees — after a few healthy decode ticks its
scheduler thread goes to sleep for 600 s mid-loop with work SEATED:
the exact silent-wedge failure mode the progress watchdog
(observability/runtime_health.py) exists to catch. Replacement seats
get a clean environment, so the drill converges.

What must then happen, and what the drill asserts:

  * DETECTION — the replica's own watchdog (its own thread; the gRPC
    status path, NOT the wedged scheduler) declares `stalled` within
    its `--stall_after_secs` budget and self-reports through
    ServerStatus -> ReplicaStatus `health_state` /
    `last_progress_age_ms`. Detection latency is measured from the
    stalling request's dispatch and must come in FAR under the 30 s
    `wedged_after_secs` lease heuristic (which stays at its
    conservative default here — the point is to beat it, not to tune
    it away). The router also drops the stalled replica from its
    dispatch rotation.

  * FLIGHT RECORDER — the ok->stalled transition atomically dumps a
    diagnostic bundle to $EDL_HEALTH_DIR: all-thread stacks
    (faulthandler — the sleeping scheduler is VISIBLE in them), the
    per-tick snapshot ring, the two-tier KV ledger, the memory
    accountant's view and the recompile counters. The drill loads it
    back and gates it through `validate_bundle` (schema, stacks
    present, non-empty ring).

  * REPLACEMENT — the supervisor's self-report path
    (`stalled_kill_after_secs`, seconds) kills and replaces the
    replica while its LEASE IS STILL VALID (the gRPC threads renew it
    happily — that is why lease decay alone needs 30 s of deliberate
    conservatism). Time from dispatch to SIGKILL must beat
    `wedged_after_secs`.

  * ZERO ACCEPTED-REQUEST LOSS — the fleet is TWO replicas (one
    armed, one clean), so every request wedged mid-decode on the
    stalled replica re-dispatches to its healthy sibling and
    completes OK while the replacement spawns; post-replacement
    traffic completes OK; every outcome is OK, never a raw transport
    code, never a shed, never a hang.

  * MEMORY ACCOUNTANT — `health_leak:drop:1` is armed on the clean
    sibling: once past its steady boundary its health thread leaks
    one 8 MiB device buffer the byte ledger cannot name, and the next
    reconcile must CONVICT it (ServerStatus
    `memory_unaccounted_bytes` >= the leak).

Timeline + outcomes archive at STALL_DRILL_REPORT.json (repo root).

Usage: python scripts/run_stall_drill.py
Exit 0 = every invariant holds."""

import glob
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the injected stall: after SKIP healthy decode ticks the scheduler
#: sleeps STALL_SECS mid-loop. SKIP outlives the replica's own warmup
#: (4 tokens = 3 decode ticks) so readiness is honest, and lands the
#: wedge inside the drill's long request.
STALL_SPEC = "engine_step:delay:1:secs=600,skip=5"
LEAK_SPEC = "health_leak:drop:1"
LEAK_BYTES = 8 << 20

STALL_AFTER_SECS = 2.0       # the replica watchdog's budget
STALLED_KILL_AFTER_SECS = 1.5  # supervisor's self-report kill budget
WEDGED_AFTER_SECS = 30.0     # the conservative lease heuristic, KEPT

DRILL_MODEL_PARAMS = (
    "vocab_size=32; seq_len=64; embed_dim=32; num_heads=2; "
    "num_layers=1"
)


def replica_args():
    return [
        "--model_zoo", os.path.join(REPO, "model_zoo"),
        "--model_def", "transformer_lm.transformer_lm.custom_model",
        "--model_params", DRILL_MODEL_PARAMS,
        "--port", "0", "--num_slots", "2", "--queue_capacity", "32",
        "--kv_block_size", "4", "--max_workers", "64",
        "--warmup_tokens", "4",
        "--runtime_health", "1",
        "--stall_after_secs", str(STALL_AFTER_SECS),
    ]


def wait_for(cond, timeout, what, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = cond()
        if got:
            return got
        time.sleep(poll)
    raise AssertionError("timed out after %.0fs waiting for %s"
                         % (timeout, what))


def main():
    import tempfile

    from elasticdl_tpu.observability.runtime_health import (
        validate_bundle,
    )
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel
    from elasticdl_tpu.serving.autoscaler import (
        AutoscalerConfig,
        ReplicaSupervisor,
        SubprocessReplicaLauncher,
    )
    from elasticdl_tpu.serving.router import Router, RouterConfig

    tmp_root = tempfile.mkdtemp(prefix="edl_stall_")
    journal_dir = os.path.join(tmp_root, "journal")
    health_dir = os.path.join(tmp_root, "health")
    os.makedirs(health_dir, exist_ok=True)

    base_env = dict(os.environ)
    base_env["JAX_PLATFORMS"] = "cpu"
    base_env["EDL_KV_PAGED"] = "1"
    base_env["EDL_HEALTH_DIR"] = health_dir
    base_env.pop("PYTHONPATH", None)
    base_env.pop("EDL_FAULT_SPEC", None)

    class FaultPerSeatLauncher(SubprocessReplicaLauncher):
        """Seat 0 is born with the stall fault armed; seat 1 (the
        clean sibling that absorbs the re-dispatches) with the
        deliberate post-steady memory leak; later seats (the
        replacement) come up clean — a fleet-wide EDL_FAULT_SPEC
        would stall every replacement forever."""

        SEAT_SPECS = {0: STALL_SPEC, 1: LEAK_SPEC}

        def spawn(self, seat_id):
            env = dict(base_env)
            spec = self.SEAT_SPECS.get(seat_id)
            if spec:
                env["EDL_FAULT_SPEC"] = spec
            self.env = env
            return super().spawn(seat_id)

    launcher = FaultPerSeatLauncher(
        replica_args(), log_dir=os.path.join(tmp_root, "logs"),
        env=base_env, cwd=REPO,
    )
    router = Router([], RouterConfig(
        poll_secs=0.25, poll_timeout_secs=2.0, lease_secs=2.0,
        breaker_cooldown_secs=1.0, redispatch_window_secs=120.0,
        dispatch_timeout_secs=150.0, max_workers=96,
    )).start(grpc_server=True)
    sup = ReplicaSupervisor(router, launcher, AutoscalerConfig(
        min_replicas=2, max_replicas=2, decide_secs=0.25,
        ready_timeout_secs=300.0, drain_timeout_secs=60.0,
        wedged_after_secs=WEDGED_AFTER_SECS,
        stalled_kill_after_secs=STALLED_KILL_AFTER_SECS,
        max_restarts=3, journal_dir=journal_dir,
    ))
    router.set_autoscaler(sup)
    sup.start()
    stub = RouterStub(build_channel("localhost:%d" % router.port))

    outcomes = {}
    lock = threading.Lock()

    def call(tag, max_new, timeout=150.0):
        try:
            stub.router_generate(
                pb.GenerateRequest(prompt=[1, 2, 3],
                                   max_new_tokens=max_new),
                timeout=timeout,
            )
            code = "OK"
        except Exception as e:  # noqa: BLE001 - status is the datum
            code_fn = getattr(e, "code", None)
            code = (code_fn().name if callable(code_fn)
                    else type(e).__name__)
        with lock:
            outcomes[tag] = code

    def fleet():
        return stub.router_status(pb.RouterStatusRequest(),
                                  timeout=20)

    def replica_health():
        try:
            st = fleet()
        except Exception:  # noqa: BLE001 - transient starvation
            return None
        return {r.address: (r.health_state, r.last_progress_age_ms,
                            r.healthy)
                for r in st.replica}

    report = {"timeline": {}, "bounds": {
        "stall_after_secs": STALL_AFTER_SECS,
        "stalled_kill_after_secs": STALLED_KILL_AFTER_SECS,
        "wedged_after_secs": WEDGED_AFTER_SECS,
    }}
    t0 = time.monotonic()

    def stamp(name):
        report["timeline"][name] = round(time.monotonic() - t0, 2)
        print("[stall] %-22s t=%.2fs" % (name, time.monotonic() - t0))

    try:
        # ---- phase 0: both replicas (seat 0 armed with the stall,
        # seat 1 clean) come up and serve
        wait_for(
            lambda: (fleet().autoscaler.live >= 2
                     if _safe(fleet) else False),
            300, "both replicas live",
        )
        stamp("fleet_live")

        # ---- phase 1: a burst of long requests spreads across both
        # replicas (least-loaded + inflight tie-break); seat 0's
        # armed delay fires after skip=5 decode ticks (warmup burned
        # 3), wedging its scheduler with several requests SEATED
        long_reqs = []
        for i in range(6):
            t = threading.Thread(
                target=call, args=("long_%d" % i, 48), daemon=True
            )
            t.start()
            long_reqs.append(t)
        stamp("burst_dispatched")
        t_dispatch = time.monotonic()

        # ---- detection: the replica SELF-REPORTS stalled while its
        # lease stays healthy (the gRPC threads renew it)
        def stalled_rep():
            view = replica_health() or {}
            for addr, (state, age_ms, _healthy) in view.items():
                if state == "stalled":
                    return (addr, age_ms)
            return None

        addr, age_ms = wait_for(
            stalled_rep, WEDGED_AFTER_SECS,
            "the replica to self-report stalled",
        )
        t_detect = time.monotonic()
        stamp("stall_detected")
        detect_secs = t_detect - t_dispatch
        assert detect_secs < WEDGED_AFTER_SECS, (
            "detection took %.1fs — no faster than the lease "
            "heuristic" % detect_secs
        )
        print("[stall] %s self-reported stalled (age %.0fms) after "
              "%.1fs — lease still valid" % (addr, age_ms,
                                             detect_secs))
        # the stalled replica must be OUT of the dispatch rotation
        # while still registered
        view = replica_health()
        assert view and view[addr][2] is False, (
            "stalled replica still marked healthy in router_status"
        )

        # ---- replacement off the self-report, beating the 30 s path
        wait_for(
            lambda: (fleet().autoscaler.replacements >= 1
                     if _safe(fleet) else False),
            WEDGED_AFTER_SECS, "the stalled replica to be killed",
        )
        t_killed = time.monotonic()
        stamp("replica_killed")
        kill_secs = t_killed - t_dispatch
        assert kill_secs < WEDGED_AFTER_SECS, (
            "dispatch->kill took %.1fs; the self-report path must "
            "beat the %.0fs lease heuristic"
            % (kill_secs, WEDGED_AFTER_SECS)
        )
        wait_for(
            lambda: (fleet().autoscaler.live >= 2
                     if _safe(fleet) else False),
            300, "the replacement to go live",
        )
        stamp("replacement_live")

        # ---- the bundle the stalled replica left behind
        def bundle_path():
            paths = glob.glob(
                os.path.join(health_dir, "health-bundle-*.json")
            )
            return paths[0] if paths else None

        path = wait_for(bundle_path, 30, "the diagnostic bundle")
        with open(path) as f:
            bundle = json.load(f)
        problems = validate_bundle(bundle)
        assert not problems, "bundle schema: %s" % problems
        assert bundle["reason"] == "progress_stall"
        assert bundle["ring"], "flight-recorder ring is empty"
        assert "serving-scheduler" in json.dumps(
            bundle["stacks"]
        ) or bundle["stacks"]["faulthandler"], (
            "the wedged scheduler thread is not visible in the stacks"
        )
        report["bundle"] = {
            "path": path,
            "ring_ticks": len(bundle["ring"]),
            "recompiles": bundle["recompiles"]["total_compiles"],
            "kv_blocks_total":
                bundle["kv_ledger"].get("kv_blocks_total"),
        }
        stamp("bundle_validated")
        print("[stall] bundle OK: %d ring ticks, stacks present"
              % len(bundle["ring"]))

        # ---- zero accepted-request loss: the requests wedged on
        # the stalled replica re-dispatch to the healthy sibling and
        # complete; post-replacement traffic completes
        for i in range(3):
            call("post_%d" % i, 8)
        for t in long_reqs:
            t.join(timeout=150)
        assert not any(t.is_alive() for t in long_reqs), (
            "a wedged request HUNG: %r" % outcomes
        )
        assert set(outcomes.values()) == {"OK"}, (
            "accepted-request loss: %r" % outcomes
        )
        stamp("traffic_verified")

        # ---- phase 2: the replacement's armed health_leak fires on
        # its health thread (post-steady); reconciliation must
        # convict ~8 MiB of unaccounted device bytes
        def unaccounted():
            # the replica ServerStatus carries it; read through the
            # roster's addresses directly
            try:
                st = fleet()
            except Exception:  # noqa: BLE001
                return 0
            return max(
                (_replica_unaccounted(r.address) for r in st.replica),
                default=0,
            )

        def _replica_unaccounted(address):
            from elasticdl_tpu.proto.service import (
                ServingStub,
                build_channel as bc,
            )

            try:
                s = ServingStub(bc(address)).server_status(
                    pb.ServerStatusRequest(), timeout=5
                )
                return int(s.memory_unaccounted_bytes)
            except Exception:  # noqa: BLE001
                return 0

        leaked = wait_for(
            lambda: (unaccounted()
                     if unaccounted() >= LEAK_BYTES // 2 else None),
            60, "the memory accountant to convict the leak",
        )
        report["leak_convicted_bytes"] = int(leaked)
        stamp("leak_convicted")
        print("[stall] accountant convicted %d unaccounted bytes "
              "(leak was %d)" % (leaked, LEAK_BYTES))

        report["outcomes"] = dict(outcomes)
        report["detect_secs"] = round(detect_secs, 2)
        report["kill_secs"] = round(kill_secs, 2)
        report["beats_lease_heuristic_by_secs"] = round(
            WEDGED_AFTER_SECS - kill_secs, 2
        )
        report["pass"] = True
        out = os.path.join(REPO, "STALL_DRILL_REPORT.json")
        with open(out, "w") as f:
            json.dump(report, f, indent=1)
        print("[stall] PASS — detect %.1fs, kill %.1fs (lease "
              "heuristic: %.0fs); report -> %s"
              % (detect_secs, kill_secs, WEDGED_AFTER_SECS, out))
        return 0
    finally:
        sup.stop(grace=20.0)
        router.stop()


def _safe(fn):
    try:
        fn()
        return True
    except Exception:  # noqa: BLE001 - transient starvation
        return False


if __name__ == "__main__":
    sys.exit(main())
