#!/usr/bin/env bash
# Local job drill (reference scripts/travis/run_job.sh:32-45 without the
# minikube cluster): submit one `elasticdl-tpu train` job through the
# client CLI — local master + 2 subprocess workers pulling tasks over
# real gRPC — and validate its terminal status with
# scripts/validate_job_status.py, exactly as the reference CI validated
# pod phases.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
unset XLA_FLAGS || true
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

WORK_DIR=$(mktemp -d)
trap 'rm -rf "$WORK_DIR"' EXIT
DATA_DIR="$WORK_DIR/train"
STATUS_FILE="$WORK_DIR/job_status.json"

python - "$DATA_DIR" <<'EOF'
import sys
from elasticdl_tpu.data import recordio_gen
recordio_gen.gen_mnist_like(sys.argv[1], num_files=2, records_per_file=48)
EOF

# setsid: own process group, so cleanup can kill master AND the worker
# subprocesses LocalInstanceManager spawns (a bare kill of the master
# skips Master.stop and would orphan them)
setsid python -m elasticdl_tpu.client.main train \
    --model_zoo model_zoo \
    --model_def mnist_functional_api.mnist_functional_api.custom_model \
    --training_data "$DATA_DIR" \
    --num_workers 2 \
    --minibatch_size 16 \
    --records_per_task 24 \
    --num_epochs 1 \
    --job_name ci-local-drill \
    --job_status_file "$STATUS_FILE" &
MASTER_PID=$!

# the validator also watches the master pid: a master that dies without
# a terminal status fails fast (rc 3) instead of eating the timeout
if python scripts/validate_job_status.py \
    --status_file "$STATUS_FILE" 600 "$MASTER_PID"
then
    wait "$MASTER_PID"
    echo "local job drill: PASSED"
else
    rc=$?
    kill -- "-$MASTER_PID" 2>/dev/null || kill "$MASTER_PID" 2>/dev/null || true
    echo "local job drill: FAILED (validator rc=$rc)" >&2
    exit "$rc"
fi
