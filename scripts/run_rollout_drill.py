#!/usr/bin/env python
"""Zero-downtime model ROLLOUT drill: the journaled wave controller
(serving/rollout.py) rolls a REAL 3-replica fleet between checkpoint
versions under live Poisson load, and every failure mode it claims to
survive is manufactured for real:

  * HEALTHY ROLLOUT — version-1 -> version-2 (a republish of the same
    weights, so greedy parity must hold) through canary -> judgment
    (pinned-prompt parity + SLO burn over a soak window) -> waves ->
    commit, with the open-loop load running throughout: zero
    accepted-request loss and a steady p99 across the swap;
  * CORRUPT CHECKPOINT — a torn shard (truncated mid-write) must
    ABORT at staging, before ANY replica swaps: the integrity
    manifest, not a crashed replica, is the tripwire;
  * POISONED CANARY — perturbed weights pass integrity (they were
    saved whole) but DRIFT on the pinned prompts: the canary is
    judged parity_fail and auto-rolled back, and the fleet must end
    PROVABLY UNIFORM on the old version;
  * CONTROLLER SIGKILL MID-WAVE — the controller is abandoned (journal
    and fleet left exactly as a kill would leave them) after the
    canary and first wave swapped; a FRESH controller over the same
    journal must resume and finish the rollout with every replica
    reloaded EXACTLY ONCE — the per-replica version history is
    asserted from the journal itself (no double-swap, no mixed
    fleet), with a `rollout_swap` delay fault injected on the resumed
    controller's first swap (the slow-swap spec) to prove the hook
    sits on the real swap path.

The replicas run --reload_poll_secs 0 (explicit-reload-only): a
rollout-managed fleet must not self-upgrade behind the controller —
or self-revert a rollback the moment its own poll sees the newer
poisoned version again. Checkpoint loads land through the
reload_checkpoint RPC only.

The wave ledger is also audited from the journal: every wave_begin
must settle in wave_commit or wave_rollback (the same EDL501 pair
edl-lint enforces statically, asserted here on the real event log).

Client outcomes, per-phase latency percentiles, verdicts, the
journal's swap history and the final fleet versions are archived at
ROLLOUT_REPORT.json (repo root).

Usage: python scripts/run_rollout_drill.py
Exit 0 = every invariant holds."""

import json
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from run_server_kill_drill import MODEL_PARAMS, launch_ready  # noqa: E402

NUM_REPLICAS = 3
OLD_V, NEW_V, POISON_V, CORRUPT_V, RESUME_V = 1, 2, 3, 4, 5
RATE_RPS = 3.0
MAX_NEW = 8
CLIENT_TIMEOUT = 120.0  # backstop; the drill asserts we stay far under
P99_BOUND_MS = 30_000.0  # generous CPU bound; a dropped/wedged swap
# stalls dispatches far past it, a clean swap never gets near it
PARITY_PROMPTS = ((1, 2, 3), (2, 3, 4))


def start_replica(ckpt_dir):
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.serving.main",
        "--model_zoo", os.path.join(REPO, "model_zoo"),
        "--model_def", "transformer_lm.transformer_lm.custom_model",
        "--model_params", MODEL_PARAMS,
        "--port", "0", "--num_slots", "2", "--queue_capacity", "32",
        "--max_workers", "64",
        # pay the jit compile BEFORE advertising ready
        "--warmup_tokens", "4",
        # explicit-reload-only: version moves ONLY via the rollout
        # controller's reload_checkpoint RPC
        "--checkpoint_dir", ckpt_dir, "--reload_poll_secs", "0",
    ]
    return launch_ready(cmd)


def wait_for(cond, timeout, what, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = cond()
        if got:
            return got
        time.sleep(poll)
    raise AssertionError("timed out after %.0fs waiting for %s"
                         % (timeout, what))


def build_trainer_state():
    """Trainer state matching the replicas' model: the checkpoint
    payload every rollout version derives from."""
    import jax
    import numpy as np

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(load_model_spec_from_module(zoo), mesh=mesh,
                      model_params=MODEL_PARAMS)
    seq_len = int(trainer.model.seq_len)
    dummy = np.zeros((1, seq_len), np.int32)
    return trainer.init_state(({"tokens": dummy}, dummy))


def poison(state):
    """Weights that pass every integrity check (saved whole, digests
    valid) but drift on greedy decode: the silent-corruption case only
    the parity judgment can catch."""
    import jax
    import jax.numpy as jnp

    def twist(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype,
                                                  jnp.floating):
            return x * -1.5 + 0.25
        return x

    return jax.tree_util.tree_map(twist, state)


def journal_events(journal_dir):
    events = []
    with open(os.path.join(journal_dir, "journal.jsonl")) as f:
        for line in f:
            if line.strip():
                row = json.loads(line)
                if "ev" in row:
                    events.append(row)
    return events


def swap_history(events):
    """addr -> [versions in landed order] from the journal's ok
    swap_done events — the per-replica version history the no-double-
    swap and uniform-fleet claims are audited against."""
    hist = {}
    for ev in events:
        if ev.get("ev") == "swap_done" and ev.get("ok"):
            hist.setdefault(ev["addr"], []).append(int(ev["to"]))
    return hist


def main():
    import tempfile

    import numpy as np

    from elasticdl_tpu.checkpoint import CheckpointSaver
    from elasticdl_tpu.common.fault_injection import FaultInjector
    from elasticdl_tpu.observability.histogram import percentiles
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import (
        RouterStub,
        ServingStub,
        build_channel,
    )
    from elasticdl_tpu.serving import rollout as ro
    from elasticdl_tpu.serving.router import Router, RouterConfig

    tmp_root = tempfile.mkdtemp(prefix="edl_rollout_")
    ckpt_dir = os.path.join(tmp_root, "ckpt")
    journal_dir = os.path.join(tmp_root, "journal")

    print("[rollout] building checkpoint payloads (jax init)")
    state = build_trainer_state()
    saver = CheckpointSaver(ckpt_dir, checkpoint_steps=1)
    saver.save(state, version=OLD_V)
    saver.save(state, version=NEW_V)  # republish: parity must hold

    procs, ports = [], []
    router = None
    ctl = None
    stop_load = threading.Event()
    try:
        print("[rollout] launching %d replicas (explicit-reload-only)"
              % NUM_REPLICAS)
        for _ in range(NUM_REPLICAS):
            proc, port = start_replica(ckpt_dir)
            procs.append(proc)
            ports.append(port)
        addrs = ["localhost:%d" % p for p in ports]

        router = Router(addrs, RouterConfig(
            poll_secs=0.25, poll_timeout_secs=2.0, lease_secs=2.0,
            breaker_cooldown_secs=1.0, redispatch_window_secs=60.0,
            max_workers=64,
        )).start(grpc_server=True)
        rstub = RouterStub(build_channel("localhost:%d" % router.port))
        wait_for(
            lambda: sum(r.in_rotation(time.monotonic())
                        for r in router.replicas())
            >= NUM_REPLICAS,
            120, "all replicas healthy behind the router",
        )

        # seed the fleet onto OLD_V through the explicit reload RPC —
        # the same handshake every rollout swap uses
        for addr in addrs:
            resp = ServingStub(build_channel(addr)).reload_checkpoint(
                pb.ReloadCheckpointRequest(version=OLD_V), timeout=120
            )
            assert resp.ok and resp.model_version == OLD_V, (
                "seeding %s onto version-%d failed: %s"
                % (addr, OLD_V, resp.error)
            )

        def fleet_versions():
            return {r.address: int(r.model_version)
                    for r in router.replicas()}

        def fleet_uniform(version):
            vs = fleet_versions()
            return (len(vs) == NUM_REPLICAS
                    and set(vs.values()) == {version}) and vs

        wait_for(lambda: fleet_uniform(OLD_V), 60,
                 "fleet advertising version-%d" % OLD_V)
        print("[rollout] fleet seeded on version-%d: %s"
              % (OLD_V, sorted(addrs)))

        # ---- open-loop Poisson load across every phase
        outcomes, latencies = {}, {}
        lock = threading.Lock()
        phase_mark = ["setup"]
        threads = []
        rs = np.random.RandomState(7)

        def call(i, phase):
            t0 = time.monotonic()
            try:
                rstub.router_generate(
                    pb.GenerateRequest(
                        prompt=[1 + i % 5, 2],
                        max_new_tokens=MAX_NEW, seed=i,
                    ),
                    timeout=CLIENT_TIMEOUT,
                )
                code = "OK"
            except Exception as e:  # noqa: BLE001 - status is the datum
                code_fn = getattr(e, "code", None)
                code = (code_fn().name if callable(code_fn)
                        else type(e).__name__)
            with lock:
                outcomes[i] = code
                latencies[i] = (phase,
                                (time.monotonic() - t0) * 1000.0)

        def drive_load():
            i = 0
            while not stop_load.is_set():
                t = threading.Thread(
                    target=call, args=(i, phase_mark[0]), daemon=True
                )
                t.start()
                threads.append(t)
                i += 1
                stop_load.wait(rs.exponential(1.0 / RATE_RPS))

        loader = threading.Thread(target=drive_load, daemon=True)
        loader.start()

        def make_controller(injector=None):
            cfg = ro.RolloutConfig(
                checkpoint_dir=ckpt_dir, journal_dir=journal_dir,
                decide_secs=0.2, wave_size=1, soak_secs=2.0,
                judge_timeout_secs=90.0,
                parity_prompts=PARITY_PROMPTS, parity_max_tokens=6,
            )
            return ro.RolloutController(router, cfg,
                                        injector=injector)

        ctl = make_controller()
        router.set_rollout(ctl)
        ctl.start()

        def rollout_done():
            return ctl.phase if ctl.phase in ro.TERMINAL else None

        # ================= phase 1: healthy rollout, zero loss
        phase_mark[0] = "healthy"
        assert ctl.begin(NEW_V)
        phase = wait_for(rollout_done, 180, "healthy rollout terminal")
        assert phase == ro.COMMITTED, (
            "healthy rollout did not commit: phase=%s verdict=%s "
            "error=%s" % (phase, ctl.verdict, ctl.last_error)
        )
        assert ctl.verdict == "pass"
        vs = wait_for(lambda: fleet_uniform(NEW_V), 60,
                      "fleet uniform on version-%d" % NEW_V)
        print("[rollout] HEALTHY rollout committed: %s" % vs)
        # the rollout block rides router_status for operators
        block = rstub.router_status(
            pb.RouterStatusRequest(), timeout=20
        ).rollout
        assert block.enabled and block.phase == "committed"
        assert block.swapped == block.fleet == NUM_REPLICAS
        assert block.target_version == NEW_V

        # ================= phase 2: corrupt checkpoint -> staging abort
        phase_mark[0] = "corrupt"
        saver.save(state, version=CORRUPT_V)
        shard = os.path.join(
            ckpt_dir, "version-%d" % CORRUPT_V,
            sorted(f for f in os.listdir(
                os.path.join(ckpt_dir, "version-%d" % CORRUPT_V)
            ) if f.startswith("variables-"))[0],
        )
        with open(shard, "r+b") as f:
            f.truncate(16)  # the torn write
        assert ctl.begin(CORRUPT_V)
        phase = wait_for(rollout_done, 120, "corrupt rollout terminal")
        assert phase == ro.ABORTED, (
            "torn checkpoint was not rejected at staging: %s" % phase
        )
        assert fleet_uniform(NEW_V), (
            "a replica swapped toward a CORRUPT checkpoint: %s"
            % fleet_versions()
        )
        events = journal_events(journal_dir)
        assert not [e for e in events
                    if e.get("ev") == "swap_start"
                    and e.get("to") == CORRUPT_V], (
            "journal shows a swap attempted toward the torn version"
        )
        print("[rollout] CORRUPT checkpoint aborted at staging "
              "(zero fleet impact): %s" % ctl.last_error)

        # ================= phase 3: poisoned canary -> auto-rollback
        phase_mark[0] = "poisoned"
        saver.save(poison(state), version=POISON_V)
        assert ctl.begin(POISON_V)
        phase = wait_for(rollout_done, 180, "poisoned rollout terminal")
        assert phase == ro.ROLLED_BACK, (
            "poisoned rollout did not roll back: phase=%s verdict=%s"
            % (phase, ctl.verdict)
        )
        assert ctl.verdict == "parity_fail", (
            "expected greedy-parity to catch the poisoned weights, "
            "got verdict=%r" % ctl.verdict
        )
        vs = wait_for(lambda: fleet_uniform(NEW_V), 60,
                      "fleet back uniform on version-%d" % NEW_V)
        assert ctl.rollbacks >= 1
        print("[rollout] POISONED canary judged parity_fail and "
              "rolled back; fleet provably uniform on version-%d"
              % NEW_V)

        # ================= phase 4: controller SIGKILL mid-wave
        phase_mark[0] = "kill_resume"
        saver.save(state, version=RESUME_V)
        assert ctl.begin(RESUME_V)
        wait_for(
            lambda: (ctl.phase == ro.WAVE
                     and len(ctl.swapped) >= 2) or None,
            180, "canary + first wave swapped",
        )
        ctl.abandon()  # journal + fleet exactly as SIGKILL leaves them
        mixed = fleet_versions()
        print("[rollout] controller ABANDONED mid-wave; fleet mixed: "
              "%s" % mixed)
        assert set(mixed.values()) == {NEW_V, RESUME_V}, (
            "expected a mixed fleet at the kill point: %s" % mixed
        )
        # a fresh controller over the same journal, with a slow-swap
        # fault on its first swap (the rollout_swap hook on the REAL
        # swap path) — the rollout must still finish
        ctl2 = make_controller(
            injector=FaultInjector(spec="rollout_swap:delay:1:secs=1")
        )
        assert ctl2.phase == ro.WAVE, (
            "journal recovery lost the wave: %s" % ctl2.phase
        )
        assert ctl2.rollout_restarts >= 1
        router.set_rollout(ctl2)
        ctl2.start()
        ctl = ctl2
        phase = wait_for(rollout_done, 180, "resumed rollout terminal")
        assert phase == ro.COMMITTED, (
            "resumed rollout did not commit: phase=%s error=%s"
            % (phase, ctl.last_error)
        )
        vs = wait_for(lambda: fleet_uniform(RESUME_V), 60,
                      "fleet uniform on version-%d" % RESUME_V)
        print("[rollout] KILLED controller resumed from the journal "
              "and committed: %s" % vs)

        # ---- journal audit: per-replica history, no double-swap,
        # settled wave ledger
        events = journal_events(journal_dir)
        hist = swap_history(events)
        assert set(hist) == set(addrs), (
            "journal swap history covers %s, fleet is %s"
            % (sorted(hist), sorted(addrs))
        )
        for addr, versions in sorted(hist.items()):
            assert versions.count(RESUME_V) == 1, (
                "%s reloaded version-%d %d times across the kill "
                "(double-swap): %s"
                % (addr, RESUME_V, versions.count(RESUME_V), versions)
            )
            assert versions.count(NEW_V) <= 2  # swap + poison rollback
            # landed order is strictly alternating versions — a
            # replica never reloads the version it already serves
            assert all(a != b for a, b in zip(versions, versions[1:])), (
                "%s journal shows a same-version reload: %s"
                % (addr, versions)
            )
        canary = sorted(addrs)[0]
        assert hist[canary].count(POISON_V) == 1, (
            "canary history missing the poisoned swap: %s"
            % hist[canary]
        )
        # raw counts balance BECAUSE resume never re-journals a wave
        # it recovered: wave 1's begin landed before the kill, its
        # commit after — one begin, one settle
        begun = len([e for e in events if e.get("ev") == "wave_begin"])
        settled = len([e for e in events
                       if e.get("ev") in ("wave_commit",
                                          "wave_rollback")])
        assert begun == settled, (
            "unsettled wave ledger: %d begun vs %d settled"
            % (begun, settled)
        )
        print("[rollout] journal audit: per-replica history %s; "
              "%d waves begun, %d settled" % (hist, begun, settled))

        # ---- zero accepted-request loss + steady p99, all phases
        stop_load.set()
        loader.join(timeout=10)
        for t in threads:
            t.join(timeout=CLIENT_TIMEOUT + 30)
        hung = [t for t in threads if t.is_alive()]
        assert not hung, "%d client threads HUNG" % len(hung)
        codes = list(outcomes.values())
        counts = {c: codes.count(c) for c in set(codes)}
        print("[rollout] outcomes over %d requests: %s"
              % (len(codes), counts))
        allowed = {"OK", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        leaked = set(codes) - allowed
        assert not leaked, (
            "accepted requests LOST across rollout transitions: %s"
            % leaked
        )
        assert codes and codes.count("OK") >= int(0.9 * len(codes)), (
            "too few completions under rollout load: %s" % counts
        )
        phase_stats = {}
        for name in ("healthy", "corrupt", "poisoned", "kill_resume"):
            rows = [ms for i, (p, ms) in latencies.items()
                    if p == name and outcomes[i] == "OK"]
            stats = percentiles(rows, (50, 99))
            phase_stats[name] = {"requests": len(rows),
                                 "latency_ms": stats}
            if rows:
                assert stats["p99"] <= P99_BOUND_MS, (
                    "p99 not steady through phase %r: %.0f ms"
                    % (name, stats["p99"])
                )
            print("[rollout] phase %-12s %3d OK requests, p99=%s ms"
                  % (name, len(rows), stats["p99"]))

        report = {
            "replicas": NUM_REPLICAS,
            "rate_rps": RATE_RPS,
            "requests": len(codes),
            "outcomes": counts,
            "phases": phase_stats,
            "verdicts": {"healthy": "pass", "corrupt": "aborted",
                         "poisoned": "parity_fail",
                         "kill_resume": "committed"},
            "rollout_restarts": ctl.rollout_restarts,
            "rollbacks_total": ctl.rollbacks,
            "swap_history": hist,
            "final_fleet_versions": fleet_versions(),
            "journal_events": len(events),
        }
        out = os.path.join(REPO, "ROLLOUT_REPORT.json")
        with open(out, "w") as f:
            json.dump(report, f, indent=1)
        print("[rollout] report archived -> %s" % out)
        print("[rollout] rollout drill PASSED: healthy commit with "
              "zero accepted-request loss and steady p99, torn "
              "checkpoint rejected at staging, poisoned canary "
              "parity-failed and auto-rolled back to a provably "
              "uniform fleet, and a SIGKILLed controller resumed "
              "from its journal to a single-swap commit")
        return 0
    finally:
        stop_load.set()
        try:
            if ctl is not None:
                ctl.abandon()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        for proc in procs:
            try:
                proc.kill()
            except OSError:
                pass
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        if router is not None:
            try:
                router.stop(grace=2.0)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


if __name__ == "__main__":
    sys.exit(main())
