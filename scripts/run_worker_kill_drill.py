#!/usr/bin/env python
"""Replayable worker-kill recovery drill (VERDICT round-3 item #5).

Runs the REAL distributed stack — master gRPC server, task dispatcher,
LocalInstanceManager spawning worker subprocesses — SIGKILLs a worker
mid-task (the exit the reference's benchmark induced by cluster
preemption, report §Elasticity), and verifies the master re-queues the
in-flight task, relaunches a replacement, and finishes the job. The
same sequence runs against a k8s cluster via
scripts/run_cluster_job_smoke.sh (EDL_CLUSTER_FULL=1) with `kubectl
delete pod` as the kill; this script needs nothing but the repo.

Usage: python scripts/run_worker_kill_drill.py
Exit 0 = recovered and finished; the transcript narrates each phase.
"""

import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.instance_manager import LocalInstanceManager
from elasticdl_tpu.master.master import Master


def main():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    workdir = tempfile.mkdtemp(prefix="kill_drill_")
    train_dir = os.path.join(workdir, "train")
    print("[drill] generating 4x48 TRec records -> %s" % train_dir)
    recordio_gen.gen_mnist_like(train_dir, num_files=4,
                                records_per_file=48)

    master = Master(
        load_model_spec_from_module(zoo),
        training_data=train_dir,
        minibatch_size=16,
        records_per_task=24,
        num_epochs=2,
    )
    master.prepare()
    print("[drill] master gRPC server on :%d, %d tasks queued"
          % (master.port, len(master.task_d._todo)))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    manager = LocalInstanceManager(
        master.task_d,
        num_workers=1,
        worker_args=[
            "--model_zoo", os.path.join(repo, "model_zoo"),
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--training_data", train_dir,
            "--minibatch_size", "16",
            "--records_per_task", "24",
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
        ],
        env=env,
    )
    master.instance_manager = manager
    manager.start_workers()
    print("[drill] worker 0 launched (subprocess)")

    try:
        deadline = time.time() + 120
        while not master.task_d.doing_tasks() and time.time() < deadline:
            time.sleep(0.2)
        if not master.task_d.doing_tasks():
            print("[drill] FAIL: worker never took a task")
            return 1
        doing = dict(master.task_d.doing_tasks())
        print("[drill] worker 0 is mid-task (in-flight: %s) — SIGKILL"
              % sorted(doing))
        manager.remove_worker(0)

        deadline = time.time() + 300
        while not master.task_d.finished() and time.time() < deadline:
            if manager.all_workers_failed():
                print("[drill] FAIL: all workers failed, no relaunch")
                return 1
            time.sleep(0.5)
        if not master.task_d.finished():
            print("[drill] FAIL: job did not finish after the kill")
            return 1
        print("[drill] worker 0 terminal phase: %s"
              % manager.worker_phase(0))
        print("[drill] replacement worker 1 phase: %s"
              % manager.worker_phase(1))
        print("[drill] job finished: every task completed after "
              "re-queue — PASSED")
        return 0
    finally:
        master.stop()


if __name__ == "__main__":
    sys.exit(main())
