#!/usr/bin/env bash
# Cluster-manifest smoke (reference scripts/travis/run_job.sh:32-45,
# which ran a real minikube job in CI): validate manifests/ against a
# REAL cluster's API server, and optionally run the full job.
#
# Levels:
#   (no cluster reachable)  -> exit 3 (callers/tests skip)
#   default                 -> server-side dry-run apply of every
#                              manifest (schema + admission validation
#                              by the API server, no workloads created)
#   EDL_CLUSTER_FULL=1      -> apply RBAC, create the master pod with
#                              EDL_SMOKE_IMAGE, wait for Succeeded
#                              (kind/minikube compatible)
set -euo pipefail
cd "$(dirname "$0")/.."

if ! kubectl cluster-info >/dev/null 2>&1; then
    echo "cluster smoke: no reachable cluster (kubectl cluster-info failed)"
    exit 3
fi

echo "cluster smoke: server-side dry-run of manifests/"
for m in manifests/*.yaml; do
    # the example master manifest carries a placeholder image; that is
    # fine for validation (the API server does not pull on dry-run)
    kubectl apply --dry-run=server -f "$m"
done

if [[ "${EDL_CLUSTER_FULL:-0}" != "1" ]]; then
    echo "cluster smoke: dry-run OK (set EDL_CLUSTER_FULL=1 for a real job)"
    exit 0
fi

: "${EDL_SMOKE_IMAGE:?EDL_CLUSTER_FULL=1 needs EDL_SMOKE_IMAGE (a built elasticdl-tpu-zoo image loadable by the cluster)}"

kubectl apply -f manifests/elasticdl-tpu-rbac.yaml
WORK=$(mktemp -d); trap 'rm -rf "$WORK"' EXIT
sed "s|YOUR_REGISTRY/elasticdl-tpu-zoo:latest|$EDL_SMOKE_IMAGE|g" \
    manifests/master-example.yaml > "$WORK/master.yaml"
kubectl delete pod elasticdl-demo-master --ignore-not-found
kubectl apply -f "$WORK/master.yaml"

echo "cluster smoke: waiting for master pod to finish..."
for _ in $(seq 1 120); do
    PHASE=$(kubectl get pod elasticdl-demo-master \
        -o jsonpath='{.status.phase}' 2>/dev/null || echo Unknown)
    case "$PHASE" in
        Succeeded) echo "cluster smoke: job Succeeded"; exit 0 ;;
        Failed)
            kubectl logs elasticdl-demo-master | tail -50
            echo "cluster smoke: job FAILED"; exit 1 ;;
    esac
    sleep 5
done
echo "cluster smoke: timed out waiting for the master pod"
exit 1
