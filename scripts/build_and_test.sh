#!/usr/bin/env bash
# CI entry (reference scripts/build_and_test.sh:17-32): build both native
# libs from a clean tree, run the full pytest suite on the virtual
# 8-device CPU mesh, then run one real local training job and validate
# its status (the reference's minikube job drill, scripts/travis/
# run_job.sh, without a cluster). One command, green, from a fresh clone.
#
#   scripts/build_and_test.sh            everything
#   scripts/build_and_test.sh --no-drill suite only (plus pytest args)
set -euo pipefail
cd "$(dirname "$0")/.."

RUN_DRILL=1
if [ "${1:-}" = "--no-drill" ]; then
    RUN_DRILL=0
    shift
fi

make -C elasticdl_tpu/native clean
make -C elasticdl_tpu/native

python -m pytest tests/ -q "$@"

if [ "$RUN_DRILL" = "1" ]; then
    bash scripts/run_local_job_drill.sh
fi
