#!/usr/bin/env bash
# CI entry (reference scripts/build_and_test.sh): build native libs, run
# the full pytest suite on the virtual 8-device CPU mesh.
set -euo pipefail
cd "$(dirname "$0")/.."

make -C elasticdl_tpu/native
python -m pytest tests/ -q "$@"
