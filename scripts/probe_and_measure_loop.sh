#!/usr/bin/env bash
# Long-running tunnel watch: probe the axon PJRT tunnel on a cadence
# and run the full hardware session (scripts/hw_session.py) the moment
# a probe answers. Appends to TUNNEL_LOG.md via probe_tpu.sh. Exits
# after a completed hardware session so the log shows one session per
# window. Usage:
#   scripts/probe_and_measure_loop.sh [interval_s] [probe_timeout_s]
set -u -o pipefail
cd "$(dirname "$0")/.."
INTERVAL=${1:-420}
PROBE_T=${2:-90}
while true; do
    STATUS=$(bash scripts/probe_tpu.sh "$PROBE_T")
    if echo "$STATUS" | grep -q "^UP"; then
        echo "[loop] tunnel UP at $(date -u +%H:%M:%S) — running hw_session"
        # a stale file must not read as success; keep the old window's
        # partial measurements around instead of destroying them
        if [ -s hw_session_results.json ]; then
            mv hw_session_results.json \
               "hw_session_results.$(date -u +%Y%m%dT%H%M%S).json"
        fi
        python scripts/hw_session.py --out hw_session_results.json \
            2>&1 | tee hw_session_run.log
        RC=$?
        echo "[loop] hw_session rc=$RC"
        # hw_session exits 0 even when every bench fell back to CPU
        # (wedge right after the probe answered) — only a flagship
        # measured ON THE CHIP counts as a completed window
        if [ "$RC" -eq 0 ] && [ -s hw_session_results.json ] && \
           python - <<'EOF'
import json, sys
d = json.load(open("hw_session_results.json"))
ok = any(
    (d.get(k) or {}).get("platform") not in (None, "cpu")
    for k in ("flagship", "flagship_prelim")
)
sys.exit(0 if ok else 1)
EOF
        then
            echo "[loop] TPU flagship captured; exiting"
            exit 0
        fi
        echo "[loop] no TPU flagship yet — continuing to probe"
    fi
    sleep "$INTERVAL"
done
