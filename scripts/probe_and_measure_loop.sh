#!/usr/bin/env bash
# Long-running tunnel watch: probe the axon PJRT tunnel on a cadence
# and run the full hardware session (scripts/hw_session.py) the moment
# a probe answers. Appends to TUNNEL_LOG.md via probe_tpu.sh. Exits
# after a completed hardware session so the log shows one session per
# window. Usage:
#   scripts/probe_and_measure_loop.sh [interval_s] [probe_timeout_s]
set -u -o pipefail
cd "$(dirname "$0")/.."
INTERVAL=${1:-420}
PROBE_T=${2:-90}
while true; do
    STATUS=$(bash scripts/probe_tpu.sh "$PROBE_T")
    if echo "$STATUS" | grep -q "^UP"; then
        echo "[loop] tunnel UP at $(date -u +%H:%M:%S) — running hw_session"
        # a stale file must not read as success; keep the old window's
        # partial measurements around instead of destroying them
        if [ -s hw_session_results.json ]; then
            mv hw_session_results.json \
               "hw_session_results.$(date -u +%Y%m%dT%H%M%S).json"
        fi
        python scripts/hw_session.py --out hw_session_results.json \
            2>&1 | tee hw_session_run.log
        # PIPESTATUS[0] is hw_session.py's own status — plain $? would
        # be tee's (last in the pipeline), letting a crashed session
        # read as success and end the loop early
        RC=${PIPESTATUS[0]}
        echo "[loop] hw_session rc=$RC"
        # hw_session exits 0 even when every bench fell back to CPU
        # (wedge right after the probe answered). A window only ends
        # the loop when the chip measurements are BROAD: the flagship
        # AND most of the family/A-B queue. Coverage ACCUMULATES over
        # the archived windows (the mv above): short tunnel windows
        # each convert a few steps, and the loop exits once their
        # UNION clears the bar — per-session-only counting could spin
        # forever when no single window lasts long enough.
        if [ "$RC" -eq 0 ] && [ -s hw_session_results.json ] && \
           python - <<'EOF'
import glob, json, sys
# current window first, then every archived partial window
paths = ["hw_session_results.json"] + sorted(
    glob.glob("hw_session_results.*.json")
)
measured, flag_ok, target = set(), False, 0
for path in paths:
    try:
        d = json.load(open(path))
    except (ValueError, OSError):
        continue
    flag_ok = flag_ok or any(
        (d.get(k) or {}).get("platform") not in (None, "cpu")
        for k in ("flagship", "flagship_prelim")
    )
    # same per-step rule hw_session.py's save() counts with; the
    # union over windows is what accumulates
    measured.update(
        k for k, v in d.items()
        if isinstance(v, dict) and v.get("platform") not in (None, "cpu")
    )
    # hw_session.py's save() derives the target from the actual step
    # roster; take the newest/largest so a grown queue raises the bar
    target = max(target, int(d.get("tpu_target") or 0))
sys.exit(0 if flag_ok and target and len(measured) >= 0.75 * target else 1)
EOF
        then
            echo "[loop] TPU window fully converted; exiting"
            exit 0
        fi
        echo "[loop] measurements still pending — continuing to probe"
    fi
    sleep "$INTERVAL"
done
