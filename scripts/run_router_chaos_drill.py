#!/usr/bin/env python
"""Multi-replica ROUTER chaos drill: zero accepted-request loss under
replica churn.

Runs the real stack as subprocesses — three `elasticdl_tpu.serving.main`
replicas behind one `elasticdl_tpu.serving.router_main` router — fires
an open-loop Poisson stream of unary generates at the ROUTER, and while
the load is live:

  * SIGSTOPs one replica, bursts requests so the router provably has
    dispatches in flight on it (the in-flight component of the load
    score spreads a burst across all replicas), then SIGKILLs it — the
    stalled dispatches die UNAVAILABLE and MUST be re-dispatched to a
    surviving replica before anything reaches the client;
  * drops a fresh checkpoint into a second replica's --checkpoint_dir
    (the hot-reload path: the replica advertises `draining` across the
    swap and keeps its streams).

The asserted invariant is the router's contract: every request the
router ACCEPTED terminates with OK or an EXPLICIT status
(RESOURCE_EXHAUSTED shed / DEADLINE_EXCEEDED) — never a raw transport
error (UNAVAILABLE/CANCELLED), never a hang. A majority must complete
OK (two replicas survive), at least one request must have been
RE-DISPATCHED (proof the chaos path actually ran), the SIGKILL'd
replica must leave the rotation, and the reloaded replica must report
the new version.

The drill also runs TRACED (EDL_TRACE_DIR): after the graceful
teardown it merges every process's span export
(observability/dump.merge_dir) and asserts the CAUSAL story
structurally, not just by counters — every accepted request's trace
reaches a terminal root span with an explicit status; at least one
trace contains a failed dispatch span targeting the killed replica
with a successful SIBLING dispatch next to it (the re-dispatch, as
causality, not as a counter); and at least one replica `serve` span
parents under a router dispatch span (the cross-process merge
actually merged). The merged Chrome-trace JSON is archived at
ROUTER_CHAOS_TRACE.json (repo root) — open it at ui.perfetto.dev.

Runs TWICE: dense KV pool and block-paged pool (EDL_KV_PAGED), like
the single-replica kill drill.

A third ROUTER-KILL phase then moves the chaos one tier up: three
replicas behind TWO router cells sharing a registry journal
(--cells / --cell_journal_dir), a CellFront dispatching shared-prefix
load pinned by fingerprint to one owning cell, SIGKILL of that cell
mid-load — every accepted request must reroute through the surviving
cell with zero loss, and the killed cell must restart replica-flag-
free and rebuild its whole fleet view from journal replay.

Usage: python scripts/run_router_chaos_drill.py
Exit 0 = the invariant holds in both modes."""

import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from run_server_kill_drill import MODEL_PARAMS, launch_ready  # noqa: E402

NUM_REPLICAS = 3
REQUESTS = 24
RATE_RPS = 10.0
MAX_NEW = 16
CLIENT_TIMEOUT = 120.0  # backstop; the drill asserts we stay far under
WARMUP_REQS = 6  # Poisson-paced requests before the chaos window
BURST_REQS = 6  # back-to-back burst fired at the SIGSTOPped victim
RELOAD_AFTER = 14  # save the hot-reload checkpoint after this many


def start_replica(ckpt_dir=None, extra_env=None):
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.serving.main",
        "--model_zoo", os.path.join(REPO, "model_zoo"),
        "--model_def", "transformer_lm.transformer_lm.custom_model",
        "--model_params", MODEL_PARAMS,
        "--port", "0", "--num_slots", "2", "--queue_capacity", "16",
    ]
    if ckpt_dir:
        cmd += ["--checkpoint_dir", ckpt_dir,
                "--reload_poll_secs", "0.3"]
    return launch_ready(cmd, extra_env=extra_env)


def start_router(replica_ports, extra_env=None):
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.serving.router_main",
        "--port", "0", "--poll_secs", "0.25", "--lease_secs", "1.5",
        "--breaker_cooldown_secs", "1.0",
        "--redispatch_window_secs", "60",
    ]
    for p in replica_ports:
        cmd += ["--replica", "localhost:%d" % p]
    return launch_ready(cmd, extra_env=extra_env,
                        ready_marker="ROUTER_READY")


def start_router_cell(replica_ports, cell_id, cells, journal_dir,
                      extra_env=None):
    """One router CELL: a full router process that shares its replica
    registry with its siblings through the write-ahead journal in
    `journal_dir`. Launched with an explicit --cell_id (no supervisor)
    so the drill controls each cell's lifetime directly."""
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.serving.router_main",
        "--port", "0", "--poll_secs", "0.25", "--lease_secs", "1.5",
        "--breaker_cooldown_secs", "1.0",
        "--redispatch_window_secs", "60",
        "--cell_id", str(cell_id), "--cells", str(cells),
        "--cell_journal_dir", journal_dir,
    ]
    for p in replica_ports:
        cmd += ["--replica", "localhost:%d" % p]
    return launch_ready(cmd, extra_env=extra_env,
                        ready_marker="ROUTER_READY")


def build_checkpoint_state():
    """Trainer state matching the replicas' model — the hot-reload
    payload. Built ONCE (jax import + init are the slow part); saving
    it mid-drill is just serialization."""
    import jax
    import numpy as np

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(load_model_spec_from_module(zoo), mesh=mesh,
                      model_params=MODEL_PARAMS)
    seq_len = int(trainer.model.seq_len)
    dummy = np.zeros((1, seq_len), np.int32)
    return trainer.init_state(({"tokens": dummy}, dummy))


def warm(port):
    """One direct generate per replica outside the measurement: pays
    the jit compile so the chaos window exercises routing, not XLA."""
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel

    stub = ServingStub(build_channel("localhost:%d" % port))
    stub.generate(
        pb.GenerateRequest(prompt=[1, 2], max_new_tokens=2), timeout=300
    )
    return stub


def verify_traces(mode, trace_dir, killed_addr, outcomes):
    """Structural assertions over the merged trace: the drill's story
    must be READABLE from causality alone. Returns the merged spans
    for archiving."""
    from elasticdl_tpu.observability.dump import merge_dir
    from elasticdl_tpu.observability.tracing import group_by_trace

    spans, meta = merge_dir(trace_dir)
    by_trace = group_by_trace(spans)
    roots = [s for s in spans if s["name"] == "router_generate"]

    # 1. every accepted request's trace reaches a terminal root span
    # (only FINISHED spans export, so presence == termination), and
    # every terminal status is explicit — the trace-level twin of the
    # no-transport-codes client assertion
    assert len(roots) == len(outcomes), (
        "[chaos:%s] %d router_generate roots for %d accepted "
        "requests — some request left no terminal span"
        % (mode, len(roots), len(outcomes))
    )
    allowed = {"ok", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
    statuses = {r["status"] for r in roots}
    assert statuses <= allowed, (
        "[chaos:%s] non-explicit terminal span statuses: %s"
        % (mode, statuses - allowed)
    )
    ok_roots = [r for r in roots if r["status"] == "ok"]
    n_ok = list(outcomes.values()).count("OK")
    assert len(ok_roots) == n_ok, (
        "[chaos:%s] %d ok roots != %d OK client outcomes"
        % (mode, len(ok_roots), n_ok)
    )

    # 2./3. causal re-dispatch + cross-process merge
    redispatch_trees = 0
    merged_trees = 0
    for root in ok_roots:
        tspans = by_trace[root["trace_id"]]
        dispatches = [
            s for s in tspans
            if s["name"] == "dispatch"
            and s["parent_span_id"] == root["span_id"]
        ]
        assert dispatches, (
            "[chaos:%s] OK root without dispatch children" % mode
        )
        oks = [d for d in dispatches if d["status"] == "ok"]
        assert oks, (
            "[chaos:%s] OK root whose dispatch legs all failed" % mode
        )
        killed_legs = [
            d for d in dispatches
            if d["status"] == "error"
            and d["attrs"].get("replica") == killed_addr
        ]
        if killed_legs and any(
                e["name"] == "redispatched" for e in root["events"]):
            redispatch_trees += 1
        ok_leg_ids = {d["span_id"] for d in oks}
        if any(s["name"] == "serve"
               and s["parent_span_id"] in ok_leg_ids
               for s in tspans):
            merged_trees += 1
    assert redispatch_trees >= 1, (
        "[chaos:%s] no trace shows a failed dispatch to the killed "
        "replica (%s) with a successful sibling — the re-dispatch "
        "causality is missing from the trace" % (mode, killed_addr)
    )
    assert merged_trees >= 1, (
        "[chaos:%s] no replica serve span parented under a router "
        "dispatch span — the cross-process merge merged nothing"
        % mode
    )
    print("[chaos:%s] traces: %d spans / %d trees from %d exports; "
          "%d trees carry the killed-replica re-dispatch story, "
          "%d merged across processes"
          % (mode, len(spans), len(by_trace), len(meta),
             redispatch_trees, merged_trees))
    return spans


def run_mode(mode, mode_env, state, tmp_root):
    import grpc
    import numpy as np

    from elasticdl_tpu.checkpoint.saver import CheckpointSaver
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel

    print("[chaos:%s] starting %d replicas + router"
          % (mode, NUM_REPLICAS))
    reload_dir = os.path.join(tmp_root, "ckpt_%s" % mode)
    os.makedirs(reload_dir, exist_ok=True)
    # every process exports its span ring here on graceful shutdown;
    # the SIGKILL'd replica's export is LOST by design — its requests'
    # causality lives in the router's dispatch spans
    trace_dir = os.path.join(tmp_root, "traces_%s" % mode)
    os.makedirs(trace_dir, exist_ok=True)
    mode_env = dict(mode_env, EDL_TRACE_DIR=trace_dir)
    replicas = []
    try:
        for i in range(NUM_REPLICAS):
            proc, port = start_replica(
                ckpt_dir=reload_dir if i == 1 else None,
                extra_env=mode_env,
            )
            replicas.append([proc, port, None])
        for rep in replicas:
            rep[2] = warm(rep[1])
        router_proc, router_port = start_router(
            [r[1] for r in replicas], extra_env=mode_env
        )
        replicas.append([router_proc, router_port, None])  # for cleanup
        stub = RouterStub(build_channel("localhost:%d" % router_port))
        stub.router_status(pb.RouterStatusRequest(), timeout=10)

        rs = np.random.RandomState(0)
        outcomes = {}
        lock = threading.Lock()

        def call(i):
            try:
                stub.router_generate(
                    pb.GenerateRequest(
                        prompt=[1 + i % 5, 2],
                        max_new_tokens=4 + i % (MAX_NEW - 3),
                        seed=i,
                    ),
                    timeout=CLIENT_TIMEOUT,
                )
                code = "OK"
            except grpc.RpcError as e:
                code = e.code().name
            with lock:
                outcomes[i] = code

        threads = []
        t0 = time.monotonic()

        def launch(i, gap):
            if gap:
                time.sleep(float(rs.exponential(1.0 / RATE_RPS)))
            t = threading.Thread(target=call, args=(i,))
            t.start()
            threads.append(t)

        i = 0
        # phase A: Poisson-paced warmup through the router
        for _ in range(WARMUP_REQS):
            launch(i, gap=True)
            i += 1
        # chaos window. SIGSTOP freezes the victim: it stops answering
        # (and polling its way back to a fresh lease) but its sockets
        # stay open, so burst dispatches routed to it STALL in flight —
        # the in-flight load component spreads the burst over all three
        # replicas, so at least one request is provably stalled there.
        # The SIGKILL then tears the sockets down mid-flight:
        # UNAVAILABLE -> re-dispatch, never a client-visible loss.
        print("[chaos:%s] SIGSTOP replica 0 (port %d), bursting %d "
              "requests" % (mode, replicas[0][1], BURST_REQS))
        replicas[0][0].send_signal(signal.SIGSTOP)
        for _ in range(BURST_REQS):
            launch(i, gap=False)
            i += 1
        time.sleep(0.5)  # let burst dispatches reach the stalled victim
        print("[chaos:%s] SIGKILL replica 0 mid-flight" % mode)
        replicas[0][0].kill()
        # phase B: Poisson-paced tail over the two survivors
        reloaded = False
        while i < REQUESTS:
            launch(i, gap=True)
            i += 1
            if i >= RELOAD_AFTER and not reloaded:
                print("[chaos:%s] dropping checkpoint v1 -> replica 1 "
                      "hot reload" % mode)
                CheckpointSaver(reload_dir, checkpoint_steps=1).save(
                    state, 1
                )
                reloaded = True

        for t in threads:
            t.join(timeout=CLIENT_TIMEOUT + 30)
        elapsed = time.monotonic() - t0
        hung = [t for t in threads if t.is_alive()]
        if hung:
            raise AssertionError(
                "[chaos:%s] %d client threads HUNG" % (mode, len(hung))
            )
        codes = sorted(outcomes.values())
        ok = codes.count("OK")
        print("[chaos:%s] outcomes=%s elapsed=%.1fs" %
              (mode, {c: codes.count(c) for c in set(codes)}, elapsed))

        # THE invariant: zero accepted-request loss. Explicit statuses
        # only — a raw transport code leaking through the router means
        # a request was lost rather than re-dispatched or shed.
        allowed = {"OK", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        leaked = set(codes) - allowed
        assert not leaked, (
            "accepted requests LOST (transport codes leaked through "
            "the router): %s" % leaked
        )
        assert len(outcomes) == REQUESTS, (
            "only %d/%d clients terminated" % (len(outcomes), REQUESTS)
        )
        assert ok >= REQUESTS // 2, (
            "too few completions for a 2-survivor fleet: %d/%d OK"
            % (ok, REQUESTS)
        )
        assert elapsed < CLIENT_TIMEOUT - 10, "clients rode the timeout"

        # the SIGKILL'd replica must be OUT of rotation (lease decay)
        deadline = time.time() + 10
        status = None
        while time.time() < deadline:
            status = stub.router_status(
                pb.RouterStatusRequest(), timeout=10
            )
            if status.healthy <= NUM_REPLICAS - 1:
                break
            time.sleep(0.3)
        assert status.healthy <= NUM_REPLICAS - 1, (
            "router still counts the SIGKILL'd replica healthy: %s"
            % status
        )
        print("[chaos:%s] router: routed=%d completed=%d "
              "redispatched=%d shed=%d breaker_trips=%d healthy=%d/%d"
              % (mode, status.routed, status.completed,
                 status.redispatched, status.shed,
                 status.breaker_trips, status.healthy, status.replicas))
        assert status.routed >= REQUESTS
        # proof the chaos path ran: the SIGKILL caught stalled
        # dispatches, and every one of them was re-dispatched (the OK
        # outcomes above show none of it reached a client)
        assert status.redispatched >= 1, (
            "SIGKILL never caught an in-flight dispatch — the drill "
            "exercised nothing"
        )

        # the hot-reloaded replica must be serving the new version
        rep1 = replicas[1][2]
        deadline = time.time() + 20
        reloads = 0
        while time.time() < deadline:
            st = rep1.server_status(pb.ServerStatusRequest(), timeout=10)
            reloads = st.reloads
            if reloads >= 1:
                break
            time.sleep(0.3)
        assert reloads >= 1, "replica 1 never hot-reloaded"
        print("[chaos:%s] replica 1 hot-reloaded (reloads=%d) with "
              "zero request loss" % (mode, reloads))

        # graceful teardown: SIGTERM everything still alive; the
        # survivors drain and exit 0
        for proc, _port, _stub in replicas:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc, _port, _stub in replicas[1:]:
            rc = proc.wait(timeout=60)
            assert rc == 0, "graceful exit must return 0, got %s" % rc
        assert replicas[0][0].wait(timeout=10) != 0  # SIGKILL, by design

        # trace forensics: the drill's causal story must be readable
        # from the merged span exports (survivors flushed on SIGTERM)
        spans = verify_traces(
            mode, trace_dir, "localhost:%d" % replicas[0][1], outcomes
        )
        return spans
    finally:
        for entry in replicas:
            if entry[0].poll() is None:
                entry[0].kill()
    print("[chaos:%s] PASSED" % mode)


def run_cell_failover(tmp_root):
    """Router-kill phase: the router tier itself is the victim.

    Three replicas behind TWO router cells sharing one registry
    journal. Cell 1 starts with NO --replica flags — its whole fleet
    view is journal replay of cell 0's adopt events. A CellFront in
    this process dispatches a Poisson stream of shared-prefix unary
    generates (one prefix family -> one fingerprint -> one owning
    cell), the drill SIGKILLs the OWNING cell mid-load, and every
    accepted request must re-dispatch through the surviving cell with
    zero loss — then the killed cell restarts replica-flag-free and
    must rebuild the full fleet from the journal."""
    import numpy as np

    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel
    from elasticdl_tpu.serving.router import RouterError
    from elasticdl_tpu.serving.router_cell import CellFront

    mode = "cells"
    env = {"EDL_KV_PAGED": "1"}
    journal_dir = os.path.join(tmp_root, "cell_journal")
    os.makedirs(journal_dir, exist_ok=True)
    procs = []  # every subprocess, for the finally-kill backstop
    front = None
    try:
        print("[chaos:%s] starting %d replicas + 2 router cells"
              % (mode, NUM_REPLICAS))
        replica_ports = []
        for _ in range(NUM_REPLICAS):
            proc, port = start_replica(extra_env=env)
            procs.append(proc)
            replica_ports.append(port)
        for port in replica_ports:
            warm(port)
        # cell 0 seeds the journal with the fleet; cell 1 starts BLIND
        # (no --replica flags) and must learn every replica from replay
        cell0, port0 = start_router_cell(
            replica_ports, 0, 2, journal_dir, extra_env=env
        )
        procs.append(cell0)
        cell1, port1 = start_router_cell(
            [], 1, 2, journal_dir, extra_env=env
        )
        procs.append(cell1)
        stub1 = RouterStub(build_channel("localhost:%d" % port1))
        deadline = time.time() + 30
        st = None
        while time.time() < deadline:
            st = stub1.router_status(pb.RouterStatusRequest(),
                                     timeout=10)
            if st.replicas >= NUM_REPLICAS and st.healthy >= NUM_REPLICAS:
                break
            time.sleep(0.3)
        assert st is not None and st.replicas >= NUM_REPLICAS, (
            "cell 1 never learned the fleet from the journal: %s" % st
        )
        assert st.journal_replayed >= NUM_REPLICAS, (
            "cell 1 reports no adopt replay (journal_replayed=%d)"
            % st.journal_replayed
        )
        print("[chaos:%s] cell 1 learned %d replicas purely from "
              "journal replay (%d events)"
              % (mode, st.replicas, st.journal_replayed))

        front = CellFront(
            ["localhost:%d" % port0, "localhost:%d" % port1],
            reroute_window_secs=30.0, timeout_secs=CLIENT_TIMEOUT,
        )
        # one shared-prefix family: every request carries the same
        # full leading block, so every request fingerprints to the
        # same key and the ring pins the whole stream to ONE owning
        # cell — the one the drill kills.
        prefix = [3] * 16

        def prompt_for(i):
            return prefix + [1 + i % 5, 2]

        owner = front._targets(
            front._route_key(pb.GenerateRequest(prompt=prompt_for(0)))
        )[0][0]
        victim, victim_port = (
            (cell0, port0) if owner.endswith(":%d" % port0)
            else (cell1, port1)
        )
        survivor_port = port1 if victim is cell0 else port0
        print("[chaos:%s] prefix family owner is cell @ %s"
              % (mode, owner))

        rs = np.random.RandomState(7)
        outcomes = {}
        lock = threading.Lock()

        def call(i):
            try:
                # prompt is 18 tokens of the drill model's seq_len=32
                # budget: cap new tokens so prompt+new always fits
                front.generate(
                    pb.GenerateRequest(
                        prompt=prompt_for(i),
                        max_new_tokens=2 + i % 12,
                        seed=i,
                    ),
                    timeout=CLIENT_TIMEOUT,
                )
                code = "OK"
            except RouterError as e:
                code = e.code
            with lock:
                outcomes[i] = code

        threads = []
        t0 = time.monotonic()

        def launch(i):
            time.sleep(float(rs.exponential(1.0 / RATE_RPS)))
            t = threading.Thread(target=call, args=(i,))
            t.start()
            threads.append(t)

        i = 0
        for _ in range(WARMUP_REQS):
            launch(i)
            i += 1
        print("[chaos:%s] SIGKILL owning cell (port %d) mid-load"
              % (mode, victim_port))
        victim.kill()
        while i < REQUESTS:
            launch(i)
            i += 1

        for t in threads:
            t.join(timeout=CLIENT_TIMEOUT + 30)
        elapsed = time.monotonic() - t0
        hung = [t for t in threads if t.is_alive()]
        if hung:
            raise AssertionError(
                "[chaos:%s] %d client threads HUNG" % (mode, len(hung))
            )
        codes = sorted(outcomes.values())
        ok = codes.count("OK")
        print("[chaos:%s] outcomes=%s elapsed=%.1fs front=%s"
              % (mode, {c: codes.count(c) for c in set(codes)},
                 elapsed, front.counters))

        # THE invariant again, one tier up: a SIGKILL'd ROUTER CELL
        # must not lose a single accepted request — the front reroutes
        # to the surviving cell, which shares the same replica fleet.
        allowed = {"OK", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        leaked = set(codes) - allowed
        assert not leaked, (
            "accepted requests LOST across the cell kill: %s" % leaked
        )
        assert len(outcomes) == REQUESTS, (
            "only %d/%d clients terminated" % (len(outcomes), REQUESTS)
        )
        assert ok >= REQUESTS // 2, (
            "too few completions for a surviving cell: %d/%d OK"
            % (ok, REQUESTS)
        )
        assert elapsed < CLIENT_TIMEOUT - 10, "clients rode the timeout"
        assert front.counters["rerouted"] >= 1, (
            "the cell kill never forced a reroute — the drill "
            "exercised nothing"
        )

        # the survivor carried the rerouted tail
        surv = RouterStub(
            build_channel("localhost:%d" % survivor_port)
        ).router_status(pb.RouterStatusRequest(), timeout=10)
        assert surv.routed >= 1, "survivor cell never routed anything"

        # failover epilogue: the killed cell restarts with NO replica
        # flags and must rebuild its fleet view from the journal alone
        print("[chaos:%s] restarting killed cell from the journal"
              % mode)
        cell_id = 0 if victim is cell0 else 1
        reborn, reborn_port = start_router_cell(
            [], cell_id, 2, journal_dir, extra_env=env
        )
        procs.append(reborn)
        stub_r = RouterStub(build_channel("localhost:%d" % reborn_port))
        deadline = time.time() + 30
        rst = None
        while time.time() < deadline:
            rst = stub_r.router_status(pb.RouterStatusRequest(),
                                       timeout=10)
            if rst.replicas >= NUM_REPLICAS:
                break
            time.sleep(0.3)
        assert rst is not None and rst.replicas >= NUM_REPLICAS, (
            "reborn cell did not recover the fleet from the journal: "
            "%s" % rst
        )
        assert rst.cell_restarts >= 1, (
            "journal store never counted a cold start over existing "
            "state (cell_restarts=%d)" % rst.cell_restarts
        )
        print("[chaos:%s] reborn cell recovered %d replicas from the "
              "journal (restart #%d)"
              % (mode, rst.replicas, rst.cell_restarts))

        # graceful teardown: survivors drain and exit 0; the SIGKILL'd
        # cell's nonzero rc proves the kill was real
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            if proc is victim:
                continue
            rc = proc.wait(timeout=60)
            assert rc == 0, "graceful exit must return 0, got %s" % rc
        assert victim.wait(timeout=10) != 0  # SIGKILL, by design
        print("[chaos:%s] PASSED" % mode)
    finally:
        if front is not None:
            front.close()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


def main():
    import json
    import tempfile

    from elasticdl_tpu.observability.tracing import chrome_trace

    state = build_checkpoint_state()
    with tempfile.TemporaryDirectory(prefix="edl_chaos_") as tmp_root:
        for mode, env in (
            ("dense", {"EDL_KV_PAGED": "0"}),
            ("paged", {"EDL_KV_PAGED": "1"}),
        ):
            spans = run_mode(mode, env, state, tmp_root)
        # router-kill phase: same invariant one tier up — SIGKILL a
        # ROUTER CELL mid-load, zero accepted-request loss
        run_cell_failover(tmp_root)
    # archive the last mode's merged trace as the CI artifact — one
    # real chaos run, loadable at ui.perfetto.dev / chrome://tracing
    out = os.path.join(REPO, "ROUTER_CHAOS_TRACE.json")
    with open(out, "w") as f:
        json.dump(chrome_trace(spans), f)
    print("[chaos] merged trace archived -> %s" % out)
    print("[chaos] router chaos drill PASSED (dense + paged + cells): "
          "zero accepted-request loss under replica SIGKILL, hot "
          "reload, AND router-cell SIGKILL with journaled failover")
    return 0


if __name__ == "__main__":
    sys.exit(main())
