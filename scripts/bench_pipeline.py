"""Pipeline-schedule A/B: gpipe vs interleaved step time for the
transformer_pp family on a virtual pp mesh.

The interleaved (circular, Megatron-style) schedule runs vM + P - 1
ticks of 1/v-size chunk bodies vs GPipe's M + P - 1 full-stage ticks —
total stage-work (M + (P-1)/v) vs (M + P - 1). At the VERDICT-r04
comparison point (M=8, P=4, v=2) that is 9.5 vs 11 stage-times: ~14%
less work on an oversubscribed virtual mesh (where wall-clock tracks
TOTAL work, all virtual devices timesharing the host) and the same
ratio in fill/drain bubble on real chips (where wall-clock tracks the
critical path — the two views agree because every device's tick count
IS the critical path).

Run on the 8-device virtual CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python scripts/bench_pipeline.py

Prints one JSON line:
    {"metric": "pp_interleaved_speedup", "value": gpipe_ms/inter_ms,
     "gpipe_step_ms": ..., "interleaved_step_ms": ...,
     "work_ratio_expected": 11/9.5, ...}
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main():
    import jax

    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.common.timing_utils import fetch_sync
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_pp import transformer_pp as zoo

    n_dev = len(jax.devices())
    pp = 4 if n_dev % 4 == 0 else max(
        d for d in (2, 1) if n_dev % d == 0)
    dp = n_dev // pp
    m, v = 8, 2
    cfg = dict(
        vocab_size=512, seq_len=64, embed_dim=128, num_heads=4,
        num_layers=2 * pp * v, num_microbatches=m,
    )
    batch_size = dp * m  # per-device batch == m (microbatch size 1)
    iters, warmup = 10, 2

    rng = np.random.RandomState(0)
    tokens = rng.randint(
        0, cfg["vocab_size"], size=(batch_size, cfg["seq_len"] + 1)
    ).astype(np.int32)
    batch = ({"tokens": tokens[:, :-1]}, tokens[:, 1:])

    def measure(extra):
        mesh = mesh_lib.build_mesh({"dp": dp, "pp": pp})
        trainer = Trainer(
            load_model_spec_from_module(zoo),
            mesh=mesh,
            model_params=format_params_str(dict(cfg, **extra)),
        )
        state = trainer.init_state(batch)
        losses = []
        for _ in range(warmup):
            state, loss = trainer.train_step(state, batch)
            losses.append(float(loss))
        fetch_sync(state.params)
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = trainer.train_step(state, batch)
        fetch_sync(state.params)
        dt = (time.perf_counter() - t0) / iters
        assert np.isfinite(float(loss))
        return dt, losses[0]

    g_dt, g_loss0 = measure({})
    i_dt, _ = measure({"pp_schedule": "interleaved",
                       "pp_interleave": v})
    # expected work ratio: (M + P - 1) / (M + (P-1)/v) stage-times
    expected = (m + pp - 1) / (m + (pp - 1) / v)
    print(json.dumps({
        "metric": "pp_interleaved_speedup",
        "value": round(g_dt / i_dt, 4),
        "unit": "x (gpipe step time / interleaved step time)",
        "gpipe_step_ms": round(g_dt * 1e3, 2),
        "interleaved_step_ms": round(i_dt * 1e3, 2),
        "work_ratio_expected": round(expected, 4),
        "pp": pp, "dp": dp, "microbatches": m, "interleave": v,
        "num_layers": cfg["num_layers"],
        "n_devices": n_dev,
        "platform": jax.default_backend(),
        "first_loss_gpipe": round(g_loss0, 6),
    }))


if __name__ == "__main__":
    main()
