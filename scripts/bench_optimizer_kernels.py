"""On-chip microbenchmark: XLA-fused optax updates vs the Pallas dense
optimizer kernels (ops/optimizer_kernels.py), and (BENCH_SPARSE=1) the
XLA gather->update->scatter row path vs the Pallas sparse row kernels.

Answers VERDICT.md round-1 item #3's "wire them or retire them with
data": the reference's C++ Eigen kernels were its PS hot loop
(go/pkg/kernel/capi/kernel_api.cc:6-96), but on TPU the optimizer update
is fused by XLA into the compiled train step, so a standalone kernel
must beat the fused update to earn the Trainer slot.

Methodology (both matter on this rig):
* the mutable state is a CARRY donated back into the jit on every
  iteration (donate_argnums=0) — without donation XLA copies the whole
  buffer per call, and for the sparse case that ~512 MB table copy
  would swamp the ~4 MB of touched-row work being compared;
* the clock stops on a host FETCH of a carry-dependent scalar:
  block_until_ready can return early over the tunneled PJRT device
  (reads >10 TB/s effective HBM on small ops).

Run on hardware:  python scripts/bench_optimizer_kernels.py
                  BENCH_SPARSE=1 python scripts/bench_optimizer_kernels.py
Prints one JSON line per (path, size).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.ops import embedding_ops as eo
from elasticdl_tpu.ops import optimizer_kernels as ok
from elasticdl_tpu.ops import update_math as um


from elasticdl_tpu.common.timing_utils import fetch_sync as _fetch  # noqa: E402


def timed_carry(step, carry, iters=30, warmup=5):
    """step(carry) -> carry, jitted with the carry donated. Timing
    continues from the warmed carry (the pre-warmup buffers are consumed
    by donation)."""
    fn = jax.jit(step, donate_argnums=(0,))
    for _ in range(warmup):
        carry = fn(carry)
    _fetch(carry)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = fn(carry)
    _fetch(carry)
    return (time.perf_counter() - t0) / iters


def main():
    n = int(os.environ.get("N_PARAMS", str(64 * 1024 * 1024)))  # 64M f32
    rng = np.random.default_rng(0)
    # host originals: each timed run donates (consumes) its device
    # buffers, so every path gets a fresh device copy
    p_host = rng.standard_normal(n).astype(np.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32)

    def fresh_p():
        return jnp.asarray(p_host)

    results = []

    # --- SGD ---
    opt = optax.sgd(0.1)

    def optax_sgd(carry):
        p, s = carry
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    def pallas_sgd(carry):
        (p,) = carry
        return (ok.sgd_update(p, g, 0.1),)

    p0 = fresh_p()
    t_optax = timed_carry(optax_sgd, (p0, opt.init(p0)))
    t_pallas = timed_carry(pallas_sgd, (fresh_p(),))
    results.append(dict(optimizer="sgd", n=n,
                        optax_ms=round(t_optax * 1e3, 3),
                        pallas_ms=round(t_pallas * 1e3, 3)))

    # --- Adam ---
    aopt = optax.adam(1e-3)

    def optax_adam(carry):
        p, s = carry
        u, s = aopt.update(g, s, p)
        return optax.apply_updates(p, u), s

    def pallas_adam(carry):
        p, m, v = carry
        return ok.adam_update(p, m, v, g, step=1, lr=1e-3)

    p0 = fresh_p()
    t_optax = timed_carry(optax_adam, (p0, aopt.init(p0)))
    p1 = fresh_p()
    t_pallas = timed_carry(
        pallas_adam, (p1, jnp.zeros_like(p1), jnp.zeros_like(p1))
    )
    results.append(dict(optimizer="adam", n=n,
                        optax_ms=round(t_optax * 1e3, 3),
                        pallas_ms=round(t_pallas * 1e3, 3)))

    # HBM roofline: adam reads p,m,v,g and writes p,m,v = 7 arrays
    for r in results:
        n_bufs = 3 if r["optimizer"] == "sgd" else 7
        gb = n_bufs * n * 4 / 1e9
        r["optax_gbps"] = round(gb / (r["optax_ms"] / 1e3), 1)
        r["pallas_gbps"] = round(gb / (r["pallas_ms"] / 1e3), 1)
        r["platform"] = jax.default_backend()
        print(json.dumps(r))


def sparse_main():
    """Sparse row update: Pallas row kernels vs the XLA gather->update->
    scatter path the Trainer uses (embedding/sparse_update
    .row_sparse_apply). The table is the donated carry, so neither path
    pays a full-table copy — exactly the Trainer's situation (donated
    TrainState)."""
    vocab = int(os.environ.get("SPARSE_VOCAB", str(2_000_000)))
    dim = int(os.environ.get("SPARSE_DIM", "64"))
    n_ids = int(os.environ.get("SPARSE_IDS", "8192"))
    rng = np.random.default_rng(0)
    table_host = rng.standard_normal((vocab, dim)).astype(np.float32)
    ids = jnp.asarray(
        np.unique(rng.integers(0, vocab, size=n_ids)), jnp.int32
    )
    grads = jnp.asarray(
        rng.standard_normal((ids.shape[0], dim)), jnp.float32
    )

    def xla_sparse_sgd(carry):
        (table,) = carry
        rows = jnp.take(table, ids, axis=0)
        return (table.at[ids].set(um.sgd_math(rows, grads, 0.1)),)

    def pallas_sparse_sgd(carry):
        (table,) = carry
        return (eo.sparse_sgd_update(table, ids, grads, 0.1),)

    for name, step in (("xla", xla_sparse_sgd),
                       ("pallas", pallas_sparse_sgd)):
        t = timed_carry(step, (jnp.asarray(table_host),), iters=20)
        gb = 2 * ids.shape[0] * dim * 4 / 1e9  # touched rows r/w
        print(json.dumps(dict(
            path=name, vocab=vocab, dim=dim, n_rows=int(ids.shape[0]),
            ms=round(t * 1e3, 3), touched_gbps=round(gb / t, 2),
            platform=jax.default_backend(),
        )))


if __name__ == "__main__":
    if os.environ.get("BENCH_SPARSE") == "1":
        sparse_main()
    else:
        main()
