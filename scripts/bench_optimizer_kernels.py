"""On-chip microbenchmark: XLA-fused optax updates vs the Pallas dense
optimizer kernels (ops/optimizer_kernels.py).

Answers VERDICT.md round-1 item #3's "wire them or retire them with
data" for the *dense* kernels: the reference's C++ Eigen kernels were its
PS hot loop (go/pkg/kernel/capi/kernel_api.cc:6-96), but on TPU the
optimizer update is fused by XLA into the compiled train step, so a
standalone kernel must beat the fused update to earn the Trainer slot.

Run on hardware:  python scripts/bench_optimizer_kernels.py
Prints one JSON line per (optimizer, size) with both step times.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.ops import optimizer_kernels as ok


def timed(fn, p, *rest, iters=30, warmup=5):
    """Chain iterations through the updated param and stop the clock on a
    host fetch: over a tunneled PJRT device, block_until_ready can return
    before execution finishes, so ready-based timing of small ops reads
    absurdly fast (>10 TB/s effective HBM). A fetch of a dependent scalar
    is the only sync this rig honors."""

    def fetch(out):
        arr = out[0] if isinstance(out, tuple) else out
        return float(np.asarray(jax.device_get(arr[0])))

    x = p
    for _ in range(warmup):
        out = fn(x, *rest)
        x = out[0] if isinstance(out, tuple) else out
    fetch(out)
    t0 = time.perf_counter()
    x = p
    for _ in range(iters):
        out = fn(x, *rest)
        x = out[0] if isinstance(out, tuple) else out
    fetch(out)
    return (time.perf_counter() - t0) / iters


def main():
    n = int(os.environ.get("N_PARAMS", str(64 * 1024 * 1024)))  # 64M f32
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.standard_normal(n), jnp.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32)
    m = jnp.zeros_like(p)
    v = jnp.zeros_like(p)

    results = []

    # --- SGD ---
    opt = optax.sgd(0.1)
    opt_state = opt.init(p)

    @jax.jit
    def optax_sgd(p, g, s):
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    @jax.jit
    def pallas_sgd(p, g):
        return ok.sgd_update(p, g, 0.1)

    t_optax = timed(optax_sgd, p, g, opt_state)
    t_pallas = timed(pallas_sgd, p, g)
    results.append(dict(optimizer="sgd", n=n,
                        optax_ms=round(t_optax * 1e3, 3),
                        pallas_ms=round(t_pallas * 1e3, 3)))

    # --- Adam ---
    aopt = optax.adam(1e-3)
    astate = aopt.init(p)

    @jax.jit
    def optax_adam(p, g, s):
        u, s = aopt.update(g, s, p)
        return optax.apply_updates(p, u), s

    @jax.jit
    def pallas_adam(p, m, v, g):
        return ok.adam_update(p, m, v, g, step=1, lr=1e-3)

    t_optax = timed(optax_adam, p, g, astate)
    t_pallas = timed(pallas_adam, p, m, v, g)
    results.append(dict(optimizer="adam", n=n,
                        optax_ms=round(t_optax * 1e3, 3),
                        pallas_ms=round(t_pallas * 1e3, 3)))

    # HBM roofline: adam reads p,m,v,g and writes p,m,v = 7 arrays
    for r in results:
        n_bufs = 3 if r["optimizer"] == "sgd" else 7
        gb = n_bufs * n * 4 / 1e9
        r["optax_gbps"] = round(gb / (r["optax_ms"] / 1e3), 1)
        r["pallas_gbps"] = round(gb / (r["pallas_ms"] / 1e3), 1)
        r["platform"] = jax.default_backend()
        print(json.dumps(r))


if __name__ == "__main__":
    main()
