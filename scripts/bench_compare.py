#!/usr/bin/env python
"""Bench regression gate: compare a fresh serve-smoke record against
the committed baseline, per-metric tolerances, exit nonzero on
regression.

The serving bench has emitted `BENCH_SERVING.json` since PR 3, and
every PR's numbers have been eyeballed in review — nothing MACHINE-
checked that bytes/token, goodput, the observability overhead ratio or
tok/s stayed where the trajectory left them. This script starts the
bench trajectory as a CI gate:

    python scripts/bench_compare.py \\
        --fresh BENCH_SERVING.json \\
        --baseline benchmarks/serving_baseline.json

Semantics, tuned for a SHARED CPU CI box (the same reality that set
the autoscale drill's SLO margins):

* throughput metrics (tok/s, goodput) are noisy — the default
  tolerance is generous (30% relative) and catches collapses, not
  jitter;
* memory metrics (bytes/token) are DETERMINISTIC for a fixed workload
  — the tolerance is tight (10%), because a bytes/token regression is
  an algorithmic change, not scheduling noise;
* the observability overhead ratio and the steady-recompile count are
  ABSOLUTE bounds (>= 0.95, == 0): they are invariants, not
  trajectories, and no baseline drift may relax them;
* a metric the BASELINE lacks is reported as `new` and passes (the
  trajectory grows as benches grow); a metric the FRESH record lacks
  that the baseline has FAILS (a silently vanished bench leg is a
  regression of the gate itself).

Tolerances are overridable per metric (``--tol tokens_per_sec=0.5``)
so a deliberate trade (e.g. spending throughput to buy memory) can
land with its justification visible in the CI config rather than by
editing the gate. Update the baseline deliberately, with the PR that
improves it:

    make serve-smoke && cp BENCH_SERVING.json \\
        benchmarks/serving_baseline.json
"""

import argparse
import json
import sys

#: relative-tolerance metrics: (dotted path, direction, default tol).
#: direction "higher" = fresh must be >= baseline * (1 - tol);
#: "lower" = fresh must be <= baseline * (1 + tol).
RELATIVE_METRICS = (
    ("tokens_per_sec", "higher", 0.30),
    ("goodput_rps", "higher", 0.30),
    ("kv.bytes_per_token", "lower", 0.10),
    ("paged_shared.tokens_per_sec", "higher", 0.30),
    ("paged_shared.kv.bytes_per_token", "lower", 0.10),
    ("paged_int8.kv.bytes_per_token", "lower", 0.10),
    # bench_int8_scan.py records (the paged-attention microbench leg
    # of `make bench-compare`; absent from serving records, so these
    # rows are skipped there and bind only on that comparison).
    # The scan ratio is XLA-vs-XLA and stable; the fused ratios time
    # the Pallas INTERPRETER on the CPU gate (~100x XLA, python-loop
    # noise), so their tolerance is collapse-sized — they exist to
    # catch order-of-magnitude breakage (per-call retracing, fallback
    # silently engaging), and the TPU record tightens naturally when
    # a hardware baseline lands.
    ("paged_int8_vs_dense_deferred", "lower", 0.30),
    ("fused_int8_vs_paged_int8", "lower", 1.50),
    ("tile_fused_int8_vs_tile_paged_int8", "lower", 1.50),
)

#: absolute-bound metrics: (dotted path, op, bound) — invariants the
#: baseline can never relax.
ABSOLUTE_METRICS = (
    ("profiler_overhead.tokens_per_sec_ratio", ">=", 0.95),
    ("health.steady_recompiles", "==", 0),
)


def lookup(record, path):
    """Resolve a dotted path in a nested dict; None when any hop is
    missing or not a dict."""
    node = record
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def compare(fresh, baseline, tolerances=None):
    """Pure comparison: returns {"rows": [...], "regressions": [...],
    "ok": bool}. Each row: {metric, kind, fresh, baseline, bound,
    status} with status in ok|new|regression|missing_fresh."""
    tolerances = tolerances or {}
    rows = []

    for path, direction, default_tol in RELATIVE_METRICS:
        tol = float(tolerances.get(path, default_tol))
        f = lookup(fresh, path)
        b = lookup(baseline, path)
        row = {"metric": path, "kind": "relative:%s" % direction,
               "fresh": f, "baseline": b, "tolerance": tol}
        if b is None:
            if f is None:
                # absent from BOTH records: a metric of the other
                # record type (serving vs int8-scan share this gate)
                # — not a row at all, so each comparison's output
                # stays all-OK when nothing it measures moved.
                continue
            row["status"] = "new"
        elif f is None:
            row["status"] = "missing_fresh"
        else:
            f, b = float(f), float(b)
            if direction == "higher":
                bound = b * (1.0 - tol)
                ok = f >= bound
            else:
                bound = b * (1.0 + tol)
                ok = f <= bound
            row["bound"] = round(bound, 3)
            row["status"] = "ok" if ok else "regression"
        rows.append(row)

    for path, op, bound in ABSOLUTE_METRICS:
        f = lookup(fresh, path)
        row = {"metric": path, "kind": "absolute%s%s" % (op, bound),
               "fresh": f, "baseline": lookup(baseline, path),
               "bound": bound}
        if f is None:
            # absolute invariants bind only when the fresh record
            # carries the leg (e.g. --overhead_ab off in a quick run);
            # the baseline having it makes absence a failure, absence
            # from both (an int8-scan record) drops the row
            if lookup(baseline, path) is None:
                continue
            row["status"] = "missing_fresh"
        else:
            f = float(f)
            ok = f >= bound if op == ">=" else f == bound
            row["status"] = "ok" if ok else "regression"
        rows.append(row)

    regressions = [r for r in rows
                   if r["status"] in ("regression", "missing_fresh")]
    return {"rows": rows, "regressions": regressions,
            "ok": not regressions}


def render(result):
    lines = []
    for r in result["rows"]:
        lines.append(
            "%-45s %-18s fresh=%-12s base=%-12s %s"
            % (r["metric"], r["kind"],
               r["fresh"] if r["fresh"] is not None else "-",
               r["baseline"] if r["baseline"] is not None else "-",
               r["status"].upper())
        )
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    parser.add_argument("--fresh", default="BENCH_SERVING.json")
    parser.add_argument("--baseline",
                        default="benchmarks/serving_baseline.json")
    parser.add_argument(
        "--tol", action="append", default=[],
        metavar="METRIC=TOL",
        help="override one metric's relative tolerance "
             "(repeatable), e.g. --tol tokens_per_sec=0.5",
    )
    parser.add_argument("--out", default="",
                        help="also write the comparison JSON here")
    args = parser.parse_args(argv)

    tolerances = {}
    for item in args.tol:
        key, _, value = item.partition("=")
        try:
            tolerances[key] = float(value)
        except ValueError:
            parser.error("bad --tol %r (want METRIC=FLOAT)" % item)

    with open(args.fresh) as f:
        fresh = json.load(f)
    with open(args.baseline) as f:
        baseline = json.load(f)
    result = compare(fresh, baseline, tolerances)
    print(render(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    if not result["ok"]:
        print("bench_compare: %d regression(s) vs %s"
              % (len(result["regressions"]), args.baseline),
              file=sys.stderr)
        return 1
    print("bench_compare: within tolerance of %s" % args.baseline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
