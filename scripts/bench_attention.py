"""Flash-attention kernel tuning bench (run on real TPU).

Sweeps block sizes for the Pallas forward + two-pass backward at the
flagship shape and compares against the XLA blockwise path and jax's
bundled TPU flash kernel. Timing is fetch-forced (block_until_ready can
return early over the tunneled PJRT plugin — see BENCHNOTES.md).

Usage:  python scripts/bench_attention.py [b h s d]

`--paged` instead sweeps the fused paged decode kernel's query-row
tile (attention.resolve_paged_rows: the sublane occupancy knob) over
the serving decode shapes — the legacy single-token step and the
verify-k tile — against the lax.scan fallback, and with `--write`
persists the winner into ops/flash_tuning.json under "paged_rows",
exactly like the flash block sizes. Without a tuned entry the kernel
uses the CPU-SAFE default of 8 rows (one f32 sublane tile, the
smallest legal Mosaic row tile — correct everywhere, fuller tiles are
a hardware-measured upgrade). Run the sweep on real TPU: off-TPU the
kernel interprets and the timings only rank interpreter overhead.

Usage:  python scripts/bench_attention.py --paged [--write] \\
            [b h hkv d L bs t]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from elasticdl_tpu.common.timing_utils import fetch_sync as fetch  # noqa: E402


def timed(fn, args, iters=20):
    out = fn(*args)
    fetch(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fetch(out)
    return (time.perf_counter() - t0) / iters


def paged_sweep(argv, write):
    """Sweep resolve_paged_rows candidates for _paged_decode_fused on
    the two serving decode shapes; optionally persist the winner."""
    import json

    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import paged_decode_attention

    try:
        shape = [int(a) for a in argv] or [8, 8, 8, 128, 2048, 16, 4]
        b, h, hkv, d, L, bs, t = shape
    except ValueError:
        sys.exit("usage: bench_attention.py --paged [b h hkv d L bs t]")
    if L % bs:
        sys.exit("--paged needs L %% bs == 0")
    rs = np.random.RandomState(0)
    nb = b * (L // bs)
    table = jnp.asarray(
        np.arange(nb, dtype=np.int32).reshape(b, L // bs)
    )
    length = jnp.full((b,), L, jnp.int32)
    k_pool = jnp.asarray(rs.randn(nb, bs, hkv, d).astype(np.float32))
    v_pool = jnp.asarray(rs.randn(nb, bs, hkv, d).astype(np.float32))

    def legs(tq):
        q = jnp.asarray(rs.randn(b, h, tq, d).astype(np.float32))
        kc = jnp.asarray(rs.randn(b, hkv, tq, d).astype(np.float32))
        vc = jnp.asarray(rs.randn(b, hkv, tq, d).astype(np.float32))
        if tq == 1:  # legacy single-token shape
            q, kc, vc = q[:, :, 0], kc[:, :, 0], vc[:, :, 0]
        return q, kc, vc, k_pool, v_pool, table, length

    results = {}
    for tq in (1, t):
        inputs = legs(tq)
        scan = jax.jit(lambda *a: paged_decode_attention(
            *a, use_kernel=False))
        t_scan = timed(scan, inputs)
        print("t=%-3d scan (lax.scan oracle)          %8.1f us"
              % (tq, t_scan * 1e6))
        for rows in (8, 16, 32, 64):
            # rows threads through the EDL_PAGED_ROWS env knob, read
            # by resolve_paged_rows at trace time (first timed call)
            os.environ["EDL_PAGED_ROWS"] = str(rows)
            try:
                t_fused = timed(jax.jit(lambda *a: paged_decode_attention(
                    *a, use_kernel=True)), inputs)
            except Exception as e:  # noqa: BLE001
                print("t=%-3d rows=%-3d FAILED: %r"
                      % (tq, rows, repr(e)[:80]))
                continue
            finally:
                os.environ.pop("EDL_PAGED_ROWS", None)
            results.setdefault(rows, 0.0)
            results[rows] += t_fused
            print("t=%-3d rows=%-3d fused                  %8.1f us"
                  " (%.2fx scan)"
                  % (tq, rows, t_fused * 1e6, t_fused / t_scan))
    if not results:
        sys.exit("--paged: every fused config failed")
    best = min(results, key=results.get)
    print("winner: paged_rows=%d (summed %0.1f us over both shapes)"
          % (best, results[best] * 1e6))
    if write:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), os.pardir,
            "elasticdl_tpu", "ops", "flash_tuning.json",
        )
        with open(path) as f:
            tuning = json.load(f)
        tuning["paged_rows"] = best
        tuning["paged_tuned_on"] = "%s b=%d h=%d hkv=%d d=%d L=%d " \
            "bs=%d t=%d" % (jax.default_backend(), b, h, hkv, d, L,
                            bs, t)
        with open(path, "w") as f:
            json.dump(tuning, f)
            f.write("\n")
        print("wrote paged_rows=%d to %s" % (best, path))


def main():
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import (
        blockwise_attention,
        flash_attention,
    )

    args = sys.argv[1:5]
    if args and len(args) != 4:
        sys.exit("usage: bench_attention.py [b h s d]")
    try:
        shape = [int(a) for a in args] or [32, 8, 1024, 128]
    except ValueError:
        sys.exit("usage: bench_attention.py [b h s d] (ints)")
    b, h, s, d = shape
    rs = np.random.RandomState(0)

    def mk():
        return jnp.asarray(
            rs.randn(b, h, s, d).astype(np.float32) * 0.1, jnp.bfloat16
        )

    q, k, v = mk(), mk(), mk()
    flops_fwd = 2 * 2 * b * h * s * s * d / 2  # causal
    print("shape b=%d h=%d s=%d d=%d   causal fwd %.1f GFLOP"
          % (b, h, s, d, flops_fwd / 1e9))

    def report(tag, t_f, t_b):
        print("%-34s fwd %7.2f ms (%5.1f TF/s)   fwd+bwd %7.2f ms"
              % (tag, t_f * 1e3, flops_fwd / t_f / 1e12, t_b * 1e3))

    def bench_pair(mk_fn, tag):
        fwd = jax.jit(mk_fn)
        grad = jax.jit(jax.grad(
            lambda q, k, v: mk_fn(q, k, v).astype(jnp.float32).mean(),
            argnums=(0, 1, 2),
        ))
        try:
            report(tag, timed(fwd, (q, k, v)), timed(grad, (q, k, v)))
        except Exception as e:  # noqa: BLE001
            print("%-34s FAILED: %r" % (tag, repr(e)[:90]))

    for bq, bk in [(64, 128), (64, 256), (64, 512),
                   (128, 128), (128, 256), (128, 512), (256, 256),
                   (256, 512), (512, 512), (256, 1024), (512, 1024),
                   (1024, 1024), (1024, 512), (128, 1024)]:
        if s % bq or s % bk:
            continue
        bench_pair(
            lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk
            ),
            "ours pallas bq=%d bk=%d" % (bq, bk),
        )

    bench_pair(
        lambda q, k, v: blockwise_attention(q, k, v, causal=True),
        "xla blockwise (scan)",
    )
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash,
        )

        sm = 1.0 / np.sqrt(d)
        bench_pair(
            lambda q, k, v: jax_flash(q, k, v, causal=True, sm_scale=sm),
            "jax bundled flash",
        )
    except ImportError:
        pass


if __name__ == "__main__":
    _argv = sys.argv[1:]
    if "--paged" in _argv:
        _argv.remove("--paged")
        _write = "--write" in _argv
        if _write:
            _argv.remove("--write")
        paged_sweep(_argv, _write)
    else:
        main()
