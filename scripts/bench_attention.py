"""Flash-attention kernel tuning bench (run on real TPU).

Sweeps block sizes for the Pallas forward + two-pass backward at the
flagship shape and compares against the XLA blockwise path and jax's
bundled TPU flash kernel. Timing is fetch-forced (block_until_ready can
return early over the tunneled PJRT plugin — see BENCHNOTES.md).

Usage:  python scripts/bench_attention.py [b h s d]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from elasticdl_tpu.common.timing_utils import fetch_sync as fetch  # noqa: E402


def timed(fn, args, iters=20):
    out = fn(*args)
    fetch(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fetch(out)
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import (
        blockwise_attention,
        flash_attention,
    )

    args = sys.argv[1:5]
    if args and len(args) != 4:
        sys.exit("usage: bench_attention.py [b h s d]")
    try:
        shape = [int(a) for a in args] or [32, 8, 1024, 128]
    except ValueError:
        sys.exit("usage: bench_attention.py [b h s d] (ints)")
    b, h, s, d = shape
    rs = np.random.RandomState(0)

    def mk():
        return jnp.asarray(
            rs.randn(b, h, s, d).astype(np.float32) * 0.1, jnp.bfloat16
        )

    q, k, v = mk(), mk(), mk()
    flops_fwd = 2 * 2 * b * h * s * s * d / 2  # causal
    print("shape b=%d h=%d s=%d d=%d   causal fwd %.1f GFLOP"
          % (b, h, s, d, flops_fwd / 1e9))

    def report(tag, t_f, t_b):
        print("%-34s fwd %7.2f ms (%5.1f TF/s)   fwd+bwd %7.2f ms"
              % (tag, t_f * 1e3, flops_fwd / t_f / 1e12, t_b * 1e3))

    def bench_pair(mk_fn, tag):
        fwd = jax.jit(mk_fn)
        grad = jax.jit(jax.grad(
            lambda q, k, v: mk_fn(q, k, v).astype(jnp.float32).mean(),
            argnums=(0, 1, 2),
        ))
        try:
            report(tag, timed(fwd, (q, k, v)), timed(grad, (q, k, v)))
        except Exception as e:  # noqa: BLE001
            print("%-34s FAILED: %r" % (tag, repr(e)[:90]))

    for bq, bk in [(64, 128), (64, 256), (64, 512),
                   (128, 128), (128, 256), (128, 512), (256, 256),
                   (256, 512), (512, 512), (256, 1024), (512, 1024),
                   (1024, 1024), (1024, 512), (128, 1024)]:
        if s % bq or s % bk:
            continue
        bench_pair(
            lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk
            ),
            "ours pallas bq=%d bk=%d" % (bq, bk),
        )

    bench_pair(
        lambda q, k, v: blockwise_attention(q, k, v, causal=True),
        "xla blockwise (scan)",
    )
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash,
        )

        sm = 1.0 / np.sqrt(d)
        bench_pair(
            lambda q, k, v: jax_flash(q, k, v, causal=True, sm_scale=sm),
            "jax bundled flash",
        )
    except ImportError:
        pass


if __name__ == "__main__":
    main()
