#!/usr/bin/env python
"""Poll a submitted job's pods until the master finishes — the CI
validation step after `elasticdl-tpu train` (reference
scripts/validate_job_status.py, 171 LoC: polls pod phases via the k8s
API and exits nonzero if the job failed).

Usage: validate_job_status.py <job_name> [namespace] [timeout_secs]
"""

import sys
import time

from elasticdl_tpu.common.k8s_client import Client


def validate(job_name, namespace="default", timeout=1800,
             poll_interval=10, core_api=None):
    client = Client(
        image_name="", namespace=namespace, job_name=job_name,
        core_api=core_api,
    )
    deadline = time.time() + timeout
    master_name = client.get_master_pod_name()
    while time.time() < deadline:
        pod = client.get_pod(master_name)
        if pod is None:
            print("master pod %s not found" % master_name)
            time.sleep(poll_interval)
            continue
        status = (
            pod.get("status", {}) if isinstance(pod, dict)
            else pod.status
        )
        phase = (
            status.get("phase") if isinstance(status, dict)
            else status.phase
        )
        print("master phase: %s" % phase)
        if phase == "Succeeded":
            return 0
        if phase == "Failed":
            return 1
        time.sleep(poll_interval)
    print("timed out after %ds" % timeout)
    return 2


if __name__ == "__main__":
    job = sys.argv[1]
    ns = sys.argv[2] if len(sys.argv) > 2 else "default"
    t = int(sys.argv[3]) if len(sys.argv) > 3 else 1800
    sys.exit(validate(job, ns, t))
