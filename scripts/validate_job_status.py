#!/usr/bin/env python
"""Poll a submitted job until the master finishes — the CI validation
step after `elasticdl-tpu train` (reference scripts/
validate_job_status.py, 171 LoC: polls pod phases via the k8s API and
exits nonzero if the job failed).

Two modes, same phase semantics (Pending/Running/Succeeded/Failed):

    validate_job_status.py <job_name> [namespace] [timeout_secs]
        k8s mode: polls the master pod's phase.

    validate_job_status.py --status_file <path> [timeout_secs] [pid]
        local mode: polls the JSON status file the local master writes
        when started with --job_status_file (the no-cluster twin of the
        master-pod status label); with [pid], fails fast when that
        master process dies without a terminal phase. Used by
        scripts/build_and_test.sh.

Exit codes: 0 Succeeded, 1 Failed, 2 timeout, 3 master died silently.
"""

import os
import sys
import time


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def validate_status_file(path, timeout=1800, poll_interval=1.0, pid=None):
    """Local mode: poll the master's --job_status_file until a terminal
    phase (common/job_status.py write/read). With `pid`, also watch the
    master process: a master that dies without writing a terminal phase
    (bad flag, OOM kill) fails fast (rc 3) instead of burning the whole
    timeout."""
    from elasticdl_tpu.common.job_status import (
        FAILED,
        SUCCEEDED,
        read_job_status,
    )

    def check(status):
        phase = status.get("status") if status else None
        if phase == SUCCEEDED:
            return 0
        if phase == FAILED:
            return 1
        return None

    deadline = time.time() + timeout
    last = object()
    while time.time() < deadline:
        status = read_job_status(path)
        phase = status.get("status") if status else None
        if phase != last:
            print("job phase: %s" % phase)
            last = phase
        rc = check(status)
        if rc is not None:
            return rc
        if pid is not None and not _alive(pid):
            # grace re-read: the terminal write may have just landed
            time.sleep(poll_interval)
            rc = check(read_job_status(path))
            if rc is not None:
                return rc
            print("master process %d exited without terminal status" % pid)
            return 3
        time.sleep(poll_interval)
    print("timed out after %ds" % timeout)
    return 2


def validate(job_name, namespace="default", timeout=1800,
             poll_interval=10, core_api=None):
    from elasticdl_tpu.common.k8s_client import Client

    client = Client(
        image_name="", namespace=namespace, job_name=job_name,
        core_api=core_api,
    )
    deadline = time.time() + timeout
    master_name = client.get_master_pod_name()
    while time.time() < deadline:
        pod = client.get_pod(master_name)
        if pod is None:
            print("master pod %s not found" % master_name)
            time.sleep(poll_interval)
            continue
        status = (
            pod.get("status", {}) if isinstance(pod, dict)
            else pod.status
        )
        phase = (
            status.get("phase") if isinstance(status, dict)
            else status.phase
        )
        print("master phase: %s" % phase)
        if phase == "Succeeded":
            return 0
        if phase == "Failed":
            return 1
        time.sleep(poll_interval)
    print("timed out after %ds" % timeout)
    return 2


if __name__ == "__main__":
    sys.path.insert(
        0,
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if sys.argv[1] == "--status_file":
        path = sys.argv[2]
        t = int(sys.argv[3]) if len(sys.argv) > 3 else 1800
        pid = int(sys.argv[4]) if len(sys.argv) > 4 else None
        sys.exit(validate_status_file(path, t, pid=pid))
    job = sys.argv[1]
    ns = sys.argv[2] if len(sys.argv) > 2 else "default"
    t = int(sys.argv[3]) if len(sys.argv) > 3 else 1800
    sys.exit(validate(job, ns, t))
