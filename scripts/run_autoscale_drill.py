#!/usr/bin/env python
"""Elastic-fleet AUTOSCALE drill: capacity follows traffic, with zero
accepted-request loss and a bounded p99 TTFT across every
replica-count change.

Runs the REAL stack: an in-process Router (real gRPC transport) whose
fleet is owned by the replica supervisor (serving/autoscaler.py),
which spawns `elasticdl_tpu.serving.main` replica SUBPROCESSES,
journals every lifecycle transition, and scales on the router's own
load signals. The drill ramps an open-loop piecewise-Poisson unary
load through the router (the SAME generator bench_serving --ramp
uses) and forces every transition the autoscaler claims to survive:

  * RAMP UP   — the high phase is calibrated to ~1.3x one replica's
    measured capacity, so the queue-wait EWMA rises and the policy
    MUST scale up (>=1 scale_up, live grows);
  * SUPERVISOR CRASH — mid-drill the supervisor is abandoned (the
    journal and replica processes left exactly as SIGKILL would leave
    them) and a FRESH supervisor recovers from the journal: it must
    RE-ADOPT the same replica pids — no double-spawn, no orphan;
  * REPLICA SIGKILL — a live replica is SIGKILLed under load; the
    supervisor must reap and REPLACE it (replacements >= 1, live back
    to target) while the router re-dispatches its in-flight work;
  * RAMP DOWN — the load drops; sustained idle (+ free-KV headroom)
    must trigger >=1 DRAIN-based scale-down: SIGTERM, drain
    advertisement, exit 0, retire — journaled `begin_drain`->`retire`
    with rc=0, never a kill of live work.

Asserted invariants, all phases:

  * zero accepted-request loss — every unary outcome is OK /
    RESOURCE_EXHAUSTED / DEADLINE_EXCEEDED, never a raw transport
    code, never a hang (the router-chaos-drill contract, held while
    the fleet ITSELF changes size);
  * p99 TTFT SLO — per-WINDOW p99 TTFT (replica histogram buckets
    delta'd between transition checkpoints, merged fleet-wide by
    addition) stays under SLO_TTFT_P99_MS for every window with
    samples. Replicas warm up BEFORE advertising ready
    (--warmup_tokens), so no window pays a jit compile;
  * the run is TRACED end-to-end (PR 6 span machinery): replica
    `serve` spans parent under router `dispatch` spans in the merged
    export, and every exported request root is terminal with an
    explicit status;
  * the LIVE METRICS PLANE is scraped mid-drill: the router's
    /metrics exposition (Prometheus text, stdlib server) is fetched
    at every transition checkpoint, parsed by the INDEPENDENT
    text-format parser (observability/promparse.py — shares nothing
    with the renderer), and the SLO burn-rate series
    (edl_router_slo_burn{slo=...,window=fast|slow}) must be present
    and FINITE at every point across the ramp — the burn trajectory
    is archived in the report;
  * the TAIL-FORENSICS loop closes end-to-end: the replacement
    checkpoint's scrape must carry >=1 parseable OpenMetrics
    exemplar; a fleet-collector bundle scraped LIVE under load
    becomes an incident report after teardown whose exemplar
    trace_ids resolve to RETAINED traces in the span dump, each
    yielding a dominant forensics.attribute() cause, with complete
    span evidence and a passing validate_report schema gate.

The scale timeline, per-phase client percentiles and per-window
server p99s are archived at AUTOSCALE_REPORT.json (repo root); the
collector's full exemplar join, per-trace attributions and cause
histogram land next to it at INCIDENT_REPORT.json (+ .txt).

Usage: python scripts/run_autoscale_drill.py
Exit 0 = every invariant holds."""

import json
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from bench_serving import parse_ramp, ramp_arrivals  # noqa: E402

CLIENT_TIMEOUT = 120.0  # backstop; the drill asserts we stay far under
# Per-window p99 TTFT bound. The backlog a calibrated 1.3x overload
# builds scales with HIGH_SECS AND with whatever else the shared CI
# container is doing: the PR 9-11 green runs crept from ~34 s to
# 42.6 s against the original 45 s bound (a <6% margin that plain
# machine variance then broke at 45.2/49.2 s with the fleet behaving
# perfectly — zero loss, scale-up/replacement/drain all on time). 60 s
# keeps the invariant meaningful — a fleet that FAILS to scale keeps
# accumulating backlog through the tail phase and blows far past it —
# without re-failing the drill every time the container is busy.
SLO_TTFT_P99_MS = 60_000.0
HIGH_SECS = 35.0
LEAD_SECS = 6.0
TAIL_SECS = 30.0
MAX_REPLICAS = 2  # 1 -> 2 -> (replace) -> 1 is the whole story; a
# small ceiling also keeps the drill honest on single-core CI, where
# each extra spawn's jit compile steals serving time

# heavy enough that one single-slot replica saturates at a few req/s
# on CPU — the ramp's high phase is calibrated to ~1.3x that, so the
# scale-up is forced on any machine speed while a non-scaling fleet
# would blow straight through the TTFT SLO
DRILL_MODEL_PARAMS = (
    "vocab_size=64; seq_len=64; embed_dim=512; num_heads=8; "
    "num_layers=6"
)
# EDL_KV_CACHE_DTYPE=int8 runs the whole fleet on QUANTIZED paged
# arenas (int8 rows + f32 scale leaves): supervision, drain-based
# scale-down, SIGKILL replacement and journal re-adoption must all
# hold with scale leaves in the arenas. `make drill` sets it, so the
# drill suite covers both arena dtypes (fp paged rides the kill and
# router-chaos drills).
KV_CACHE_DTYPE = os.environ.get("EDL_KV_CACHE_DTYPE", "")
if KV_CACHE_DTYPE:
    DRILL_MODEL_PARAMS += "; kv_cache_dtype=%r" % KV_CACHE_DTYPE


def replica_args():
    return [
        "--model_zoo", os.path.join(REPO, "model_zoo"),
        "--model_def", "transformer_lm.transformer_lm.custom_model",
        "--model_params", DRILL_MODEL_PARAMS,
        "--port", "0", "--num_slots", "1", "--queue_capacity", "128",
        "--kv_block_size", "4",
        # the gRPC pool must exceed the worst-case in-flight RPC count
        # (~ queue_capacity), or blocked generate handlers starve
        # server_status and the router reads lease decay into a
        # perfectly healthy, merely saturated replica
        "--max_workers", "256",
        # pay the jit compile BEFORE advertising ready: a freshly
        # adopted replica must never serve live traffic cold
        "--warmup_tokens", "4",
    ]


def wait_for(cond, timeout, what, poll=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = cond()
        if got:
            return got
        time.sleep(poll)
    raise AssertionError("timed out after %.0fs waiting for %s"
                         % (timeout, what))


class FleetWatch(object):
    """Samples router_status on a thread: scale-decision timeline for
    the report, plus last-seen state for the orchestration waits."""

    def __init__(self, stub, pb):
        self._stub = stub
        self._pb = pb
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.timeline = []
        self._last = None
        self._t0 = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                st = self._stub.router_status(
                    self._pb.RouterStatusRequest(), timeout=10
                )
            except Exception:  # noqa: BLE001 - keep sampling
                self._stop.wait(0.5)
                continue
            a = st.autoscaler
            snap = {
                "t": round(time.monotonic() - self._t0, 2),
                "target": a.target, "live": a.live,
                "starting": a.starting, "draining": a.draining,
                "scale_ups": a.scale_ups,
                "scale_downs": a.scale_downs,
                "replacements": a.replacements,
                "last_decision": a.last_decision,
                "healthy": st.healthy,
            }
            with self._lock:
                keys = [k for k in snap if k != "t"]
                if (self._last is None
                        or any(snap[k] != self._last[k] for k in keys)):
                    self.timeline.append(snap)
                self._last = snap
            self._stop.wait(0.5)

    def last(self):
        with self._lock:
            return dict(self._last) if self._last else None

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


class TtftWindows(object):
    """Per-transition p99 TTFT from the replicas' mergeable histogram
    buckets: at each checkpoint the fleet's cumulative buckets (last
    seen per address, so a killed replica's history is kept) are
    delta'd against the previous checkpoint and the WINDOW p99 read
    off the merged delta — percentile of counts, never an average."""

    def __init__(self, router):
        from elasticdl_tpu.observability.histogram import (
            LogLinearHistogram,
        )

        self._hist_cls = LogLinearHistogram
        self._router = router
        self._by_addr = {}
        self._prev = None
        self.windows = []

    def _fleet_cum(self):
        for rep in self._router.replicas():
            if rep.ttft_hist:
                self._by_addr[rep.address] = list(rep.ttft_hist)
        width = max([len(c) for c in self._by_addr.values()] or [0])
        cum = [0] * width
        for counts in self._by_addr.values():
            for i, n in enumerate(counts):
                cum[i] += n
        return cum

    def checkpoint(self, name):
        cum = self._fleet_cum()
        prev = self._prev or []
        delta = [
            max(0, c - (prev[i] if i < len(prev) else 0))
            for i, c in enumerate(cum)
        ]
        self._prev = cum
        hist = self._hist_cls.from_counts(delta)
        self.windows.append({
            "window": name,
            "samples": hist.count,
            "ttft_p50_ms": hist.percentile(50),
            "ttft_p99_ms": hist.percentile(99),
        })
        print("[autoscale] window %-18s samples=%-4d p99 TTFT=%s ms"
              % (name, hist.count, hist.percentile(99)))


class MetricsScrapes(object):
    """Mid-drill scrapes of the router's /metrics exposition. Every
    scrape must PARSE through the independent text-format parser
    (observability/promparse.py validates histogram monotonicity,
    counter naming, label grammar — any violation raises), carry the
    families the metrics plane promises, and show a FINITE burn-rate
    value for every SLO x window. The points accumulate into the
    report as the burn trajectory across the ramp."""

    REQUIRED_FAMILIES = (
        "edl_router_routed_total",    # closed counter set
        "edl_router_healthy_replicas",  # closed gauge set
        "edl_router_e2e_ms",          # histogram (_bucket/_sum/_count)
        "edl_router_fleet_ttft_ms",   # fleet-merged replica buckets
        "edl_router_slo_burn",        # the burn-rate engine
        "edl_autoscaler_target",      # supervisor block rides along
    )

    def __init__(self, port):
        self._url = "http://127.0.0.1:%d/metrics" % port
        self.points = []

    def scrape(self, name):
        import math
        import urllib.request

        from elasticdl_tpu.observability.promparse import (
            parse_prometheus_text,
        )

        text = urllib.request.urlopen(
            self._url, timeout=10
        ).read().decode("utf-8")
        fams = parse_prometheus_text(text)  # raises on malformation
        for fam in self.REQUIRED_FAMILIES:
            assert fam in fams, (
                "scrape %r: family %s missing from the exposition"
                % (name, fam)
            )
        burns = {}
        for _metric, labels, value in (
                fams["edl_router_slo_burn"]["samples"]):
            assert math.isfinite(value), (
                "scrape %r: non-finite burn rate for %r"
                % (name, labels)
            )
            burns["%s/%s" % (labels["slo"], labels["window"])] = (
                round(value, 4)
            )
        for key in ("ttft_p99/fast", "ttft_p99/slow",
                    "e2e_p99/fast", "goodput/fast"):
            assert key in burns, (
                "scrape %r: burn series %s absent" % (name, key)
            )
        # exemplar-linked buckets (the forensics loop's metrics end):
        # the independent parser already validated their grammar and
        # bucket-range; keep the trace ids so the post-teardown
        # assertions can resolve them against the span dump
        exemplars = [
            {"family": fam, "trace_id": ex_labels["trace_id"],
             "value_ms": value, "le": labels.get("le")}
            for fam, info in fams.items()
            for _m, labels, ex_labels, value, _ts in info["exemplars"]
            if "trace_id" in ex_labels
        ]
        self.points.append({
            "at": name,
            "families": len(fams),
            "burns": burns,
            "exemplars": len(exemplars),
            "exemplar_rows": exemplars,
        })
        print("[autoscale] /metrics @ %-12s %d families, "
              "ttft_p99 burn fast=%.2f slow=%.2f, %d exemplars"
              % (name, len(fams), burns["ttft_p99/fast"],
                 burns["ttft_p99/slow"], len(exemplars)))


def calibrate(stub, pb):
    """Measured single-replica unary throughput (req/s): 2 waves of 3
    concurrent requests. The ramp rates derive from it, so the high
    phase overloads one replica on ANY machine speed."""
    def one():
        stub.router_generate(
            pb.GenerateRequest(prompt=[1, 2], max_new_tokens=8),
            timeout=60,
        )

    t0 = time.monotonic()
    for _ in range(2):
        ts = [threading.Thread(target=one) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=90)
    wall = max(time.monotonic() - t0, 1e-3)
    rate = 6.0 / wall
    print("[autoscale] calibration: %.1f req/s single-replica" % rate)
    return rate


def main():
    import tempfile

    import numpy as np

    from elasticdl_tpu.observability.tracing import configure, recorder
    from elasticdl_tpu.observability.histogram import percentiles
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel
    from elasticdl_tpu.serving.autoscaler import (
        AutoscalerConfig,
        ReplicaSupervisor,
        SubprocessReplicaLauncher,
    )
    from elasticdl_tpu.serving.router import Router, RouterConfig

    tmp_root = tempfile.mkdtemp(prefix="edl_autoscale_")
    journal_dir = os.path.join(tmp_root, "journal")
    trace_dir = os.path.join(tmp_root, "traces")
    os.makedirs(trace_dir, exist_ok=True)
    os.environ["EDL_TRACE_DIR"] = trace_dir
    configure(service="autoscale-drill")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["EDL_KV_PAGED"] = "1"
    env["EDL_TRACE_DIR"] = trace_dir
    env.pop("PYTHONPATH", None)

    def make_launcher():
        return SubprocessReplicaLauncher(
            replica_args(), log_dir=os.path.join(tmp_root, "logs"),
            env=env, cwd=REPO,
        )

    def make_config():
        return AutoscalerConfig(
            min_replicas=1, max_replicas=MAX_REPLICAS, decide_secs=0.25,
            up_queue_wait_ms=150.0, up_queue_depth=4,
            up_window_secs=1.0,
            idle_queue_wait_ms=120.0, down_window_secs=4.0,
            down_free_kv_blocks=1,
            cooldown_secs=4.0, ready_timeout_secs=240.0,
            drain_timeout_secs=90.0, wedged_after_secs=30.0,
            max_restarts=3, journal_dir=journal_dir,
        )

    router = Router([], RouterConfig(
        poll_secs=0.25, poll_timeout_secs=2.0, lease_secs=2.0,
        breaker_cooldown_secs=1.0, redispatch_window_secs=60.0,
        # one worker per worst-case concurrent client + status margin
        max_workers=384,
        # the live metrics plane under drill: ephemeral /metrics port,
        # SLO objectives on the drill's own TTFT bound with windows
        # scaled to the ramp (fast must fit inside the high phase)
        metrics_port=0,
        slo_ttft_p99_ms=SLO_TTFT_P99_MS,
        slo_e2e_p99_ms=2 * SLO_TTFT_P99_MS,
        slo_fast_window_secs=10.0,
        slo_slow_window_secs=40.0,
    )).start(grpc_server=True)
    sup = ReplicaSupervisor(router, make_launcher(), make_config())
    router.set_autoscaler(sup)
    sup.start()
    stub = RouterStub(build_channel("localhost:%d" % router.port))
    watch = None

    def fleet():
        return stub.router_status(
            pb.RouterStatusRequest(), timeout=20
        ).autoscaler

    def fleet_when(pred, timeout, what):
        """wait_for over the autoscaler block, tolerant of a status
        RPC starved behind a saturation burst: a failed poll is 'not
        yet', not a drill failure."""
        def cond():
            try:
                a = fleet()
            except Exception:  # noqa: BLE001 - transient starvation
                return None
            return a if pred(a) else None
        return wait_for(cond, timeout, what)

    try:
        print("[autoscale] waiting for the first replica")
        fleet_when(lambda a: a.live >= 1, 240, "first replica live")
        rate = calibrate(stub, pb)
        low = max(0.3, 0.15 * rate)
        high = min(8.0, max(2.5, 1.3 * rate))
        tail = max(0.5, min(1.0, 0.15 * rate))
        ramp = "%.2f:%.0f,%.2f:%.0f,%.2f:%.0f" % (
            low, LEAD_SECS, high, HIGH_SECS, tail, TAIL_SECS,
        )
        print("[autoscale] ramp profile: %s" % ramp)
        rs = np.random.RandomState(0)
        arrivals = ramp_arrivals(parse_ramp(ramp), rs)
        new_tokens = [int(rs.randint(12, 25)) for _ in arrivals]

        windows = TtftWindows(router)
        scrapes = MetricsScrapes(router.metrics.port)
        watch = FleetWatch(stub, pb)
        outcomes = {}
        latencies = {}
        lock = threading.Lock()
        threads = []

        def call(i, phase, max_new):
            t0 = time.monotonic()
            try:
                stub.router_generate(
                    pb.GenerateRequest(
                        prompt=[1 + i % 5, 2], max_new_tokens=max_new,
                        seed=i,
                    ),
                    timeout=CLIENT_TIMEOUT,
                )
                code = "OK"
            except Exception as e:  # noqa: BLE001 - status is the datum
                code_fn = getattr(e, "code", None)
                code = (code_fn().name if callable(code_fn)
                        else type(e).__name__)
            with lock:
                outcomes[i] = code
                latencies[i] = (
                    phase, (time.monotonic() - t0) * 1000.0
                )

        def drive_load():
            t0 = time.monotonic()
            for i, (at, phase) in enumerate(arrivals):
                delay = at - (time.monotonic() - t0)
                if delay > 0:
                    time.sleep(delay)
                t = threading.Thread(
                    target=call, args=(i, phase, new_tokens[i]),
                    daemon=True,  # a failed drill must still exit
                )
                t.start()
                threads.append(t)

        loader = threading.Thread(target=drive_load, daemon=True)
        loader.start()
        windows.checkpoint("lead")
        scrapes.scrape("lead")

        # ---- transition 1: ramp forces a scale-up
        fleet_when(lambda a: a.scale_ups >= 1,
                   LEAD_SECS + HIGH_SECS + 30, "a scale-up decision")
        up = fleet_when(lambda a: a.live >= 2, 180,
                        "second replica live")
        print("[autoscale] scaled up: target=%d live=%d (%s)"
              % (up.target, up.live, up.last_reason))
        windows.checkpoint("scale_up")
        scrapes.scrape("scale_up")

        # ---- transition 2: supervisor crash + journal recovery
        sup.abandon()  # decide loop gone; journal + replicas as-is
        pids_before = sorted(s["pid"] for s in sup.roster())
        print("[autoscale] supervisor ABANDONED (journal + %d replica "
              "pids left as a SIGKILL would)" % len(pids_before))
        sup2 = ReplicaSupervisor(router, make_launcher(), make_config())
        # BEFORE the decide loop starts, the roster is purely what
        # recovery rebuilt: it must be the SAME pids — re-adopted, not
        # re-spawned, none orphaned
        pids_after = sorted(s["pid"] for s in sup2.roster())
        assert pids_after == pids_before, (
            "recovery changed the fleet: %s -> %s (double-spawn or "
            "orphan)" % (pids_before, pids_after)
        )
        assert sup2.supervisor_restarts >= 1
        router.set_autoscaler(sup2)
        sup2.start()
        sup = sup2
        time.sleep(2.0)  # several decide ticks over the adopted fleet
        pids_now = sorted(s["pid"] for s in sup2.roster())
        assert set(pids_before) <= set(pids_now), (
            "recovered supervisor dropped adopted replicas: %s -> %s"
            % (pids_before, pids_now)
        )
        st = fleet_when(lambda a: True, 60, "router status")
        assert st.supervisor_restarts >= 1 and st.live >= 2
        print("[autoscale] supervisor RECOVERED: re-adopted %d "
              "replicas from the journal (restarts=%d)"
              % (len(pids_after), st.supervisor_restarts))

        # ---- transition 3: replica SIGKILL under load -> replacement
        victim = min(
            (s for s in sup2.roster() if s["state"] == "live"),
            key=lambda s: s["seat"],
        )
        print("[autoscale] SIGKILL replica seat %d (pid %d, %s) "
              "under load" % (victim["seat"], victim["pid"],
                              victim["address"]))
        os.kill(victim["pid"], signal.SIGKILL)
        fleet_when(lambda a: a.replacements >= 1, 90,
                   "the kill to be reaped")
        repl = fleet_when(lambda a: a.live >= a.target, 240,
                          "the replacement replica to go live")
        print("[autoscale] replacement live (replacements=%d)"
              % repl.replacements)
        windows.checkpoint("replacement")
        scrapes.scrape("replacement")
        # the replacement scrape is the forensics loop's anchor: it
        # must carry at least one parseable exemplar whose trace the
        # post-teardown assertions resolve in the span dump
        assert scrapes.points[-1]["exemplars"] >= 1, (
            "replacement-checkpoint scrape carried no exemplars — "
            "the metrics->traces join has nothing to walk"
        )
        # fleet-collector scrape bundle, taken LIVE under load (the
        # trace join happens after teardown, once spans have exported)
        from elasticdl_tpu.observability import collector as coll

        bundle = coll.scrape_fleet(
            ["127.0.0.1:%d" % router.metrics.port],
            scrapes=3, interval_secs=2.0,
        )

        # ---- load drains; then sustained idle forces scale-down
        loader.join(timeout=LEAD_SECS + HIGH_SECS + TAIL_SECS + 60)
        assert not loader.is_alive(), "arrival scheduler hung"
        for t in threads:
            t.join(timeout=CLIENT_TIMEOUT + 30)
        hung = [t for t in threads if t.is_alive()]
        assert not hung, "%d client threads HUNG" % len(hung)
        windows.checkpoint("ramp_down")
        scrapes.scrape("ramp_down")

        down = fleet_when(
            lambda a: (a.scale_downs >= 1 and a.live == 1
                       and a.draining == 0 and a.target == 1),
            180, "drain-based scale-down to min replicas",
        )
        print("[autoscale] scaled down to min: target=%d live=%d "
              "scale_downs=%d" % (down.target, down.live,
                                  down.scale_downs))
        windows.checkpoint("scale_down")
        scrapes.scrape("scale_down")

        # the scale-down was a DRAIN, not a kill: the journal must
        # show begin_drain -> retire with exit code 0
        retired_rc = []
        with open(os.path.join(journal_dir, "journal.jsonl")) as f:
            events = [json.loads(line) for line in f if line.strip()]
        drained = {e["seat"] for e in events
                   if e.get("ev") == "begin_drain"}
        retired_rc = [e.get("rc") for e in events
                      if e.get("ev") == "retire"
                      and e.get("seat") in drained]
        assert 0 in retired_rc, (
            "no drained replica retired with rc=0: drains=%s "
            "retires=%s" % (drained, retired_rc)
        )

        # ---- invariants over the whole run
        codes = list(outcomes.values())
        counts = {c: codes.count(c) for c in set(codes)}
        print("[autoscale] outcomes: %s" % counts)
        assert len(outcomes) == len(arrivals), (
            "only %d/%d clients terminated"
            % (len(outcomes), len(arrivals))
        )
        allowed = {"OK", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        leaked = set(codes) - allowed
        assert not leaked, (
            "accepted requests LOST across scaling transitions "
            "(transport codes leaked): %s" % leaked
        )
        ok = codes.count("OK")
        assert ok >= int(0.8 * len(codes)), (
            "too few completions: %d/%d OK" % (ok, len(codes))
        )
        for w in windows.windows:
            if not w["samples"]:
                continue
            assert w["ttft_p99_ms"] is not None and (
                w["ttft_p99_ms"] <= SLO_TTFT_P99_MS
            ), (
                "p99 TTFT SLO broken in window %r: %.0f ms > %.0f ms"
                % (w["window"], w["ttft_p99_ms"], SLO_TTFT_P99_MS)
            )
        assert sum(w["samples"] for w in windows.windows) > 0

        # the burn-rate trajectory: present + finite at EVERY
        # checkpoint (each scrape already parsed through the
        # independent parser and asserted finiteness — here we pin
        # that all five checkpoints actually produced a point)
        assert len(scrapes.points) == 5, (
            "expected 5 mid-drill /metrics scrapes, got %d"
            % len(scrapes.points)
        )

        # per-phase client latency for the report
        phase_stats = []
        for phase, (rate_rps, secs) in enumerate(parse_ramp(ramp)):
            rows = [
                (i, ms) for i, (p, ms) in latencies.items()
                if p == phase
            ]
            phase_stats.append({
                "phase": phase, "rate_rps": rate_rps, "secs": secs,
                "requests": len(rows),
                "ok": sum(1 for i, _ in rows if outcomes[i] == "OK"),
                "latency_ms": percentiles(
                    [ms for i, ms in rows if outcomes[i] == "OK"],
                    (50, 90, 99),
                ),
            })

        # graceful teardown: the supervisor drains its fleet (exit 0),
        # the router stops, every process flushes its span ring
        watch.stop()
        final = fleet_when(lambda a: True, 60, "final status")
        sup.stop()
        router.stop()

        # ---- the causal story must be READABLE in the merged traces
        from elasticdl_tpu.observability.dump import merge_dir

        spans, _meta = merge_dir(trace_dir)
        roots = [s for s in spans if s["name"] == "router_generate"]
        assert roots, "no router_generate roots exported"
        bad = {r["status"] for r in roots} - {
            "ok", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED",
        }
        assert not bad, (
            "non-terminal/implicit root span statuses: %s" % bad
        )
        dispatch_ids = {
            s["span_id"] for s in spans if s["name"] == "dispatch"
        }
        merged = sum(
            1 for s in spans
            if s["name"] == "serve"
            and s["parent_span_id"] in dispatch_ids
        )
        assert merged >= 1, (
            "no replica serve span parents under a router dispatch "
            "span — the cross-process trace merge merged nothing"
        )
        print("[autoscale] traces: %d spans, %d request roots, %d "
              "serve spans merged across processes"
              % (len(spans), len(roots), merged))

        # ---- the forensics loop, end to end: the collector bundle
        # scraped under load joins to the spans every process has now
        # exported — exemplar -> retained trace -> attributed cause —
        # and the incident report must pass its own schema gate
        from elasticdl_tpu.observability.forensics import CAUSES
        from elasticdl_tpu.observability.slo import (
            default_router_slos,
        )

        incident = coll.build_report(
            bundle,
            default_router_slos(SLO_TTFT_P99_MS,
                                2 * SLO_TTFT_P99_MS, 0.02),
            trace_dir=trace_dir,
        )
        coll.validate_report(incident)
        assert incident["exemplars"], (
            "collector scraped no exemplars off the router exposition"
        )
        resolved = [e for e in incident["exemplars"] if e["resolved"]]
        assert resolved, (
            "no scraped exemplar trace_id resolved to a retained "
            "trace in the span dump — the metrics->traces loop is "
            "broken"
        )
        attributed = [
            incident["traces"][e["trace_id"]]["attribution"]
            for e in resolved
        ]
        assert any(v["dominant_cause"] in CAUSES
                   for v in attributed), (
            "no resolved exemplar trace yielded a dominant cause"
        )
        assert incident["span_evidence"]["complete"], (
            "span evidence incomplete: %r"
            % (incident["span_evidence"],)
        )
        incident_out = os.path.join(REPO, "INCIDENT_REPORT.json")
        with open(incident_out, "w") as f:
            json.dump(incident, f, indent=1)
        with open(os.path.join(REPO, "INCIDENT_REPORT.txt"),
                  "w") as f:
            f.write(coll.render_text(incident))
        print("[autoscale] incident report archived -> %s "
              "(%d exemplars, %d resolved to traces, dominant "
              "cause: %s)"
              % (incident_out, len(incident["exemplars"]),
                 len(resolved), incident["dominant_cause"]))

        report = {
            "calibrated_single_replica_rps": round(rate, 2),
            "kv_cache_dtype": KV_CACHE_DTYPE,
            "ramp": ramp,
            "slo_ttft_p99_ms": SLO_TTFT_P99_MS,
            "outcomes": counts,
            "requests": len(arrivals),
            "scale_ups": final.scale_ups,
            "scale_downs": final.scale_downs,
            "replacements": final.replacements,
            "supervisor_restarts": final.supervisor_restarts,
            "ttft_windows": windows.windows,
            "metrics_scrapes": scrapes.points,
            "phases": phase_stats,
            "timeline": watch.timeline,
            "trace_spans": len(spans),
            # the forensics loop's summary (full report in
            # INCIDENT_REPORT.json next to this file)
            "incident": {
                "exemplars": len(incident["exemplars"]),
                "resolved": len(resolved),
                "dominant_cause": incident["dominant_cause"],
                "cause_histogram": incident["cause_histogram"],
                "alerting": incident["alerting"],
                "evidence_complete": (
                    incident["span_evidence"]["complete"]
                ),
            },
        }
        out = os.path.join(REPO, "AUTOSCALE_REPORT.json")
        with open(out, "w") as f:
            json.dump(report, f, indent=1)
        print("[autoscale] report archived -> %s" % out)
        print("[autoscale] autoscale drill PASSED: scale-up, journal "
              "recovery, SIGKILL replacement and drain-based "
              "scale-down with zero accepted-request loss, p99 "
              "TTFT <= %.0f ms in every window, a finite "
              "parse-clean SLO burn trajectory at all %d /metrics "
              "scrapes, and the forensics loop closed (exemplar -> "
              "retained trace -> attributed cause, schema-valid "
              "incident report)"
              % (SLO_TTFT_P99_MS, len(scrapes.points)))
        return 0
    finally:
        if watch is not None:
            watch.stop()
        # belt and braces: no replica may outlive the drill, even on
        # an assertion failure — kill, REAP (no zombies), stop the
        # transport so straggling client threads fail fast
        try:
            sup.abandon()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        for seat in sup.roster():
            try:
                os.kill(seat["pid"], signal.SIGKILL)
            except OSError:
                pass
            try:
                os.waitpid(seat["pid"], 0)
            except OSError:
                pass
        try:
            router.stop(grace=2.0)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        recorder().flush()


if __name__ == "__main__":
    sys.exit(main())
