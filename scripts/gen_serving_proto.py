#!/usr/bin/env python
"""Regenerate elasticdl_pb2.py with the Serving service appended.

The container ships no protoc binary, so (as with the TaskReason
addition before it — see the header of proto/elasticdl_pb2.py) the
descriptor is produced by parsing the CURRENT serialized
FileDescriptorProto, appending the serving messages + service with the
descriptor_pb2 API, and re-serializing. Idempotent: existing serving
entries are replaced, so the script can be rerun after editing the
tables below. Keep proto/elasticdl.proto (the human-readable source of
truth) in sync by hand.

BYTE-DETERMINISTIC: serving message types and services are appended
sorted by name and fields sorted by field number, so the output bytes
depend only on the CONTENT of the tables below — never on their
ordering, dict ordering, or how often the script has run. The edl-lint
proto-drift gate (EDL301, elasticdl_tpu/analysis/proto_rules.py) and
the regen-twice test in tests/test_lint.py rely on this: a flaky byte
diff would turn the CI gate into noise.

Usage: python scripts/gen_serving_proto.py [--check] [--out PATH]
  --check  regenerate in memory and exit 1 on drift, writing nothing
  --out    write somewhere other than the checked-in pb2 (drills)
"""

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from google.protobuf import descriptor_pb2  # noqa: E402

PB2_PATH = os.path.join(REPO, "elasticdl_tpu", "proto", "elasticdl_pb2.py")

T = descriptor_pb2.FieldDescriptorProto

# message name -> [(field name, number, type, label[, type_name])]
# (type_name only for TYPE_MESSAGE fields, fully qualified)
_OPT, _REP = T.LABEL_OPTIONAL, T.LABEL_REPEATED
SERVING_MESSAGES = {
    "GenerateRequest": [
        ("prompt", 1, T.TYPE_INT32, _REP),
        ("max_new_tokens", 2, T.TYPE_INT32, _OPT),
        ("temperature", 3, T.TYPE_FLOAT, _OPT),
        ("seed", 4, T.TYPE_INT32, _OPT),
        # relative deadline budget; 0 = no deadline
        ("deadline_ms", 5, T.TYPE_INT64, _OPT),
        # distributed-tracing context (observability/tracing.py): the
        # sender's trace and span ids — a replica parents its serve
        # span under the router's dispatch span, so one request is ONE
        # span tree across processes, hedges and re-dispatches.
        # Empty = untraced sender; the receiver mints a fresh trace.
        ("trace_id", 6, T.TYPE_STRING, _OPT),
        ("parent_span_id", 7, T.TYPE_STRING, _OPT),
        # disaggregated serving (serving/disagg.py): run the prompt to
        # completion as cache-warming only — the chain is seated,
        # registered in the prefix trie and released for export; the
        # single sampled token is NOT the answer (the decode replica
        # re-derives it token-exactly from the shared chain)
        ("prefill_only", 8, T.TYPE_BOOL, _OPT),
    ],
    "GenerateResponse": [
        ("tokens", 1, T.TYPE_INT32, _REP),
        ("model_version", 2, T.TYPE_INT32, _OPT),
    ],
    "TokenChunk": [
        ("tokens", 1, T.TYPE_INT32, _REP),
        ("done", 2, T.TYPE_BOOL, _OPT),
        ("model_version", 3, T.TYPE_INT32, _OPT),
    ],
    "ServerStatusRequest": [],
    "ServerStatusResponse": [
        ("queue_depth", 1, T.TYPE_INT32, _OPT),
        ("active_slots", 2, T.TYPE_INT32, _OPT),
        ("num_slots", 3, T.TYPE_INT32, _OPT),
        ("model_version", 4, T.TYPE_INT32, _OPT),
        ("admitted", 5, T.TYPE_INT64, _OPT),
        ("rejected", 6, T.TYPE_INT64, _OPT),
        ("expired", 7, T.TYPE_INT64, _OPT),
        ("completed", 8, T.TYPE_INT64, _OPT),
        ("tokens_generated", 9, T.TYPE_INT64, _OPT),
        ("reloads", 10, T.TYPE_INT64, _OPT),
        ("uptime_secs", 11, T.TYPE_DOUBLE, _OPT),
        ("max_active_slots", 12, T.TYPE_INT32, _OPT),
        # KV-pool memory accounting (block-paged pool lands these;
        # the dense pool reports bytes with zero block fields)
        ("kv_bytes_in_use", 13, T.TYPE_INT64, _OPT),
        ("kv_bytes_total", 14, T.TYPE_INT64, _OPT),
        ("kv_blocks_free", 15, T.TYPE_INT32, _OPT),
        ("kv_blocks_total", 16, T.TYPE_INT32, _OPT),
        ("kv_block_size", 17, T.TYPE_INT32, _OPT),
        ("kv_paged", 18, T.TYPE_BOOL, _OPT),
        ("kv_bytes_in_use_peak", 19, T.TYPE_INT64, _OPT),
        # average KV bytes resident per generated token (sum-over-
        # steps of kv_bytes_in_use / tokens_generated)
        ("kv_bytes_per_token", 20, T.TYPE_DOUBLE, _OPT),
        # drain advertisement: the replica is finishing in-flight work
        # (SIGTERM drain / hot-reload swap) — routers take it out of
        # rotation for NEW requests while existing streams complete
        ("draining", 21, T.TYPE_BOOL, _OPT),
        # recent average time requests spend queued before seating (ms,
        # EWMA) — part of the router's least-loaded signal
        ("queue_wait_ms", 22, T.TYPE_DOUBLE, _OPT),
        # latency percentiles from the shared log-linear histograms
        # (observability/histogram.py) — the same code path
        # bench_serving.py computes its percentiles with
        ("ttft_p50_ms", 23, T.TYPE_DOUBLE, _OPT),
        ("ttft_p90_ms", 24, T.TYPE_DOUBLE, _OPT),
        ("ttft_p99_ms", 25, T.TYPE_DOUBLE, _OPT),
        ("queue_wait_p50_ms", 26, T.TYPE_DOUBLE, _OPT),
        ("queue_wait_p90_ms", 27, T.TYPE_DOUBLE, _OPT),
        ("queue_wait_p99_ms", 28, T.TYPE_DOUBLE, _OPT),
        # raw histogram bucket counts (fixed shared bucket scheme,
        # trailing zeros trimmed): mergeable by addition, so the
        # router aggregates its replicas' histograms and reports
        # fleet-wide percentiles without percentile-averaging errors
        ("ttft_hist", 29, T.TYPE_INT64, _REP),
        ("queue_wait_hist", 30, T.TYPE_INT64, _REP),
        # prefix-shared paged pool (serving/kv_pool.py): whether
        # refcounted prefix sharing is on, blocks referenced by >1
        # table right now, refcount-0 blocks held reclaimable by the
        # prefix cache, prompt tokens seated by incref instead of
        # re-prefilling, and copy-on-write faults served
        ("kv_shared", 31, T.TYPE_BOOL, _OPT),
        ("kv_blocks_shared", 32, T.TYPE_INT32, _OPT),
        ("kv_blocks_cached", 33, T.TYPE_INT32, _OPT),
        ("prefix_hit_tokens", 34, T.TYPE_INT64, _OPT),
        ("cow_copies", 35, T.TYPE_INT64, _OPT),
        # speculative decode: tokens drafted per tick (0 = off) and
        # the proposal economy (accept rate = accepted / proposed)
        ("draft_k", 36, T.TYPE_INT32, _OPT),
        ("draft_proposed", 37, T.TYPE_INT64, _OPT),
        ("draft_accepted", 38, T.TYPE_INT64, _OPT),
        # KV arena storage format: "" = compute dtype, "int8" =
        # symmetric per-row int8 with f32 scale arenas. The byte
        # fields above count TRUE arena bytes at each leaf's own
        # dtype (int8 rows + f32 scale leaves), so equal-byte
        # comparisons across formats are honest.
        ("kv_cache_dtype", 39, T.TYPE_STRING, _OPT),
        # tiered host spill (serving/kv_pool.py): evicted prefix
        # chains demoted to bounded host-RAM buffers and revived by
        # device upload instead of re-prefill. Occupancy gauges
        # (blocks/bytes parked host-side right now) plus the monotone
        # revival economy: batched upload scatters served, prompt
        # tokens those uploads seated WITHOUT re-running prefill, and
        # spilled entries the bounded host LRU (or a reload flush)
        # dropped.
        ("kv_host_blocks", 40, T.TYPE_INT32, _OPT),
        ("kv_host_bytes", 41, T.TYPE_INT64, _OPT),
        ("revive_uploads", 42, T.TYPE_INT64, _OPT),
        ("prefill_tokens_revived", 43, T.TYPE_INT64, _OPT),
        ("host_drops", 44, T.TYPE_INT64, _OPT),
        # windowed prefix-hit-rate (time-series ring, trailing ~30 s):
        # the share of prompt tokens seated WITHOUT paying prefill
        # compute (prefix incref + spilled revival) — the warm-vs-cold
        # capacity signal prefix-affinity routing reads, as a live
        # window rather than a lifetime ratio
        ("prefix_hit_rate_window", 45, T.TYPE_DOUBLE, _OPT),
        # terminally-slow requests by dominant attributed cause
        # (observability/forensics.py CAUSES, declared order — the
        # same closed set behind edl_serving_slow_cause_total): the
        # scrapeable distribution of WHY, not just the that
        ("slow_cause_counts", 46, T.TYPE_INT64, _REP),
        # runtime health plane (observability/runtime_health.py):
        # the progress watchdog's self-report — ms since the
        # scheduler last made progress with work seated (0 = idle or
        # moving) and the watchdog state "ok" | "stalled" ("" = the
        # replica predates the health plane / runs with it off, the
        # autoscaler's cue to fall back to lease decay)
        ("last_progress_age_ms", 47, T.TYPE_DOUBLE, _OPT),
        ("health_state", 48, T.TYPE_STRING, _OPT),
        # recompile sentry: total tracked jit compilations, and the
        # post-warmup-boundary recompile anomalies ("churn never
        # recompiles" — serve-smoke pins steady_recompiles at zero)
        ("jit_compiles", 49, T.TYPE_INT64, _OPT),
        ("steady_recompiles", 50, T.TYPE_INT64, _OPT),
        # device-memory accountant: PEAK unaccounted device-byte
        # drift since the steady baseline (ledger vs live buffers) —
        # a leak detector, monotone by construction
        ("memory_unaccounted_bytes", 51, T.TYPE_INT64, _OPT),
        # disaggregated prefill/decode (serving/disagg.py): the
        # replica's advertised phase role — "prefill" | "decode" |
        # "unified" ("" = pre-disagg replica, treated as unified) —
        # and the KV chain-transfer economy: chains exported to /
        # imported from sibling replicas, prompt tokens those imports
        # seated without re-prefill, transfers dropped via
        # abort_transfer, and exports currently awaiting their
        # import/abort resolution (0 after drain = clean handoff
        # ledger, the kill-drill's post-drain assertion)
        ("role", 52, T.TYPE_STRING, _OPT),
        ("chain_exports", 53, T.TYPE_INT64, _OPT),
        ("chain_imports", 54, T.TYPE_INT64, _OPT),
        ("chain_import_tokens", 55, T.TYPE_INT64, _OPT),
        ("transfer_aborts", 56, T.TYPE_INT64, _OPT),
        ("transfers_inflight", 57, T.TYPE_INT32, _OPT),
        # hot-reload failure advertisement (serving/hot_reload.py):
        # the watcher exhausted its retry ladder against a checkpoint
        # that would not verify/load — old params still serving, error
        # carried verbatim so the rollout controller can abort with
        # evidence instead of inferring from a version that never moves
        ("reload_failed", 58, T.TYPE_BOOL, _OPT),
        ("reload_error", 59, T.TYPE_STRING, _OPT),
    ],
    # ---- explicit checkpoint handshake (serving/rollout.py) ----
    # The rollout controller's swap RPC: unlike the poll path this
    # names an exact target version — including an OLDER one, which is
    # what a rollback is — and returns a structured verdict instead of
    # relying on the caller to notice the version never moved.
    "ReloadCheckpointRequest": [
        ("version", 1, T.TYPE_INT32, _OPT),
    ],
    "ReloadCheckpointResponse": [
        ("ok", 1, T.TYPE_BOOL, _OPT),
        ("model_version", 2, T.TYPE_INT32, _OPT),
        ("error", 3, T.TYPE_STRING, _OPT),
    ],
    # ---- disaggregated prefill/decode handoff (serving/disagg.py) ----
    # One finished prefix chain exported as a dense byte copy: the
    # same tree-generic kv_row_leaf gather the host spill tier uses,
    # one KvChainBlock per trie block in root-first chain order. The
    # decode side imports the blocks into freshly allocated device
    # blocks and re-keys them into its content-addressed trie, so
    # prefix sharing and speculative decode compose unchanged.
    "ExportChainRequest": [
        ("prompt", 1, T.TYPE_INT32, _REP),
        # coordinator-minted id correlating export -> import|abort
        ("transfer_id", 2, T.TYPE_STRING, _OPT),
    ],
    "KvChainBlock": [
        # the block's token ids (a full kv_block_size run of the
        # prompt) — with the parent chain implied by list order this
        # re-derives the (parent, tokens) trie key on the importer
        ("tokens", 1, T.TYPE_INT32, _REP),
        # raw row bytes, one entry per 4-d kv_row_leaf in
        # jax.tree.leaves order (int8 rows + f32 scale leaves travel
        # as siblings, exactly like the host spill tier)
        ("leaves", 2, T.TYPE_BYTES, _REP),
    ],
    "TransferChainRequest": [
        ("transfer_id", 1, T.TYPE_STRING, _OPT),
        ("block_size", 2, T.TYPE_INT32, _OPT),
        # leaf dtype names in the same order as KvChainBlock.leaves —
        # the importer refuses a chain whose arena format differs
        ("leaf_dtypes", 3, T.TYPE_STRING, _REP),
        ("blocks", 4, T.TYPE_MESSAGE, _REP, ".elasticdl_tpu.KvChainBlock"),
    ],
    "TransferChainResponse": [
        ("transfer_id", 1, T.TYPE_STRING, _OPT),
        ("ok", 2, T.TYPE_BOOL, _OPT),
        # blocks/tokens actually uploaded (deduped against blocks the
        # importer's trie already held)
        ("blocks", 3, T.TYPE_INT32, _OPT),
        ("tokens", 4, T.TYPE_INT32, _OPT),
        ("error", 5, T.TYPE_STRING, _OPT),
    ],
    "AbortTransferRequest": [
        ("transfer_id", 1, T.TYPE_STRING, _OPT),
    ],
    # ---- router tier (serving/router.py) ----
    "RouterStatusRequest": [],
    # the replica supervisor/autoscaler (serving/autoscaler.py):
    # desired-count target, roster by lifecycle state, decision
    # counters and the last scale decision + reason — absent (all
    # zeros / enabled=false) when the router runs a static fleet
    "AutoscalerStatus": [
        ("enabled", 1, T.TYPE_BOOL, _OPT),
        ("target", 2, T.TYPE_INT32, _OPT),
        ("live", 3, T.TYPE_INT32, _OPT),
        ("starting", 4, T.TYPE_INT32, _OPT),
        ("draining", 5, T.TYPE_INT32, _OPT),
        ("scale_ups", 6, T.TYPE_INT64, _OPT),
        ("scale_downs", 7, T.TYPE_INT64, _OPT),
        # unplanned replica losses (crash / wedged kill) replaced
        # through the deficit path
        ("replacements", 8, T.TYPE_INT64, _OPT),
        ("spawn_failures", 9, T.TYPE_INT64, _OPT),
        # max_restarts consecutive spawn failures opened the restart
        # circuit: no more respawns until the supervisor restarts
        ("circuit_open", 10, T.TYPE_BOOL, _OPT),
        ("last_decision", 11, T.TYPE_STRING, _OPT),
        ("last_reason", 12, T.TYPE_STRING, _OPT),
        ("last_decision_age_secs", 13, T.TYPE_DOUBLE, _OPT),
        # journal recoveries: how many supervisors have come up over
        # this roster's write-ahead state
        ("supervisor_restarts", 14, T.TYPE_INT64, _OPT),
    ],
    # One SLO objective's burn-rate evaluation (observability/slo.py):
    # the declared target, the error-budget goal, and the multi-window
    # (fast/slow) burn rates over the router's time-series ring.
    # alerting = both windows burning above 1.0 (spending the budget
    # faster than planned) — the signal, not an action: the autoscaler
    # consumes it read-only as a logged advisory.
    "SloObjective": [
        ("name", 1, T.TYPE_STRING, _OPT),
        ("kind", 2, T.TYPE_STRING, _OPT),
        ("threshold_ms", 3, T.TYPE_DOUBLE, _OPT),
        ("goal", 4, T.TYPE_DOUBLE, _OPT),
        ("fast_burn", 5, T.TYPE_DOUBLE, _OPT),
        ("slow_burn", 6, T.TYPE_DOUBLE, _OPT),
        ("fast_window_secs", 7, T.TYPE_DOUBLE, _OPT),
        ("slow_window_secs", 8, T.TYPE_DOUBLE, _OPT),
        ("fast_samples", 9, T.TYPE_INT64, _OPT),
        ("slow_samples", 10, T.TYPE_INT64, _OPT),
        ("alerting", 11, T.TYPE_BOOL, _OPT),
    ],
    # the fleet rollout controller (serving/rollout.py): journaled
    # canary -> judge -> progressive waves -> commit state machine.
    # phase names the wave controller's current state ("idle" when no
    # rollout has ever run); verdict carries the canary judgment
    # ("pass" | "parity_fail" | "burn_fail" | "timeout" | "" while
    # undecided); rollout_restarts counts controllers that came up over
    # this journal — the crash-recovery odometer the rollout drill
    # asserts on
    "RolloutStatus": [
        ("enabled", 1, T.TYPE_BOOL, _OPT),
        ("phase", 2, T.TYPE_STRING, _OPT),
        ("target_version", 3, T.TYPE_INT32, _OPT),
        ("old_version", 4, T.TYPE_INT32, _OPT),
        ("wave", 5, T.TYPE_INT32, _OPT),
        ("waves_total", 6, T.TYPE_INT32, _OPT),
        ("swapped", 7, T.TYPE_INT32, _OPT),
        ("fleet", 8, T.TYPE_INT32, _OPT),
        ("canary", 9, T.TYPE_STRING, _OPT),
        ("verdict", 10, T.TYPE_STRING, _OPT),
        ("last_error", 11, T.TYPE_STRING, _OPT),
        ("rollbacks", 12, T.TYPE_INT64, _OPT),
        ("rollout_restarts", 13, T.TYPE_INT64, _OPT),
    ],
    "ReplicaStatus": [
        ("address", 1, T.TYPE_STRING, _OPT),
        ("healthy", 2, T.TYPE_BOOL, _OPT),
        ("draining", 3, T.TYPE_BOOL, _OPT),
        # circuit breaker state: "closed" | "open" | "half_open"
        ("breaker", 4, T.TYPE_STRING, _OPT),
        ("lease_remaining_secs", 5, T.TYPE_DOUBLE, _OPT),
        ("queue_depth", 6, T.TYPE_INT32, _OPT),
        ("active_slots", 7, T.TYPE_INT32, _OPT),
        ("kv_blocks_free", 8, T.TYPE_INT32, _OPT),
        ("queue_wait_ms", 9, T.TYPE_DOUBLE, _OPT),
        ("dispatched", 10, T.TYPE_INT64, _OPT),
        ("failures", 11, T.TYPE_INT64, _OPT),
        # router-side dispatches currently in flight on this replica
        ("inflight", 12, T.TYPE_INT32, _OPT),
        # the replica's KV arena storage format ("" | "int8"),
        # passed through from its ServerStatus
        ("kv_cache_dtype", 13, T.TYPE_STRING, _OPT),
        # tiered host spill, passed through from ServerStatus: warm
        # prefix capacity that survived device eviction on this
        # replica — the warm-vs-cold signal prefix-affinity routing
        # and the autoscaler read
        ("kv_host_blocks", 14, T.TYPE_INT32, _OPT),
        ("kv_host_bytes", 15, T.TYPE_INT64, _OPT),
        ("revive_uploads", 16, T.TYPE_INT64, _OPT),
        ("prefill_tokens_revived", 17, T.TYPE_INT64, _OPT),
        ("host_drops", 18, T.TYPE_INT64, _OPT),
        # windowed prefix-hit-rate, passed through from ServerStatus
        ("prefix_hit_rate_window", 19, T.TYPE_DOUBLE, _OPT),
        # slow-cause distribution, passed through from ServerStatus
        # (forensics taxonomy, declared order)
        ("slow_cause_counts", 20, T.TYPE_INT64, _REP),
        # runtime health, passed through from ServerStatus: a
        # "stalled" replica leaves the dispatch rotation and the
        # supervisor replaces it on a seconds-scale budget instead
        # of the 30 s lease heuristic ("" = pre-health replica)
        ("last_progress_age_ms", 21, T.TYPE_DOUBLE, _OPT),
        ("health_state", 22, T.TYPE_STRING, _OPT),
        # prefix-cache occupancy, passed through from ServerStatus:
        # cached = refcount-0 blocks parked reclaimable, shared =
        # blocks referenced by >1 sequence — with the host tier and
        # hit-rate above, the warm-capacity ladder affinity ranks by
        ("kv_blocks_cached", 23, T.TYPE_INT32, _OPT),
        ("kv_blocks_shared", 24, T.TYPE_INT32, _OPT),
        # advertised phase role, passed through from ServerStatus:
        # "prefill" replicas leave the normal dispatch rotation and
        # serve only cache-warming prefills + chain exports
        ("role", 25, T.TYPE_STRING, _OPT),
        # checkpoint identity, passed through from ServerStatus: the
        # version this replica is serving right now plus the hot-reload
        # failure latch — together the rollout controller's per-replica
        # ground truth (a wave commits only when every member's
        # advertised version equals the target)
        ("model_version", 26, T.TYPE_INT32, _OPT),
        ("reload_failed", 27, T.TYPE_BOOL, _OPT),
    ],
    "RouterStatusResponse": [
        ("replicas", 1, T.TYPE_INT32, _OPT),
        ("healthy", 2, T.TYPE_INT32, _OPT),
        ("replica", 3, T.TYPE_MESSAGE, _REP, ".elasticdl_tpu.ReplicaStatus"),
        ("routed", 4, T.TYPE_INT64, _OPT),
        ("completed", 5, T.TYPE_INT64, _OPT),
        ("redispatched", 6, T.TYPE_INT64, _OPT),
        ("hedges", 7, T.TYPE_INT64, _OPT),
        ("hedge_wins", 8, T.TYPE_INT64, _OPT),
        ("shed", 9, T.TYPE_INT64, _OPT),
        ("breaker_trips", 10, T.TYPE_INT64, _OPT),
        ("uptime_secs", 11, T.TYPE_DOUBLE, _OPT),
        # router-observed end-to-end dispatch latency (accept ->
        # terminal outcome, re-dispatches and hedges included)
        ("e2e_p50_ms", 12, T.TYPE_DOUBLE, _OPT),
        ("e2e_p90_ms", 13, T.TYPE_DOUBLE, _OPT),
        ("e2e_p99_ms", 14, T.TYPE_DOUBLE, _OPT),
        # fleet-wide percentiles: the replicas' ttft/queue-wait
        # histogram buckets merged by addition at the router
        ("ttft_p50_ms", 15, T.TYPE_DOUBLE, _OPT),
        ("ttft_p90_ms", 16, T.TYPE_DOUBLE, _OPT),
        ("ttft_p99_ms", 17, T.TYPE_DOUBLE, _OPT),
        ("queue_wait_p50_ms", 18, T.TYPE_DOUBLE, _OPT),
        ("queue_wait_p90_ms", 19, T.TYPE_DOUBLE, _OPT),
        ("queue_wait_p99_ms", 20, T.TYPE_DOUBLE, _OPT),
        # replica supervisor/autoscaler block (serving/autoscaler.py);
        # unset when the fleet is static
        ("autoscaler", 21, T.TYPE_MESSAGE, _OPT,
         ".elasticdl_tpu.AutoscalerStatus"),
        # fleet-wide tiered-host-spill view: occupancy gauges and the
        # monotone revival economy summed across the roster
        ("kv_host_blocks", 22, T.TYPE_INT64, _OPT),
        ("kv_host_bytes", 23, T.TYPE_INT64, _OPT),
        ("revive_uploads", 24, T.TYPE_INT64, _OPT),
        ("prefill_tokens_revived", 25, T.TYPE_INT64, _OPT),
        ("host_drops", 26, T.TYPE_INT64, _OPT),
        # declared SLO objectives evaluated as multi-window burn
        # rates over the router's time-series ring (one block per
        # objective; empty when the router has no SLO engine)
        ("slo", 27, T.TYPE_MESSAGE, _REP,
         ".elasticdl_tpu.SloObjective"),
        # multi-cell router tier (serving/router_cell.py): which cell
        # answered this status and how many the tier runs; the
        # affinity counters are the prefix-affine dispatch ladder's
        # verdicts; journal_* report the shared-registry write-ahead
        # journal (events appended by this cell / replayed into it at
        # start), cell_restarts the journal dir's restart marker —
        # the crash-recovery odometer
        ("cell_id", 28, T.TYPE_INT32, _OPT),
        ("cells", 29, T.TYPE_INT32, _OPT),
        ("affinity_hits", 30, T.TYPE_INT64, _OPT),
        ("affinity_misses", 31, T.TYPE_INT64, _OPT),
        ("journal_events", 32, T.TYPE_INT64, _OPT),
        ("journal_replayed", 33, T.TYPE_INT64, _OPT),
        ("cell_restarts", 34, T.TYPE_INT64, _OPT),
        # disaggregated dispatch (serving/disagg.py): requests whose
        # prefill ran on a dedicated prefill replica with the chain
        # handed to the decode target, and handoffs that failed
        # mid-transfer and fell back to the unified path (the decode
        # replica paid prefill itself — degraded, never lost)
        ("disagg_handoffs", 35, T.TYPE_INT64, _OPT),
        ("disagg_fallbacks", 36, T.TYPE_INT64, _OPT),
        # fleet rollout controller block (serving/rollout.py); unset
        # when no controller is attached
        ("rollout", 37, T.TYPE_MESSAGE, _OPT,
         ".elasticdl_tpu.RolloutStatus"),
    ],
}

# Fields appended to messages that live in the BASE descriptor (the
# original elasticdl.proto surface, not the serving tables above).
# Same determinism rules: idempotent replace-by-name, appended sorted
# by field number. Used for the training-plane trace context: the
# master mints a trace per task and hands (trace_id, span_id) to the
# worker on the Task it dispatches, so task dispatch -> worker fetch ->
# report_task_result reassembles as one span tree keyed by task id.
EXTRA_MESSAGE_FIELDS = {
    "Task": [
        ("trace_id", 10, T.TYPE_STRING, _OPT),
        ("span_id", 11, T.TYPE_STRING, _OPT),
    ],
}

# service name -> [(method name, request, response, server_streaming)]
SERVICES = {
    "Serving": [
        ("generate", "GenerateRequest", "GenerateResponse", False),
        ("generate_stream", "GenerateRequest", "TokenChunk", True),
        ("server_status", "ServerStatusRequest", "ServerStatusResponse",
         False),
        # disaggregated handoff surface: export a finished chain as a
        # dense byte copy (the response IS the transfer payload),
        # import one on the decode side, or abandon an export whose
        # import failed so the exporter's inflight ledger settles
        ("export_chain", "ExportChainRequest", "TransferChainRequest",
         False),
        ("transfer_chain", "TransferChainRequest", "TransferChainResponse",
         False),
        ("abort_transfer", "AbortTransferRequest", "TransferChainResponse",
         False),
        # explicit checkpoint swap (rollout controller handshake):
        # load exactly this version — newer or older — on the
        # scheduler thread, draining advertised for the duration
        ("reload_checkpoint", "ReloadCheckpointRequest",
         "ReloadCheckpointResponse", False),
    ],
    # the multi-replica routing tier in front of N Serving replicas;
    # method names are distinct from the replica surface so
    # EDL_FAULT_SPEC rules can target one boundary without the other
    "Router": [
        ("router_generate", "GenerateRequest", "GenerateResponse", False),
        ("router_generate_stream", "GenerateRequest", "TokenChunk", True),
        ("router_status", "RouterStatusRequest", "RouterStatusResponse",
         False),
    ],
}

PB2_TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# source: elasticdl.proto
# (regenerated descriptor: TaskReason enum + Task.reason field, then the
# Serving service (scripts/gen_serving_proto.py), added by mutating the
# FileDescriptorProto in-process; the container ships no protoc binary —
# see docs/designs/fault_tolerance.md and docs/designs/serving.md)
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({serialized!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'elasticdl_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
  _TASK_EXTENDEDCONFIGENTRY._options = None
  _TASK_EXTENDEDCONFIGENTRY._serialized_options = b'8\\001'
  _REPORTTASKRESULTREQUEST_EXECCOUNTERSENTRY._options = None
  _REPORTTASKRESULTREQUEST_EXECCOUNTERSENTRY._serialized_options = b'8\\001'
# @@protoc_insertion_point(module_scope)
'''


def current_serialized_pb(src=None):
    """Extract the serialized descriptor from the committed pb2 module
    without importing it (imports would register it in the default pool
    and block re-registration elsewhere in the same process)."""
    if src is None:
        with open(PB2_PATH) as f:
            src = f.read()
    m = re.search(r"AddSerializedFile\((b'(?:[^'\\]|\\.)*')\)", src)
    if not m:
        raise RuntimeError("cannot find AddSerializedFile in %s" % PB2_PATH)
    return eval(m.group(1))  # noqa: S307 - a bytes literal from our own file


def build_descriptor(serialized):
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(serialized)

    # idempotence: drop any earlier serving entries before re-adding
    keep = [m for m in fdp.message_type if m.name not in SERVING_MESSAGES]
    del fdp.message_type[:]
    fdp.message_type.extend(keep)
    keep_svc = [s for s in fdp.service if s.name not in SERVICES]
    del fdp.service[:]
    fdp.service.extend(keep_svc)

    # append the extra fields to base-descriptor messages, idempotently
    # (replace-by-name) and in field-number order — same determinism
    # contract as the serving tables
    for msg in fdp.message_type:
        extras = EXTRA_MESSAGE_FIELDS.get(msg.name)
        if not extras:
            continue
        names = {spec[0] for spec in extras}
        keep_fields = [f for f in msg.field if f.name not in names]
        del msg.field[:]
        msg.field.extend(keep_fields)
        for spec in sorted(extras, key=lambda s: s[1]):
            fname, num, ftype, label = spec[:4]
            fld = msg.field.add()
            fld.name = fname
            fld.number = num
            fld.type = ftype
            fld.label = label
            fld.json_name = _json_name(fname)
            if ftype == T.TYPE_MESSAGE:
                fld.type_name = spec[4]

    # stable ordering: names sort the tables, numbers sort the fields —
    # the serialized bytes cannot depend on dict/tuple declaration order
    for name in sorted(SERVING_MESSAGES):
        fields = SERVING_MESSAGES[name]
        msg = fdp.message_type.add()
        msg.name = name
        for spec in sorted(fields, key=lambda s: s[1]):
            fname, num, ftype, label = spec[:4]
            fld = msg.field.add()
            fld.name = fname
            fld.number = num
            fld.type = ftype
            fld.label = label
            fld.json_name = _json_name(fname)
            if ftype == T.TYPE_MESSAGE:
                fld.type_name = spec[4]

    for sname in sorted(SERVICES):
        methods = SERVICES[sname]
        svc = fdp.service.add()
        svc.name = sname
        for mname, req, resp, streaming in methods:
            meth = svc.method.add()
            meth.name = mname
            meth.input_type = ".elasticdl_tpu.%s" % req
            meth.output_type = ".elasticdl_tpu.%s" % resp
            if streaming:
                meth.server_streaming = True
    return fdp.SerializeToString()


def _json_name(snake):
    parts = snake.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


def generate_text(src=None):
    """The full pb2 file text, regenerated from `src` (the current pb2
    source text; None reads the checked-in file). Pure function of the
    tables above + the non-serving part of the existing descriptor —
    the hermetic entry point the EDL301 drift gate and the regen-twice
    determinism test call."""
    serialized = build_descriptor(current_serialized_pb(src))
    return PB2_TEMPLATE.format(serialized=serialized)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default=PB2_PATH)
    parser.add_argument("--check", action="store_true",
                        help="exit 1 on drift; write nothing")
    args = parser.parse_args(argv)
    text = generate_text()
    if args.check:
        with open(PB2_PATH) as f:
            if f.read() != text:
                print("gen_serving_proto: %s has DRIFTED from the "
                      "generator tables" % PB2_PATH, file=sys.stderr)
                return 1
        print("gen_serving_proto: %s is up to date" % PB2_PATH)
        return 0
    with open(args.out, "w") as f:
        f.write(text)
    print("wrote %s (%d chars)" % (args.out, len(text)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
