"""Elasticity benchmark: gang vs elastic scheduling makespan.

The reference's ONLY published benchmark is its cluster-elasticity
report (`docs/benchmark/report_cn.md:70-120`): two training jobs on a
fixed-capacity cluster finish sooner under elastic scheduling (job 2
starts immediately on leftover slots and scales up when job 1's
resources free) than under gang scheduling (job 2 waits for its full
worker count), with convergence invariant to the changing worker count.
This script reproduces that experiment with REAL elasticdl_tpu jobs —
in-process masters, subprocess workers pulling tasks over gRPC — and a
fixed pool of worker slots played by the script (the reference's
scheduler was k8s, likewise external to the framework). Elastic scale-up
needs no framework support beyond what exists: a late worker simply
registers and starts pulling tasks from the dynamic-sharding queue.

    python scripts/bench_elasticity.py [--slots 3] [--workers-per-job 2]

Prints ONE JSON line:
    {"metric": "elastic_vs_gang_makespan_speedup", "value": ...,
     "gang": {...}, "elastic": {...}}
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _worker_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    return env


class Job(object):
    """One training job: an in-process master plus up to
    `target_workers` subprocess workers the scheduler may start."""

    def __init__(self, name, data_dir, target_workers, minibatch=8,
                 records_per_task=32):
        from elasticdl_tpu.common.model_utils import (
            load_model_spec_from_module,
        )
        from elasticdl_tpu.master.master import Master
        from model_zoo.mnist_functional_api import (
            mnist_functional_api as zoo,
        )

        self.name = name
        self.target_workers = target_workers
        self.minibatch = minibatch
        self.master = Master(
            load_model_spec_from_module(zoo),
            training_data=data_dir,
            minibatch_size=minibatch,
            records_per_task=records_per_task,
            num_epochs=1,
            port=0,
        )
        self.master.prepare()
        self._data_dir = data_dir
        self.procs = []
        self.log_paths = []
        self.recovered = set()
        self.failures = 0
        self.max_failures = 3
        self.peak_workers = 0
        self.t_submit = None
        self.t_first_worker = None
        self.t_done = None

    def launch_worker(self):
        wid = len(self.procs)
        cmd = [
            sys.executable, "-m", "elasticdl_tpu.worker.main",
            "--worker_id", str(wid),
            "--model_zoo", "model_zoo",
            "--model_def",
            "mnist_functional_api.mnist_functional_api.custom_model",
            "--master_addr", "localhost:%d" % self.master.port,
            "--training_data", self._data_dir,
            "--job_type", "training_only",
            "--minibatch_size", str(self.minibatch),
        ]
        log_path = os.path.join(
            tempfile.gettempdir(),
            "edl_elastic_%s_w%d.log" % (self.name, wid),
        )
        log = open(log_path, "w")
        proc = subprocess.Popen(
            cmd, env=_worker_env(), cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT,
        )
        log.close()
        self.procs.append(proc)
        self.log_paths.append(log_path)
        if self.t_first_worker is None:
            self.t_first_worker = time.time()
        return proc

    @property
    def live_workers(self):
        live = sum(1 for p in self.procs if p.poll() is None)
        self.peak_workers = max(self.peak_workers, live)
        return live

    def crashed_workers(self):
        return [
            (i, p.returncode) for i, p in enumerate(self.procs)
            if p.poll() is not None and p.returncode != 0
        ]

    @property
    def todo_count(self):
        return len(self.master.task_d._todo)

    @property
    def wants_workers(self):
        # more workers help ONLY while undispatched tasks remain: a
        # cleanly-exited worker ("no more tasks" while a peer still
        # holds the last ones) must not trigger futile relaunches
        return (
            not self.finished
            and self.live_workers < self.target_workers
            and self.todo_count > 0
        )

    @property
    def finished(self):
        if self.t_done is not None:
            return True
        if self.master.task_d.finished() and self.live_workers == 0:
            self.t_done = time.time()
            return True
        return False

    def stop(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        self.master.stop()


def run_cluster(mode, slots, make_jobs, job2_delay, timeout=900):
    """Schedule `make_jobs()`'s two jobs over `slots` worker slots.

    gang: a job launches only when ALL its workers fit at once.
    elastic: a job launches as soon as ONE slot is free and scales up
    whenever more slots free (report_cn.md's elastic policy).
    """
    job1, job2 = make_jobs()
    t0 = time.time()
    job1.t_submit = t0
    job2.t_submit = t0 + job2_delay
    pending = [job1]
    deadline = t0 + timeout
    used_slot_seconds = 0.0
    t_prev = t0
    try:
        while time.time() < deadline:
            now = time.time()
            if job2 not in pending and job2.t_submit <= now and (
                    job2.t_first_worker is None):
                pending.append(job2)
            running = [j for j in (job1, job2) if j.procs]
            used = sum(j.live_workers for j in running)
            free = slots - used
            used_slot_seconds += used * (now - t_prev)
            t_prev = now
            for job in list(pending):
                if job.t_first_worker is None:
                    need = (
                        job.target_workers if mode == "gang" else 1
                    )
                    if free >= need:
                        n = (job.target_workers if mode == "gang"
                             else min(free, job.target_workers))
                        for _ in range(n):
                            job.launch_worker()
                        free -= n
                        pending.remove(job)
            for job in (job1, job2):
                # a crashed worker's in-flight tasks go back to todo
                # (the script plays the instance manager's recover
                # role); repeated failures surface the worker log
                # instead of hanging to the timeout
                for i, rc in job.crashed_workers():
                    if i in job.recovered:
                        continue
                    job.recovered.add(i)
                    job.failures += 1
                    job.master.task_d.recover_tasks(i)
                    if job.failures > job.max_failures:
                        tail = ""
                        try:
                            with open(job.log_paths[i]) as f:
                                tail = f.read()[-2000:]
                        except OSError:
                            pass
                        raise RuntimeError(
                            "%s worker %d exited rc=%d (failure %d):\n%s"
                            % (job.name, i, rc, job.failures, tail)
                        )
            # launches: crash replacements in either mode; in elastic
            # mode the same rule IS the scale-up policy (any free slot
            # goes to a started job with undispatched tasks)
            for job in (job1, job2):
                while (free > 0 and job.t_first_worker is not None
                       and job.wants_workers):
                    job.launch_worker()
                    free -= 1
            if job1.finished and job2.finished:
                break
            time.sleep(0.25)
        else:
            raise TimeoutError("cluster run exceeded %ds" % timeout)
        return {
            "makespan_s": round(
                max(job1.t_done, job2.t_done) - t0, 1),
            "job1_s": round(job1.t_done - job1.t_submit, 1),
            "job2_s": round(job2.t_done - job2.t_submit, 1),
            "job2_wait_s": round(
                job2.t_first_worker - job2.t_submit, 1),
            "job2_peak_workers": job2.peak_workers,
            # launches are the scheduler's structural decision; peak
            # CONCURRENT workers additionally depends on how fast a
            # late-launched worker process comes up (load-dependent)
            "job2_workers_launched": len(job2.procs),
            # report_cn.md:88-91's utilization property: fraction of
            # slot-seconds busy over the makespan
            "utilization": round(
                used_slot_seconds
                / (slots * (max(job1.t_done, job2.t_done) - t0)), 3),
        }
    finally:
        job1.stop()
        job2.stop()


def run_mixed(slots, make_job, phases, timeout=900):
    """Mixed deployment (report_cn.md:94-106): a latency-sensitive
    service autoscales over `phases` = [(duration_s, slots_demanded)],
    and a LOW-PRIORITY elastic training job runs on whatever is left —
    yielding workers to the service via PREEMPTION (SIGKILL + task
    recovery) on scale-up and reclaiming slots on scale-down. Returns
    utilization of the whole cluster plus the training job's fate."""
    job = make_job()
    t0 = time.time()
    job.t_submit = t0
    deadline = t0 + timeout
    busy_slot_seconds = 0.0
    t_prev = t0
    preemptions = 0

    def demand_at(elapsed):
        acc = 0.0
        for dur, d in phases:
            acc += dur
            if elapsed < acc:
                return d
        return phases[-1][1]

    try:
        while time.time() < deadline:
            now = time.time()
            demand = demand_at(now - t0)
            live = job.live_workers
            busy_slot_seconds += min(demand + live, slots) * (
                now - t_prev)
            t_prev = now
            for i, rc in job.crashed_workers():
                if i not in job.recovered:
                    job.recovered.add(i)
                    job.master.task_d.recover_tasks(i)
            free_for_training = slots - demand
            if live > free_for_training:
                # service scaled up: preempt the newest training
                # workers (SIGKILL, the exit-137-class path); their
                # tasks go back to todo
                for idx in range(len(job.procs) - 1, -1, -1):
                    if live <= free_for_training:
                        break
                    p = job.procs[idx]
                    if p.poll() is None:
                        p.kill()
                        p.wait()
                        job.recovered.add(idx)
                        job.master.task_d.recover_tasks(idx)
                        preemptions += 1
                        live -= 1
            else:
                while (live < min(free_for_training,
                                  job.target_workers)
                       and not job.finished and job.todo_count > 0):
                    job.launch_worker()
                    live += 1
            if job.finished:
                break
            time.sleep(0.25)
        else:
            raise TimeoutError("mixed run exceeded %ds" % timeout)
        makespan = job.t_done - t0
        return {
            "utilization": round(
                busy_slot_seconds / (slots * makespan), 3),
            "training_makespan_s": round(makespan, 1),
            "preemptions": preemptions,
            "training_completed": True,
        }
    finally:
        job.stop()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--workers-per-job", type=int, default=2)
    ap.add_argument("--records", type=int, default=192)
    ap.add_argument("--records2", type=int, default=0,
                    help="job2 record count (default: same as --records;"
                         " make job2 larger to guarantee it is still "
                         "running when job1's slots free)")
    ap.add_argument("--job2-delay", type=float, default=3.0)
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--mixed", action="store_true",
                    help="run the mixed-deployment (service + "
                         "low-priority training) scenario instead")
    args = ap.parse_args(argv)
    if args.workers_per_job > args.slots:
        ap.error(
            "--workers-per-job (%d) must be <= --slots (%d): gang "
            "scheduling could never place a job"
            % (args.workers_per_job, args.slots)
        )

    from elasticdl_tpu.data import recordio_gen

    work = tempfile.mkdtemp(prefix="edl_elastic_bench.")
    try:
        dirs = []
        counts = [args.records, args.records2 or args.records]
        for i in (1, 2):
            d = os.path.join(work, "job%d" % i)
            recordio_gen.gen_mnist_like(
                d, num_files=2,
                records_per_file=counts[i - 1] // 2, seed=i,
            )
            dirs.append(d)

        def make_jobs():
            return (
                Job("job1", dirs[0], args.workers_per_job),
                Job("job2", dirs[1], args.workers_per_job),
            )

        if args.mixed:
            # service demand: low -> high -> low (the reference's
            # autoscaled-NGINX pattern); training takes the leftovers
            mixed = run_mixed(
                args.slots,
                lambda: Job("train", dirs[1], args.workers_per_job),
                phases=[(15, 1), (20, args.slots - 1), (10_000, 1)],
                timeout=args.timeout,
            )
            print(json.dumps({
                "metric": "mixed_deployment_cluster_utilization",
                "value": mixed["utilization"],
                "unit": "fraction",
                "vs_baseline": 1.0,
                "slots": args.slots,
                **mixed,
            }))
            return 0

        results = {}
        for mode in ("gang", "elastic"):
            results[mode] = run_cluster(
                mode, args.slots, make_jobs, args.job2_delay,
                timeout=args.timeout,
            )
            sys.stderr.write("%s: %s\n" % (mode, results[mode]))
    finally:
        shutil.rmtree(work, ignore_errors=True)
    speedup = (
        results["gang"]["makespan_s"]
        / max(results["elastic"]["makespan_s"], 1e-9)
    )
    print(json.dumps({
        "metric": "elastic_vs_gang_makespan_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": 1.0,
        "slots": args.slots,
        "workers_per_job": args.workers_per_job,
        "gang": results["gang"],
        "elastic": results["elastic"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
