#!/usr/bin/env python
"""Replayable SERVING kill drill (the inference twin of
scripts/run_master_kill_drill.py).

Runs the REAL serving stack as a subprocess (`python -m
elasticdl_tpu.serving.main`) and drills the two ways a serving process
dies, asserting the client-visible invariant both times: every
in-flight request either COMPLETES or terminates with a CLEAN status —
never a hang.

Phase 1 — graceful (SIGTERM mid-load): admission closes, queued
  requests get RESOURCE_EXHAUSTED, seated requests drain to completion,
  the process exits 0. Allowed outcomes: OK / RESOURCE_EXHAUSTED /
  DEADLINE_EXCEEDED.

Phase 2 — hard kill (EDL_FAULT_SPEC=generate:kill:1:skip=N, the same
  spec grammar the master drills use): the process SIGKILLs itself
  mid-load; surviving clients see the transport die as UNAVAILABLE /
  CANCELLED within seconds. The point is the absence of hangs, not the
  status: a SIGKILL'd server cannot promise more than a torn socket,
  and common/retry.py classifies exactly these codes as transient for
  the retry-elsewhere path.

Phase 3 — shared-prefix ledger (paged mode: EDL_KV_SHARED=1): every
  request carries a COMMON prompt prefix so refcounted shared chains
  are resident (serving/kv_pool.py); a full wave completes and the
  block ledger must drain clean (every block free or cached — no
  leaked refcount, no double-free panic), then the server is SIGKILLed
  mid-load with the chains still shared and a FRESH server must come
  up, serve the same shared-prefix load, and drain to a clean ledger
  again — a crash can never corrupt block accounting across restarts
  because the ledger is process-local and rebuilt from nothing.

Phase 4 — tiered host spill (paged mode, --kv_host_bytes): three
  distinct system prompts over a device pool too small for their
  chains plus an active seat, so reclaimable chains are forced to
  SPILL to the host tier and REVIVE by upload when their prefix comes
  back around. A full wave completes with revivals demonstrably
  served (`prefill_tokens_revived > 0`), the two-tier ledger drains
  clean (every device block free | cached, host bytes inside the
  budget — a spilled chain is either revived or budget-dropped,
  never leaked), then the server is SIGKILLed mid-load with spilled
  chains live and a FRESH server must come up with an EMPTY host
  tier (the tier is process-local — a crash can never leak host
  memory across restarts), serve the same load, revive again, and
  drain to a clean two-tier ledger.

Phase 5 — disaggregated handoff (paged+shared, serving/disagg.py): a
  role-split fleet (one prefill replica, one decode replica, a router
  orchestrating the chain handoff between them) first proves the
  success path — handoffs counted, chains exported/imported, both
  pool ledgers drain clean with zero transfers in flight — then a
  fresh prefill replica armed with EDL_FAULT_SPEC=export_chain:kill:1
  SIGKILLs itself WITH A TRANSFER IN FLIGHT: every accepted request
  must still complete (the router falls back to a cold dispatch; a
  handoff may cost the warm-start, never the request) and the
  surviving decode pool must drain to a clean ledger.

All phases run TWICE: against the dense KV pool and against the
block-paged pool (EDL_KV_PAGED=1, serving/kv_pool.py) — drain and
SIGKILL semantics must hold regardless of where the cache rows live
(phase 3's ledger assertions are paged-only; dense mode still proves
the no-hang/clean-status contract under the shared-prefix load; the
phase 4 host tier exists only over the paged pool).
A THIRD pass runs phases 1 + 3 + 4 with INT8 arenas
(kv_cache_dtype='int8'): graceful drain, the shared-chain ledger,
the spill/revive lifecycle, SIGKILL mid-load and the fresh-restart
rebuild must all hold with scale leaves in the arenas (the hard-kill
transport semantics of phase 2 are dtype-blind and already covered).

Usage: python scripts/run_server_kill_drill.py
Exit 0 = all phases hold in all modes."""

import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

MODEL_PARAMS = (
    "vocab_size=16; seq_len=32; embed_dim=32; num_heads=2; num_layers=1"
)
CLIENT_TIMEOUT = 60.0  # backstop; the drill asserts we never get near it


def launch_ready(cmd, extra_env=None, ready_marker="SERVING_READY",
                 startup_secs=180):
    """Start a drill subprocess and wait for its `<marker> port=N`
    readiness line; returns (proc, port) with the pipe drained in the
    background so the child can't block on a full buffer. Shared by
    this drill and scripts/run_router_chaos_drill.py."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    port = None
    deadline = time.time() + startup_secs
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    "process died during startup (rc=%s)"
                    % proc.returncode
                )
            continue
        if line.startswith(ready_marker):
            port = int(line.strip().split("port=")[1])
            break
    if port is None:
        proc.kill()
        proc.wait(timeout=30)  # reap before bailing — no zombie
        raise RuntimeError("process never became ready: %r" % cmd)
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True
    ).start()
    return proc, port


# the common system-prompt prefix phase 3 shares (2 full blocks at
# the drill's --kv_block_size 4, so chains actually form)
SHARED_PREFIX = [1, 2, 3, 4, 5, 6, 7, 2]


def start_server(extra_env=None, num_slots=1, model_params=None,
                 extra_args=()):
    return launch_ready(
        [
            sys.executable, "-m", "elasticdl_tpu.serving.main",
            "--model_zoo", os.path.join(REPO, "model_zoo"),
            "--model_def", "transformer_lm.transformer_lm.custom_model",
            "--model_params", model_params or MODEL_PARAMS,
            "--port", "0", "--num_slots", str(num_slots),
            "--queue_capacity", "8", "--kv_block_size", "4",
            *extra_args,
        ],
        extra_env=extra_env,
    )


def fire_requests(port, n, max_new=24, shared_prefix=False,
                  prompt_fn=None):
    """n concurrent unary requests; returns (outcomes, elapsed) where
    outcomes[i] is 'OK' or a gRPC status name. Joins with a hard bound:
    any thread still alive past the client timeout = a hang = failure.
    shared_prefix=True sends the common system prompt + a per-request
    tail, so the paged+shared pool builds refcounted chains;
    prompt_fn(i) overrides the prompt outright (the host-tier phase
    rotates several distinct system prompts)."""
    import grpc

    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel

    stub = ServingStub(build_channel("localhost:%d" % port))
    outcomes = {}
    lock = threading.Lock()

    def call(i):
        if prompt_fn is not None:
            prompt = prompt_fn(i)
        else:
            prompt = (
                SHARED_PREFIX + [1 + i % 5] if shared_prefix
                else [1 + i % 5, 2]
            )
        try:
            stub.generate(
                pb.GenerateRequest(
                    prompt=prompt, max_new_tokens=max_new,
                ),
                timeout=CLIENT_TIMEOUT,
            )
            code = "OK"
        except grpc.RpcError as e:
            code = e.code().name
        with lock:
            outcomes[i] = code

    threads = [
        threading.Thread(target=call, args=(i,)) for i in range(n)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    return threads, outcomes, t0


def join_all(threads, outcomes, t0, n):
    for t in threads:
        t.join(timeout=CLIENT_TIMEOUT + 30)
    elapsed = time.monotonic() - t0
    hung = [t for t in threads if t.is_alive()]
    if hung:
        raise AssertionError("%d client threads HUNG" % len(hung))
    if len(outcomes) != n:
        raise AssertionError(
            "only %d/%d clients terminated" % (len(outcomes), n)
        )
    return elapsed


def phase_graceful(mode_env=None, mode="dense", model_params=None):
    print("[drill] phase 1 (%s): SIGTERM mid-load (graceful drain)"
          % mode)
    proc, port = start_server(extra_env=mode_env,
                              model_params=model_params)
    try:
        threads, outcomes, t0 = fire_requests(port, 8)
        time.sleep(0.4)  # let some seat, some queue
        proc.send_signal(signal.SIGTERM)
        elapsed = join_all(threads, outcomes, t0, 8)
        rc = proc.wait(timeout=60)
        codes = sorted(outcomes.values())
        print("[drill]   outcomes=%s elapsed=%.1fs rc=%s"
              % (codes, elapsed, rc))
        allowed = {"OK", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        assert set(codes) <= allowed, codes
        assert "OK" in codes, "drain completed nothing: %s" % codes
        assert elapsed < CLIENT_TIMEOUT - 10, "clients rode the timeout"
        assert rc == 0, "graceful exit must return 0, got %s" % rc
    finally:
        if proc.poll() is None:
            proc.kill()
    print("[drill] phase 1 (%s) OK" % mode)


def phase_hard_kill(mode_env=None, mode="dense"):
    print("[drill] phase 2 (%s): EDL_FAULT_SPEC self-SIGKILL mid-load"
          % mode)
    env = {"EDL_FAULT_SPEC": "generate:kill:1:skip=3"}
    env.update(mode_env or {})
    proc, port = start_server(extra_env=env)
    try:
        threads, outcomes, t0 = fire_requests(port, 8)
        elapsed = join_all(threads, outcomes, t0, 8)
        codes = sorted(outcomes.values())
        print("[drill]   outcomes=%s elapsed=%.1fs" % (codes, elapsed))
        # a SIGKILL'd transport yields UNAVAILABLE/CANCELLED for the
        # survivors; requests completed before the kill are OK. The
        # invariant is clean termination, fast.
        allowed = {"OK", "UNAVAILABLE", "CANCELLED",
                   "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        assert set(codes) <= allowed, codes
        assert any(c != "OK" for c in codes), (
            "the kill never fired: %s" % codes
        )
        assert elapsed < CLIENT_TIMEOUT - 10, "clients rode the timeout"
        proc.wait(timeout=30)
        assert proc.returncode != 0  # SIGKILL, by design
    finally:
        if proc.poll() is None:
            proc.kill()
    print("[drill] phase 2 (%s) OK" % mode)


def _ledger(port):
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel

    stub = ServingStub(build_channel("localhost:%d" % port))
    return stub.server_status(pb.ServerStatusRequest(), timeout=30)


def _assert_clean_ledger(st, where):
    """Post-drain block accounting: every block free or cached —
    a leaked refcount would show as blocks_free < blocks_total, a
    double-free would have crashed the allocator long before."""
    assert st.kv_blocks_free == st.kv_blocks_total, (
        "%s: %d/%d blocks free (leaked refcount?)"
        % (where, st.kv_blocks_free, st.kv_blocks_total)
    )


def phase_shared_ledger(mode_env=None, mode="dense",
                        model_params=None):
    print("[drill] phase 3 (%s): shared prefixes resident through "
          "SIGKILL + restart" % mode)
    env = dict(mode_env or {})
    env["EDL_KV_SHARED"] = "1"
    proc, port = start_server(extra_env=env, num_slots=3,
                              model_params=model_params)
    paged = mode.startswith("paged")
    try:
        # wave 1: completes fully; the ledger must drain clean with
        # the prefix chains parked reclaimable (no leaked refcount)
        threads, outcomes, t0 = fire_requests(
            port, 6, max_new=16, shared_prefix=True
        )
        join_all(threads, outcomes, t0, 6)
        assert set(outcomes.values()) == {"OK"}, outcomes
        st = _ledger(port)
        if paged:
            assert st.kv_paged and st.kv_shared
            assert st.prefix_hit_tokens > 0, (
                "shared load never matched a prefix"
            )
            _assert_clean_ledger(st, "post-wave-1")
        # wave 2: SIGKILL lands mid-load with shared chains LIVE
        threads, outcomes, t0 = fire_requests(
            port, 6, max_new=16, shared_prefix=True
        )
        time.sleep(0.3)
        proc.kill()
        join_all(threads, outcomes, t0, 6)
        allowed = {"OK", "UNAVAILABLE", "CANCELLED",
                   "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        assert set(outcomes.values()) <= allowed, outcomes
    finally:
        if proc.poll() is None:
            proc.kill()
    # restart: a fresh process must rebuild clean block accounting and
    # serve the same shared-prefix load — nothing about the crash can
    # poison the (process-local) ledger
    proc, port = start_server(extra_env=env, num_slots=3,
                              model_params=model_params)
    try:
        threads, outcomes, t0 = fire_requests(
            port, 6, max_new=16, shared_prefix=True
        )
        join_all(threads, outcomes, t0, 6)
        assert set(outcomes.values()) == {"OK"}, outcomes
        st = _ledger(port)
        if paged:
            assert st.prefix_hit_tokens > 0
            _assert_clean_ledger(st, "post-restart")
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
    print("[drill] phase 3 (%s) OK" % mode)


# three distinct 2-block system prompts (kv_block_size 4): working
# set 6 blocks, deliberately more than the phase-4 device pool can
# cache beside an active seat — chains must spill and revive
HOST_PREFIXES = [
    [1, 2, 3, 4, 5, 6, 7, 2],
    [2, 3, 4, 5, 6, 7, 1, 3],
    [3, 4, 5, 6, 7, 1, 2, 4],
]
HOST_BUDGET_BYTES = 1 << 20


def _host_prompt(i):
    return HOST_PREFIXES[i % len(HOST_PREFIXES)] + [1 + i % 5]


def phase_host_tier(mode_env=None, mode="paged", model_params=None):
    print("[drill] phase 4 (%s): host tier — spill under pressure, "
          "revive through a wave, SIGKILL with spilled chains live, "
          "fresh restart rebuilds an empty tier" % mode)
    env = dict(mode_env or {})
    env["EDL_KV_SHARED"] = "1"
    # 8 device blocks: one active seat commits 6 (9 prompt rows + 15
    # decode rows), so at most one 2-block chain survives beside it —
    # the other two spill; the host budget holds them all. The wave
    # fires 12 concurrent requests, so the queue must hold the tail
    # that waits out the block backpressure (argparse keeps the last
    # --queue_capacity, overriding start_server's default of 8).
    extra = ("--kv_num_blocks", "8",
             "--kv_host_bytes", str(HOST_BUDGET_BYTES),
             "--queue_capacity", "16")
    proc, port = start_server(extra_env=env, num_slots=2,
                              model_params=model_params,
                              extra_args=extra)
    try:
        # wave 1: 12 requests rotating 3 distinct prefixes — every
        # return of a prefix finds its chain evicted (spilled) and
        # revives it by upload instead of re-prefilling
        threads, outcomes, t0 = fire_requests(
            port, 12, max_new=16, prompt_fn=_host_prompt
        )
        join_all(threads, outcomes, t0, 12)
        assert set(outcomes.values()) == {"OK"}, outcomes
        st = _ledger(port)
        assert st.kv_paged and st.kv_shared
        assert st.prefix_hit_tokens > 0
        # the spill machinery demonstrably engaged: chains were
        # demoted AND came back by upload
        assert st.revive_uploads > 0, "no revival upload served"
        assert st.prefill_tokens_revived > 0
        # two-tier ledger: device side fully free|cached, host side
        # inside its byte budget — spilled chains are revived or
        # budget-dropped, never leaked
        _assert_clean_ledger(st, "post-wave-1 (host tier)")
        assert st.kv_host_bytes <= HOST_BUDGET_BYTES, (
            "host tier over budget: %d > %d"
            % (st.kv_host_bytes, HOST_BUDGET_BYTES)
        )
        revived_before_kill = st.prefill_tokens_revived
        # wave 2: SIGKILL mid-load with spilled chains LIVE
        threads, outcomes, t0 = fire_requests(
            port, 6, max_new=16, prompt_fn=_host_prompt
        )
        time.sleep(0.3)
        proc.kill()
        join_all(threads, outcomes, t0, 6)
        allowed = {"OK", "UNAVAILABLE", "CANCELLED",
                   "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED"}
        assert set(outcomes.values()) <= allowed, outcomes
    finally:
        if proc.poll() is None:
            proc.kill()
    # restart: the host tier is process-local — a fresh server must
    # come up EMPTY (no leaked host memory, no phantom spilled
    # chains), serve the same rotating load, revive again, and drain
    # to a clean two-tier ledger
    proc, port = start_server(extra_env=env, num_slots=2,
                              model_params=model_params,
                              extra_args=extra)
    try:
        st0 = _ledger(port)
        assert st0.kv_host_blocks == 0 and st0.kv_host_bytes == 0, (
            "fresh server has a non-empty host tier"
        )
        assert st0.prefill_tokens_revived == 0
        threads, outcomes, t0 = fire_requests(
            port, 12, max_new=16, prompt_fn=_host_prompt
        )
        join_all(threads, outcomes, t0, 12)
        assert set(outcomes.values()) == {"OK"}, outcomes
        st = _ledger(port)
        assert st.revive_uploads > 0
        assert st.prefill_tokens_revived > 0
        _assert_clean_ledger(st, "post-restart (host tier)")
        assert st.kv_host_bytes <= HOST_BUDGET_BYTES
        print("[drill]   revived %d tokens pre-kill, %d post-restart"
              % (revived_before_kill, st.prefill_tokens_revived))
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
    print("[drill] phase 4 (%s) OK" % mode)


# two distinct 2-block system prompts for the disagg phase (one per
# leg, so the kill leg's handoff is never satisfied by leg 1's
# already-imported chain)
DISAGG_PREFIXES = [
    [1, 2, 3, 4, 5, 6, 7, 2],
    [4, 5, 6, 7, 1, 2, 3, 5],
]


def _start_disagg_router(replica_ports):
    """Router subprocess over the two-pool fleet; affinity blocks
    sized to the drill's 8-token system prompts so requests carry a
    fingerprint (no fingerprint = no handoff to drill)."""
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.serving.router_main",
        "--port", "0", "--poll_secs", "0.25", "--lease_secs", "2.0",
        "--breaker_cooldown_secs", "1.0",
        "--redispatch_window_secs", "60",
        "--affinity_block_tokens", "8",
    ]
    for p in replica_ports:
        cmd += ["--replica", "localhost:%d" % p]
    return launch_ready(cmd, ready_marker="ROUTER_READY")


def _fire_routed(router_port, n, prefix, max_new=8):
    """n concurrent requests through the ROUTER (RouterStub), all
    sharing `prefix` + a per-request tail; same hang-bounded join
    contract as fire_requests."""
    import grpc

    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel

    stub = RouterStub(build_channel("localhost:%d" % router_port))
    outcomes = {}
    lock = threading.Lock()

    def call(i):
        try:
            stub.router_generate(
                pb.GenerateRequest(
                    prompt=prefix + [1 + i % 5],
                    max_new_tokens=max_new,
                ),
                timeout=CLIENT_TIMEOUT,
            )
            code = "OK"
        except grpc.RpcError as e:
            code = e.code().name
        with lock:
            outcomes[i] = code

    threads = [
        threading.Thread(target=call, args=(i,)) for i in range(n)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    return threads, outcomes, t0


def _router_status(port):
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import RouterStub, build_channel

    stub = RouterStub(build_channel("localhost:%d" % port))
    return stub.router_status(pb.RouterStatusRequest(), timeout=30)


def _assert_pool_settled(st, where):
    """A disagg pool's post-drain ledger: every block free|cached AND
    no transfer-family RPC still executing — a stuck inflight gauge
    would mean a handoff the two-pool ledger cannot reconcile."""
    _assert_clean_ledger(st, where)
    assert st.transfers_inflight == 0, (
        "%s: %d transfers still in flight after drain"
        % (where, st.transfers_inflight)
    )


def phase_disagg_handoff():
    """Phase 5 — disaggregated prefill/decode handoff (paged+shared):
    a dedicated prefill replica warms chains and hands them to the
    decode replica as a dense byte copy (router-orchestrated,
    serving/disagg.py). Leg 1 proves the success path end to end:
    requests complete through the router with the handoff ledger
    moving on BOTH pools and both ledgers draining clean. Leg 2 arms
    EDL_FAULT_SPEC=export_chain:kill:1 on a fresh prefill replica, so
    the replica SIGKILLs itself WITH THE TRANSFER IN FLIGHT — the
    router must fall back to a plain cold dispatch (zero accepted-
    request loss) and the surviving decode pool must still drain to a
    clean ledger with nothing in flight."""
    print("[drill] phase 5 (disagg): prefill->decode handoff, then "
          "SIGKILL the prefill replica mid-transfer")
    env = {"EDL_KV_PAGED": "1", "EDL_KV_SHARED": "1"}
    decode, decode_port = start_server(
        extra_env=env, num_slots=3,
        extra_args=("--role", "decode", "--queue_capacity", "16"),
    )
    prefill = prefill2 = router = router2 = None
    try:
        # ---- leg 1: the handoff succeeds
        prefill, prefill_port = start_server(
            extra_env=env, num_slots=2,
            extra_args=("--role", "prefill"),
        )
        router, router_port = _start_disagg_router(
            [prefill_port, decode_port]
        )
        threads, outcomes, t0 = _fire_routed(
            router_port, 4, DISAGG_PREFIXES[0]
        )
        join_all(threads, outcomes, t0, 4)
        assert set(outcomes.values()) == {"OK"}, outcomes
        rst = _router_status(router_port)
        assert rst.disagg_handoffs >= 1, (
            "no handoff happened: handoffs=%d fallbacks=%d"
            % (rst.disagg_handoffs, rst.disagg_fallbacks)
        )
        pst = _ledger(prefill_port)
        dst = _ledger(decode_port)
        assert pst.role == "prefill" and dst.role == "decode"
        assert pst.chain_exports >= 1, "prefill pool exported nothing"
        assert dst.chain_imports >= 1, "decode pool imported nothing"
        assert dst.chain_import_tokens >= 8
        _assert_pool_settled(pst, "leg-1 prefill pool")
        _assert_pool_settled(dst, "leg-1 decode pool")
        print("[drill]   leg 1: handoffs=%d exports=%d imports=%d "
              "(%d tokens)" % (rst.disagg_handoffs, pst.chain_exports,
                               dst.chain_imports,
                               dst.chain_import_tokens))
        router.send_signal(signal.SIGTERM)
        router.wait(timeout=60)
        prefill.send_signal(signal.SIGTERM)
        prefill.wait(timeout=60)
        # ---- leg 2: the prefill replica dies mid-transfer
        kill_env = dict(env)
        kill_env["EDL_FAULT_SPEC"] = "export_chain:kill:1"
        prefill2, prefill2_port = start_server(
            extra_env=kill_env, num_slots=2,
            extra_args=("--role", "prefill"),
        )
        router2, router2_port = _start_disagg_router(
            [prefill2_port, decode_port]
        )
        threads, outcomes, t0 = _fire_routed(
            router2_port, 4, DISAGG_PREFIXES[1]
        )
        join_all(threads, outcomes, t0, 4)
        # the client-visible invariant: a handoff can cost the warm
        # start, NEVER the request — every accepted request completes
        assert set(outcomes.values()) == {"OK"}, (
            "accepted requests lost to a mid-transfer kill: %s"
            % outcomes
        )
        prefill2.wait(timeout=30)
        assert prefill2.returncode != 0  # SIGKILL, by design
        rst2 = _router_status(router2_port)
        assert rst2.disagg_fallbacks >= 1, (
            "the kill never interrupted a transfer: handoffs=%d "
            "fallbacks=%d" % (rst2.disagg_handoffs,
                              rst2.disagg_fallbacks)
        )
        dst2 = _ledger(decode_port)
        _assert_pool_settled(dst2, "leg-2 decode pool")
        print("[drill]   leg 2: fallbacks=%d, all %d requests OK, "
              "decode ledger clean" % (rst2.disagg_fallbacks,
                                       len(outcomes)))
    finally:
        for proc in (router, router2, prefill, prefill2, decode):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
    print("[drill] phase 5 (disagg) OK")


def main():
    # dense pool, then the block-paged pool (kv_block_size 4 divides
    # the drill model's seq_len=32; sharing needs full blocks)
    for mode, env in (
        ("dense", {"EDL_KV_PAGED": "0"}),
        ("paged", {"EDL_KV_PAGED": "1"}),
    ):
        phase_graceful(mode_env=env, mode=mode)
        phase_hard_kill(mode_env=env, mode=mode)
        phase_shared_ledger(mode_env=env, mode=mode)
    # the tiered host spill lifecycle exists only over the paged pool
    phase_host_tier(mode_env={"EDL_KV_PAGED": "1"}, mode="paged")
    # int8 arenas: the same drain / SIGKILL-restart / shared-chain
    # ledger / spill-revive invariants must hold with scale leaves in
    # the arenas (kv_cache_dtype='int8'); the hard-kill transport
    # semantics are dtype-blind and already covered above
    int8_params = MODEL_PARAMS + "; kv_cache_dtype='int8'"
    phase_graceful(mode_env={"EDL_KV_PAGED": "1"}, mode="paged_int8",
                   model_params=int8_params)
    phase_shared_ledger(mode_env={"EDL_KV_PAGED": "1"},
                        mode="paged_int8", model_params=int8_params)
    phase_host_tier(mode_env={"EDL_KV_PAGED": "1"},
                    mode="paged_int8", model_params=int8_params)
    # disaggregated prefill/decode: clean handoff, then a SIGKILL'd
    # prefill replica mid-transfer (paged+shared only — the handoff
    # surface exists only over the prefix-shared paged pool)
    phase_disagg_handoff()
    print("[drill] serving kill drill PASSED (dense + paged + "
          "paged-int8, shared-prefix ledger, host-tier spill/revive, "
          "disagg handoff)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
