"""Gradient-plane collective bandwidth (BASELINE.md target:
"PS→allreduce gradient bandwidth").

The reference's gradient plane was gRPC push/pull to PS pods (256 MB
message cap); ours is the psum XLA inserts inside the compiled step.
This measures that plane directly: an all-reduce of a flagship-sized
gradient pytree over every device the mesh has.

* multi-chip TPU: the number is ICI all-reduce bandwidth — the
  v5e-16 figure BASELINE.md asks to establish;
* single chip: the collective degenerates to identity, so the bench
  reports the in-place gradient update bandwidth (HBM) instead and
  labels it as such;
* CPU (virtual 8-device mesh): functional smoke only, labeled cpu.

Timing is fetch-forced (common/timing_utils.fetch_sync): over the
tunneled PJRT plugin block_until_ready can return early.

    python scripts/bench_collectives.py [size_mb]

Prints ONE JSON line {"metric": ..., "value": GB/s, ...}.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.common.timing_utils import fetch_sync
    from elasticdl_tpu.parallel import mesh as mesh_lib

    size_mb = float(sys.argv[1]) if len(sys.argv) > 1 else 256.0
    n = int(size_mb * 1e6 / 4)
    mesh = mesh_lib.build_mesh()
    n_dev = mesh.size
    axes = tuple(mesh.axis_names)

    def grad_allreduce(local):
        # the gradient plane: sum over every mesh axis (what the
        # batch-sharded loss's backward inserts for replicated params)
        return jax.lax.psum(local, axes)

    fn = jax.jit(
        jax.shard_map(
            grad_allreduce, mesh=mesh,
            in_specs=P(axes[0]), out_specs=P(),
            check_vma=False,
        )
    )
    rng = np.random.RandomState(0)
    # leading dim divisible by every axis: pad up
    rows = ((n // 128 + n_dev - 1) // n_dev) * n_dev
    x = jnp.asarray(rng.rand(rows, 128).astype(np.float32))
    bytes_payload = x.size * 4

    out = fn(x)
    fetch_sync(out)
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    fetch_sync(out)
    dt = (time.perf_counter() - t0) / iters

    platform = jax.default_backend()
    # ring all-reduce moves 2*(n-1)/n of the payload per link; report
    # the conventional algorithm bandwidth payload/time and the bus
    # bandwidth alongside
    algo_bw = bytes_payload / dt
    bus_bw = algo_bw * (2 * (n_dev - 1) / n_dev if n_dev > 1 else 1.0)
    print(json.dumps({
        "metric": (
            "grad_allreduce_bandwidth" if n_dev > 1
            else "grad_reduce_hbm_bandwidth_single_device"
        ),
        "value": round(algo_bw / 1e9, 2),
        "unit": "GB/s",
        "vs_baseline": 1.0,
        "bus_bandwidth_gbps": round(bus_bw / 1e9, 2),
        "payload_mb": round(bytes_payload / 1e6, 1),
        "devices": n_dev,
        "mesh": dict(mesh.shape),
        "platform": platform,
        "step_ms": round(dt * 1e3, 3),
    }))


if __name__ == "__main__":
    main()
