"""Gradient-plane collective bandwidth (BASELINE.md target:
"PS→allreduce gradient bandwidth") + expert-parallel all-to-all cost.

The reference's gradient plane was gRPC push/pull to PS pods (256 MB
message cap); ours is the psum XLA inserts inside the compiled step.
This measures that plane directly: an all-reduce of a flagship-sized
gradient pytree over every device the mesh has.

* multi-chip TPU: the number is ICI all-reduce bandwidth — the
  v5e-16 figure BASELINE.md asks to establish;
* single chip: the collective degenerates to identity, so the bench
  reports the in-place gradient update bandwidth (HBM) instead and
  labels it as such;
* CPU (virtual 8-device mesh): functional smoke only, labeled cpu.

With >1 device it ALSO measures the MoE expert-parallel all-to-all
(parallel/moe.py moe_mlp_apply_a2a) at 8 and 64 experts: the raw
all_to_all of the capacity-bounded [E, C, D] send buffer (bytes/step +
latency + effective bandwidth) and the full explicit-dispatch forward
(route -> a2a -> expert FFNs -> reverse a2a -> combine). One JSON line
per a2a measurement, then the final all-reduce line with an "a2a"
summary dict embedded (hw_session records the final line).

Timing is fetch-forced (common/timing_utils.fetch_sync): over the
tunneled PJRT plugin block_until_ready can return early.

    python scripts/bench_collectives.py [size_mb]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import bench as bench_mod

    bench_mod.require_accelerator_or_exit()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.common.timing_utils import fetch_sync
    from elasticdl_tpu.parallel import mesh as mesh_lib

    size_mb = float(sys.argv[1]) if len(sys.argv) > 1 else 256.0
    n = int(size_mb * 1e6 / 4)
    mesh = mesh_lib.build_mesh()
    n_dev = mesh.size
    axes = tuple(mesh.axis_names)

    def grad_allreduce(local):
        # the gradient plane: sum over every mesh axis (what the
        # batch-sharded loss's backward inserts for replicated params)
        return jax.lax.psum(local, axes)

    fn = jax.jit(
        jax.shard_map(
            grad_allreduce, mesh=mesh,
            in_specs=P(axes[0]), out_specs=P(),
            check_vma=False,
        )
    )
    rng = np.random.RandomState(0)
    # leading dim divisible by every axis: pad up
    rows = ((n // 128 + n_dev - 1) // n_dev) * n_dev
    x = jnp.asarray(rng.rand(rows, 128).astype(np.float32))
    bytes_payload = x.size * 4

    out = fn(x)
    fetch_sync(out)
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    fetch_sync(out)
    dt = (time.perf_counter() - t0) / iters

    platform = jax.default_backend()

    # --- expert-parallel all-to-all (VERDICT r04 #4) ---
    a2a_summary = {}
    if n_dev > 1:
        from elasticdl_tpu.parallel import moe as moe_lib

        ep_mesh = mesh_lib.build_mesh({"ep": n_dev})
        t_tok, dmodel, hdim, topk, cf = 8192, 512, 512, 2, 1.25
        for n_exp in (8, 64):
            if n_exp % n_dev:
                continue
            cap = moe_lib.expert_capacity(
                t_tok // n_dev * topk, n_exp, cf)
            e_loc = n_exp // n_dev
            local_bytes = n_dev * e_loc * cap * dmodel * 4
            # raw all_to_all of the dispatch send buffer
            buf = jnp.asarray(rng.rand(
                n_dev * n_dev, e_loc, cap, dmodel).astype(np.float32))
            a2a_fn = jax.jit(
                jax.shard_map(
                    lambda b: jax.lax.all_to_all(
                        b, "ep", split_axis=0, concat_axis=0),
                    mesh=ep_mesh, in_specs=P("ep"), out_specs=P("ep"),
                    check_vma=False,
                )
            )
            out = a2a_fn(buf)
            fetch_sync(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = a2a_fn(buf)
            fetch_sync(out)
            raw_dt = (time.perf_counter() - t0) / iters

            # full explicit dispatch forward at the same shapes
            prng = np.random.RandomState(1)
            params = {
                "router": jnp.asarray(
                    prng.rand(dmodel, n_exp).astype(np.float32)),
                "w_up": jnp.asarray((prng.rand(
                    n_exp, dmodel, hdim) / np.sqrt(dmodel)
                ).astype(np.float32)),
                "b_up": jnp.zeros((n_exp, hdim), jnp.float32),
                "w_down": jnp.asarray((prng.rand(
                    n_exp, hdim, dmodel) / np.sqrt(hdim)
                ).astype(np.float32)),
                "b_down": jnp.zeros((n_exp, dmodel), jnp.float32),
            }
            xt = jnp.asarray(
                rng.rand(t_tok, dmodel).astype(np.float32))
            disp_fn = jax.jit(
                lambda p, xv: moe_lib.moe_mlp_apply_a2a(
                    p, xv, ep_mesh, capacity_factor=cf,
                    router_top_k=topk,
                )[0]
            )
            with ep_mesh:
                out = disp_fn(params, xt)
                fetch_sync(out)
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = disp_fn(params, xt)
                fetch_sync(out)
            disp_dt = (time.perf_counter() - t0) / iters
            entry = {
                "experts": n_exp,
                "capacity_per_group": cap,
                "a2a_bytes_per_step_per_device_mb": round(
                    local_bytes / 1e6, 2),
                "a2a_global_bytes_per_step_mb": round(
                    local_bytes * n_dev / 1e6, 2),
                "a2a_latency_ms": round(raw_dt * 1e3, 3),
                "a2a_effective_gbps": round(
                    local_bytes * n_dev / raw_dt / 1e9, 2),
                "dispatch_fwd_ms": round(disp_dt * 1e3, 3),
                "tokens": t_tok, "d_model": dmodel,
                "router_top_k": topk, "capacity_factor": cf,
            }
            a2a_summary["e%d" % n_exp] = entry
            print(json.dumps(dict(
                {"metric": "moe_a2a_dispatch", "platform": platform,
                 "devices": n_dev}, **entry)), flush=True)

    # ring all-reduce moves 2*(n-1)/n of the payload per link; report
    # the conventional algorithm bandwidth payload/time and the bus
    # bandwidth alongside
    algo_bw = bytes_payload / dt
    bus_bw = algo_bw * (2 * (n_dev - 1) / n_dev if n_dev > 1 else 1.0)
    print(json.dumps({
        "metric": (
            "grad_allreduce_bandwidth" if n_dev > 1
            else "grad_reduce_hbm_bandwidth_single_device"
        ),
        "value": round(algo_bw / 1e9, 2),
        "unit": "GB/s",
        "vs_baseline": None if platform == "cpu" else 1.0,
        "bus_bandwidth_gbps": round(bus_bw / 1e9, 2),
        "payload_mb": round(bytes_payload / 1e6, 1),
        "devices": n_dev,
        "mesh": dict(mesh.shape),
        "platform": platform,
        "step_ms": round(dt * 1e3, 3),
        "a2a": a2a_summary or None,
    }))


if __name__ == "__main__":
    main()
