#!/usr/bin/env bash
# Bounded TPU-tunnel probe, appending one timestamped line to
# TUNNEL_LOG.md. The axon tunnel flaps (BENCHNOTES.md); this keeps an
# auditable record of when hardware was reachable. Usage:
#   scripts/probe_tpu.sh [timeout_s]
set -u
cd "$(dirname "$0")/.."
T=${1:-90}
TS=$(date -u +"%Y-%m-%d %H:%M UTC")
OUT=$(PYTHONPATH=/root/.axon_site timeout "$T" python -c \
  "import jax, jax.numpy as jnp; x = jnp.ones((256, 256)); \
   print(float((x @ x).sum())); print('PROBE_UP', jax.devices())" 2>&1)
if echo "$OUT" | grep -q PROBE_UP; then
    STATUS="UP: $(echo "$OUT" | grep PROBE_UP | tail -c 120)"
else
    STATUS="wedged (no response in ${T}s)"
fi
echo "- $TS — $STATUS" >> TUNNEL_LOG.md
echo "$STATUS"
