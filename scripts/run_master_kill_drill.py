#!/usr/bin/env python
"""Replayable MASTER-kill recovery drill (the control-plane twin of
scripts/run_worker_kill_drill.py).

Runs the REAL distributed stack with the master as a subprocess —
`python -m elasticdl_tpu.master.main` with a --job_state_dir journal,
LocalInstanceManager spawning a worker subprocess — then SIGKILLs the
MASTER mid-job. The orphaned worker keeps retrying inside its bounded
reconnect window (common/retry.py) instead of exiting; a second master
process started over the same --job_state_dir restores the dispatcher
from the journal (todo ∪ requeued-doing), the worker re-registers, and
the job runs to completion. The drill then audits the two journals:
every record range must be completed exactly once (done ∪ done_recovered
over both master lifetimes), and the recovery gauges (master/restarts,
master/recovery_requeued_tasks, fault/rpc_retries) must appear in the
TensorBoard event stream.

Usage: python scripts/run_master_kill_drill.py
Exit 0 = recovered, exactly-once accounting holds; the transcript
narrates each phase.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def read_journal(path):
    """Parse journal events, tolerating the torn final line a SIGKILL
    can leave behind (same rule as state_store.load)."""
    events = []
    if not os.path.exists(path):
        return events
    with open(path) as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            if i != len(lines) - 1:
                raise
    return events


def completed_ranges(events):
    """(shard, start, end) of every done / done_recovered event."""
    out = []
    for ev in events:
        if ev.get("ev") in ("done", "done_recovered"):
            p = ev["task"]
            out.append((p[0], p[1], p[2]))
    return out


def find_worker_pids():
    """PIDs of elasticdl_tpu.worker.main processes (the orphan-worker
    probe: /proc scan, no psutil dependency)."""
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as f:
                cmd = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        if "elasticdl_tpu.worker.main" in cmd:
            pids.append(int(pid))
    return pids


def tb_stream_contains(tb_dir, tags):
    """True when every tag appears in some TensorBoard event file under
    tb_dir (tags are embedded as plain strings in the Event protos, so a
    byte scan needs no TF)."""
    blobs = []
    for root, _, files in os.walk(tb_dir):
        for name in files:
            if "tfevents" in name:
                with open(os.path.join(root, name), "rb") as f:
                    blobs.append(f.read())
    blob = b"".join(blobs)
    return all(tag.encode() in blob for tag in tags)


def master_cmd(port, train_dir, state_dir, status_file, tb_dir,
               num_workers, records_per_task, minibatch_size, num_epochs):
    return [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--model_zoo", os.path.join(REPO, "model_zoo"),
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data", train_dir,
        "--minibatch_size", str(minibatch_size),
        "--records_per_task", str(records_per_task),
        "--num_epochs", str(num_epochs),
        "--num_workers", str(num_workers),
        "--port", str(port),
        "--job_state_dir", state_dir,
        "--job_status_file", status_file,
        "--need_tensorboard", "true",
        "--tensorboard_log_dir", tb_dir,
    ]


def run_drill(
    workdir=None,
    num_files=4,
    records_per_file=48,
    records_per_task=24,
    minibatch_size=16,
    num_epochs=1,
    reconnect_window_secs=120,
    startup_timeout=180,
    finish_timeout=300,
    log=print,
):
    """Execute the kill/restart/verify sequence; returns a result dict
    (raises AssertionError on drill failure). Shared by the CLI and
    tests/test_master_failover.py."""
    from elasticdl_tpu.data import recordio_gen

    workdir = workdir or tempfile.mkdtemp(prefix="master_kill_drill_")
    train_dir = os.path.join(workdir, "train")
    state_dir = os.path.join(workdir, "job_state")
    tb_dir = os.path.join(workdir, "tb")
    status_file = os.path.join(workdir, "job_status.json")
    total_records = num_files * records_per_file
    log("[drill] generating %dx%d TRec records -> %s"
        % (num_files, records_per_file, train_dir))
    recordio_gen.gen_mnist_like(train_dir, num_files=num_files,
                                records_per_file=records_per_file)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # bounded reconnect window the orphan worker must ride out; huge
    # snapshot threshold so the journal keeps every event for the
    # exactly-once audit below
    env["EDL_RPC_RECONNECT_WINDOW_SECS"] = str(reconnect_window_secs)
    env["EDL_RPC_TIMEOUT_SECS"] = "15"
    env["EDL_STATE_SNAPSHOT_EVERY"] = "100000"

    port = free_port()
    journal = os.path.join(state_dir, "journal.jsonl")
    args = (train_dir, state_dir, status_file, tb_dir)
    m2 = None
    m1 = subprocess.Popen(
        master_cmd(port, *args, num_workers=1,
                   records_per_task=records_per_task,
                   minibatch_size=minibatch_size, num_epochs=num_epochs),
        env=env,
    )
    log("[drill] master #1 (pid %d) on :%d, journaling to %s"
        % (m1.pid, port, state_dir))

    try:
        # wait until the worker is mid-job: at least one task dispatched
        # AND one completed (so the kill lands between ranges, proving
        # both replay paths: done stays done, doing gets requeued)
        deadline = time.time() + startup_timeout
        while time.time() < deadline:
            events = read_journal(journal)
            kinds = [e.get("ev") for e in events]
            if kinds.count("dispatch") >= 2 and "done" in kinds:
                break
            if m1.poll() is not None:
                raise AssertionError(
                    "master #1 exited rc=%s before the kill"
                    % m1.returncode)
            time.sleep(0.2)
        else:
            raise AssertionError("worker never got mid-job (journal: %s)"
                                 % kinds)

        worker_pids = find_worker_pids()
        assert worker_pids, "no worker subprocess found"
        log("[drill] worker(s) %s mid-job — SIGKILL master #1"
            % worker_pids)
        os.kill(m1.pid, signal.SIGKILL)
        m1.wait()

        # audit what master #1's lifetime completed, BEFORE the restart
        # compacts the journal
        events1 = read_journal(journal)
        done1 = completed_ranges(events1)
        log("[drill] master #1 journal: %d events, %d ranges done"
            % (len(events1), len(done1)))

        time.sleep(1.0)
        alive = [p for p in worker_pids
                 if os.path.exists("/proc/%d" % p)]
        assert alive, (
            "worker exited during the master outage — the 'UNAVAILABLE "
            "means job done' bug is back")
        log("[drill] workers %s survived the outage (retrying)" % alive)

        # master #2 over the same journal; the orphan worker reconnects,
        # so no fresh worker fleet (--num_workers 0)
        m2 = subprocess.Popen(
            master_cmd(port, *args, num_workers=0,
                       records_per_task=records_per_task,
                       minibatch_size=minibatch_size,
                       num_epochs=num_epochs),
            env=env,
        )
        log("[drill] master #2 (pid %d) restoring from the journal"
            % m2.pid)

        deadline = time.time() + finish_timeout
        while time.time() < deadline:
            if m2.poll() is not None:
                break
            time.sleep(0.5)
        assert m2.poll() is not None, "master #2 did not finish in time"
        assert m2.returncode == 0, (
            "master #2 exited rc=%d" % m2.returncode)

        with open(status_file) as f:
            status = json.load(f)["status"]
        assert status == "Succeeded", "job status %s" % status

        # exactly-once accounting across both master lifetimes
        events2 = read_journal(journal)
        done2 = completed_ranges(events2)
        all_done = sorted(done1 + done2)
        expected = sorted(
            (shard, start, min(start + records_per_task, records))
            for shard, records in (
                (os.path.join(train_dir, name), records_per_file)
                for name in sorted(os.listdir(train_dir))
            )
            for start in range(0, records, records_per_task)
            for _ in range(num_epochs)
        )
        assert all_done == expected, (
            "record-range accounting mismatch:\n got %s\n want %s"
            % (all_done, expected))
        requeued = [e for e in events2 if e.get("ev") == "done_recovered"]
        log("[drill] exactly-once holds over %d ranges (%d records), "
            "%d reconciled from pre-crash doing"
            % (len(all_done), total_records, len(requeued)))

        # the recovery gauges must be visible in the TensorBoard stream
        tags = ["master/restarts", "master/recovery_requeued_tasks",
                "fault/rpc_retries"]
        assert tb_stream_contains(tb_dir, tags), (
            "recovery gauges missing from the TensorBoard stream: %s"
            % tags)
        log("[drill] recovery gauges present in TB stream: %s" % tags)

        deadline = time.time() + 60
        while time.time() < deadline and any(
            os.path.exists("/proc/%d" % p) for p in alive
        ):
            time.sleep(0.5)
        log("[drill] worker(s) exited after JOB_COMPLETE")
        return {
            "ranges": len(all_done),
            "requeued_reconciled": len(requeued),
            "worker_pids": worker_pids,
        }
    finally:
        for proc in (m1, m2):
            if proc is not None and proc.poll() is None:
                proc.kill()
        for pid in find_worker_pids():
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def main():
    res = run_drill(num_epochs=2)
    print("[drill] master-kill recovery drill PASSED: %s" % res)
    return 0


if __name__ == "__main__":
    sys.exit(main())
