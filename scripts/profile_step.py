"""Capture a jax.profiler trace of the flagship train step and print
the top device-side ops — the tool behind the round-2 finding that
attention consumed ~44% of the step at ~11% of the FLOPs.

Usage (on TPU):
    python scripts/profile_step.py [trace_dir]
Prints a per-op duration summary from the Chrome trace; the full
xplane/trace files stay in trace_dir for TensorBoard's profile plugin.
"""

import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def capture(trace_dir):
    import jax
    import numpy as np

    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.common.timing_utils import fetch_sync
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    cfg = dict(vocab_size=32000, seq_len=1024, embed_dim=1024,
               num_heads=8, num_layers=8, dtype="bf16")
    bsz = 32
    trainer = Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh_lib.build_mesh(),
        model_params=format_params_str(cfg),
    )
    rng = np.random.RandomState(0)
    tok = rng.randint(0, 32000, size=(bsz, 1025)).astype(np.int32)
    batch = ({"tokens": tok[:, :-1]}, tok[:, 1:])
    state = trainer.init_state(batch)
    batch = jax.device_put(batch, mesh_lib.batch_sharding(trainer.mesh))
    for _ in range(3):
        state, _ = trainer.train_step(state, batch)
    fetch_sync(state.params)
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            state, _ = trainer.train_step(state, batch)
        fetch_sync(state.params)


def summarize(trace_dir, top=30):
    paths = glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")
    )
    if not paths:
        print("no trace found under", trace_dir)
        return
    with gzip.open(sorted(paths)[-1]) as f:
        events = json.load(f).get("traceEvents", [])
    durs = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("dur"):
            durs[e.get("name", "")] += e["dur"]
    print("top device/host ops by total duration (3 steps):")
    for name, d in durs.most_common(top):
        print("%10.2f ms  %s" % (d / 1000.0, name[:100]))


def main():
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/edl-trace"
    import bench as bench_mod

    bench_mod.require_accelerator_or_exit()
    capture(trace_dir)
    summarize(trace_dir)


if __name__ == "__main__":
    main()
