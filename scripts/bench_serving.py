#!/usr/bin/env python
"""Closed-loop serving load generator: the serving-throughput entry in
the bench trajectory (BENCH_* record family).

Runs the real stack in one process — GenerationServer (continuous-
batching engine + gRPC transport) and a Poisson open-loop arrival
process of streaming clients with mixed prompt/output lengths — and
emits ONE JSON line:

    {"metric": "serving_goodput_tokens_per_sec", "value": ...,
     "ttft_ms": {"p50": ..., "p99": ...}, "latency_ms": {...},
     "tokens_per_sec": ..., "goodput_rps": ..., "rejected": ...,
     "expired": ..., "kv": {...}, ...}

* TTFT is measured at the FIRST streamed chunk (prefill + queueing);
  all percentiles run through the shared log-linear histogram code
  (elasticdl_tpu/observability/histogram.py) — the same definition
  the live ServerStatus/router_status percentile fields report, whose
  server-side view of the run is echoed under "server_ttft_ms" /
  "server_queue_wait_ms";
* tokens_per_sec counts only tokens of COMPLETED requests over the
  measurement wall; goodput_rps is completed requests per second —
  rejected (backpressure) and expired (deadline) requests score zero,
  which is what makes overload visible as a goodput plateau;
* arrivals are open-loop Poisson (exponential gaps at --rate), so
  backpressure actually engages instead of the clients self-throttling;
* the "kv" block records the memory-efficiency trajectory: bytes
  resident in the pool at peak, average KV bytes per generated token,
  block budget and admitted-vs-rejected under it.

--compare_paged runs the SAME arrival plan several ways — the dense
pool, the block-paged pool (serving/kv_pool.py) with prefix sharing
OFF, the paged pool with prefix sharing ON (plus speculative decode
when --draft_k > 0), and with --kv_cache_dtype int8 an INT8-ARENA leg
(quantized block storage, deferred dequantize in the paged scan) —
all holding the SAME total KV bytes (the int8 leg pays its budget in
~2-3x as many smaller blocks) — and nests the records plus headline
ratios under "paged" / "paged_shared" / "paged_shared_spec" /
"paged_int8" / "paged_vs_dense" / "shared_vs_paged" /
"spec_vs_shared" / "int8_vs_shared" (the last with a greedy-match
rate against the int8 DENSE oracle). That A/B is the
`make serve-smoke` shape: equal HBM, more admissible concurrency,
deduped prefixes converting into admitted slots, and quantized
arenas compounding on top.

--shared_prefix switches the workload to the system-prompt shape the
sharing is FOR: every prompt = one of --prefix_pool common prefixes of
--prefix_len tokens + a random --suffix_len suffix. --draft_k k seats
a draft model (--draft_params; default = the target's params, i.e.
self-draft — the acceptance ceiling) and verifies k drafted tokens
per tick. --shared_prefix also runs the ROUTER-tier prefix-affinity
A/B ("affinity_ab"): the same shape through a real two-replica fleet
behind the Router, fingerprint-affine dispatch ON vs OFF — fleet
re-paid prefix prefill tokens and warm TTFT percentiles.

Defaults are CPU-smoke sized; on hardware raise --requests/--rate and
the model dims.

Usage:
    python scripts/bench_serving.py --requests 32 --rate 16 \
        --num_slots 4 --compare_paged --out BENCH_SERVING.json
"""

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=16.0,
                   help="mean arrival rate, requests/sec (Poisson)")
    p.add_argument("--ramp", default="",
                   help="piecewise-Poisson load profile r1:t1,r2:t2,"
                        "... (rate req/s : duration secs per phase); "
                        "overrides --rate/--requests and records "
                        "per-phase percentiles — the SAME generator "
                        "the autoscale drill ramps with")
    p.add_argument("--num_slots", type=int, default=4)
    p.add_argument("--queue_capacity", type=int, default=16)
    p.add_argument("--prompt_len", default="2:6",
                   help="min:max prompt tokens (uniform)")
    p.add_argument("--out_len", default="4:12",
                   help="min:max generated tokens (uniform)")
    p.add_argument("--deadline_ms", type=int, default=0,
                   help="per-request deadline budget; 0 = none")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--model_params", default=(
        "vocab_size=32; seq_len=32; embed_dim=32; num_heads=2; "
        "num_layers=1"
    ))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="",
                   help="also write the JSON record to this path")
    # KV pool layout (serving/kv_pool.py)
    p.add_argument("--kv_paged", type=int, default=0,
                   help="1 = serve from the block-paged KV pool")
    p.add_argument("--kv_block_size", type=int, default=4)
    p.add_argument("--kv_num_blocks", type=int, default=0,
                   help="block budget; 0 = dense-equivalent bytes for "
                        "--num_slots")
    p.add_argument("--paged_slots", type=int, default=0,
                   help="slot count for the paged side of "
                        "--compare_paged; 0 = 2x --num_slots")
    p.add_argument("--compare_paged", action="store_true",
                   help="A/B the dense pool vs the paged pool (shared "
                        "off AND on) at EQUAL total KV bytes; nests "
                        "the paged/paged_shared records")
    p.add_argument("--kv_shared", type=int, default=1,
                   help="1 = refcounted prefix sharing in the paged "
                        "pool (single-run mode; --compare_paged runs "
                        "both)")
    # shared-prefix workload: common system prompts + random suffixes
    p.add_argument("--shared_prefix", action="store_true",
                   help="draw prompts as <common prefix> + <random "
                        "suffix> instead of fully random")
    p.add_argument("--prefix_len", type=int, default=16,
                   help="tokens in each common system prompt")
    p.add_argument("--prefix_pool", type=int, default=2,
                   help="distinct system prompts in the pool")
    p.add_argument("--suffix_len", default="1:4",
                   help="min:max per-request suffix tokens (uniform)")
    # speculative decode (paged+shared leg / single paged run)
    p.add_argument("--draft_k", type=int, default=0,
                   help="draft tokens per tick; 0 = speculative "
                        "decode off")
    p.add_argument("--draft_params", default="",
                   help="draft model_params; empty = the target's "
                        "(self-draft: the acceptance ceiling)")
    # int8 KV arenas (model kv_cache_dtype): single-run mode serves
    # the whole run quantized; with --compare_paged this adds an
    # int8-arena leg at EQUAL KV BYTES (more blocks, not fewer bytes)
    # plus an int8_vs_shared ratio block with a greedy-match rate
    # against the int8 DENSE oracle (offline decode on the same
    # quantized model)
    p.add_argument("--kv_cache_dtype", default="",
                   choices=("", "int8"))
    # per-step decode profiler (serving/engine.py StepProfiler): the
    # run records each phase's p50/p99/count under "profile" —
    # prefill / suffix_tile / decode / draft / verify_commit /
    # scatter / revive_upload / reload_swap
    p.add_argument("--profile", action="store_true")
    # metrics+profiler overhead A/B: run the paged+shared leg twice —
    # plane OFF (no profiler, no /metrics server) vs ON (profiler +
    # live exposition being scraped is the serve path under test) —
    # and assert the ON leg's tokens/sec within OVERHEAD_BOUND of OFF
    p.add_argument("--overhead_ab", action="store_true")
    # tiered host spill (serving/kv_pool.py): host-tier capacity in
    # BLOCKS (converted to bytes at the serving rig's exact
    # block_bytes). Single-run mode arms the tier directly; with
    # --compare_paged AND --shared_prefix it also runs the
    # EVICTION-PRESSURE A/B: the same shared-prefix plan over a
    # device pool deliberately sized below the prefix working set,
    # once with the host tier off (every evicted chain re-pays
    # prefill) and once on (evicted chains revive by upload), at
    # equal DEVICE KV bytes — the "host_vs_evict" ratio block
    p.add_argument("--kv_host_blocks", type=int, default=0)
    # the disaggregation A/B (serving/disagg.py): the same open-loop
    # plan of long COLD prompts through a real two-replica in-process
    # fleet behind the Router, three ways at EQUAL FLEET KV BYTES —
    # monolithic prefill, chunked prefill, and chunked + phase-split
    # (dedicated prefill replica handing chains to the decode replica
    # over TransferChain) — each leg with its own slowest-TTFT-decile
    # cause breakdown (the "disagg_ab" record block)
    p.add_argument("--disagg", action="store_true")
    return p.parse_args(argv)


def _span(text):
    lo, _, hi = text.partition(":")
    lo, hi = int(lo), int(hi or lo)
    if not 1 <= lo <= hi:
        raise ValueError("bad span %r" % text)
    return lo, hi


def parse_ramp(spec):
    """'r1:t1,r2:t2,...' -> [(rate_rps, duration_secs), ...]. The one
    ramp grammar the bench and scripts/run_autoscale_drill.py share —
    one load generator, so a drill phase and a bench phase mean the
    same arrival process."""
    phases = []
    for part in spec.split(","):
        rate_text, _, secs_text = part.strip().partition(":")
        rate, secs = float(rate_text), float(secs_text)
        if rate <= 0 or secs <= 0:
            raise ValueError("bad ramp phase %r in %r" % (part, spec))
        phases.append((rate, secs))
    if not phases:
        raise ValueError("empty ramp spec %r" % spec)
    return phases


def ramp_arrivals(phases, rs):
    """Open-loop piecewise-Poisson arrival plan: [(offset_secs,
    phase_index), ...] with exponential gaps at each phase's rate,
    phase boundaries at the cumulative durations."""
    out = []
    t0 = 0.0
    for idx, (rate, secs) in enumerate(phases):
        t = t0 + float(rs.exponential(1.0 / rate))
        while t < t0 + secs:
            out.append((t, idx))
            t += float(rs.exponential(1.0 / rate))
        t0 += secs
    return out


# percentiles go through the SAME log-linear histogram code the live
# telemetry and the status RPCs use (observability/histogram.py), so a
# bench p99 and a ServerStatus p99 are definitionally the same number
# — not a sorted-list math that drifts from the serving-side buckets
from elasticdl_tpu.observability.histogram import percentiles  # noqa: E402


def build_rig(args, model_params=None):
    """The trainer/state every A/B side shares (same params -> the
    dense and paged runs serve identical token streams), plus the
    draft rig when --draft_k asks for speculative decode.
    `model_params` overrides args.model_params (the int8-arena leg
    builds a second rig with kv_cache_dtype='int8' — the knob changes
    only the cache buffers, so the same seed yields the same
    weights)."""
    import jax
    import numpy as np

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])

    def one(params):
        trainer = Trainer(
            load_model_spec_from_module(zoo), mesh=mesh,
            model_params=params,
        )
        seq_len = int(trainer.model.seq_len)
        dummy = np.zeros((1, seq_len), np.int32)
        return trainer, trainer.init_state(({"tokens": dummy}, dummy))

    trainer, state = one(model_params or args.model_params)
    draft = None
    if args.draft_k > 0:
        draft = one(args.draft_params or args.model_params)
    return trainer, state, draft


def block_bytes_for(trainer, block_size):
    """Per-block arena bytes for this model's KV row leaves at their
    OWN dtypes — the same sum PagedKVPool computes, so the equal-byte
    block budgets below are exact (int8 rows + f32 scale leaves, not a
    homogeneous-dtype guess)."""
    import jax
    import numpy as np

    from elasticdl_tpu.api.generation import (
        _decode_cache,
        _kv_shapes_for,
        kv_row_leaf,
    )

    seq_len = int(trainer.model.seq_len)
    kv_shapes = _kv_shapes_for(
        _decode_cache(trainer), trainer.model, 1
    )
    return int(sum(
        np.dtype(leaf.dtype).itemsize * block_size
        * leaf.shape[1] * leaf.shape[3]
        for leaf in jax.tree.leaves(kv_shapes)
        if kv_row_leaf(leaf, seq_len)
    ))


def build_plan(args, seq_len, vocab):
    import numpy as np

    o_lo, o_hi = _span(args.out_len)
    rs = np.random.RandomState(args.seed)
    if args.shared_prefix:
        # the system-prompt workload: every request = one of a small
        # pool of common prefixes + a short random suffix — what the
        # refcounted prefix index dedupes to one resident chain
        s_lo, s_hi = _span(args.suffix_len)
        if args.prefix_len + s_hi + o_hi > seq_len:
            raise SystemExit(
                "prefix_len %d + suffix max %d + out max %d exceeds "
                "seq_len %d"
                % (args.prefix_len, s_hi, o_hi, seq_len)
            )
        pool = [
            rs.randint(0, vocab, size=args.prefix_len)
            for _ in range(max(1, args.prefix_pool))
        ]

        def prompt(i):
            suffix = rs.randint(0, vocab,
                                size=rs.randint(s_lo, s_hi + 1))
            return np.concatenate([pool[i % len(pool)], suffix])
    else:
        p_lo, p_hi = _span(args.prompt_len)
        if p_hi + o_hi > seq_len:
            raise SystemExit(
                "prompt_len max %d + out_len max %d exceeds seq_len %d"
                % (p_hi, o_hi, seq_len)
            )

        def prompt(i):
            return rs.randint(0, vocab,
                              size=rs.randint(p_lo, p_hi + 1))

    if args.ramp:
        # piecewise-Poisson ramp: the arrival schedule fixes both the
        # request count and each request's phase tag
        arrivals = ramp_arrivals(parse_ramp(args.ramp), rs)
        gaps = [
            at - (arrivals[i - 1][0] if i else 0.0)
            for i, (at, _phase) in enumerate(arrivals)
        ]
        return [
            {
                "prompt": prompt(i),
                "new": int(rs.randint(o_lo, o_hi + 1)),
                "gap": float(gaps[i]),
                "seed": int(i),
                "phase": int(arrivals[i][1]),
            }
            for i in range(len(arrivals))
        ]
    return [
        {
            "prompt": prompt(i),
            "new": int(rs.randint(o_lo, o_hi + 1)),
            "gap": float(rs.exponential(1.0 / args.rate)),
            "seed": int(i),
            "phase": None,
        }
        for i in range(args.requests)
    ]


def run_load(args, trainer, state, plan, num_slots, kv_paged,
             kv_block_size, kv_num_blocks, kv_shared=False,
             draft=None, draft_k=0, kv_host_bytes=0, profile=False,
             metrics_port=None, forensics=True, runtime_health=True):
    import jax

    from elasticdl_tpu.observability.tracing import new_trace_id
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel
    from elasticdl_tpu.serving import GenerationServer, ServingConfig

    server = GenerationServer(
        trainer, state,
        ServingConfig(
            num_slots=num_slots,
            queue_capacity=args.queue_capacity,
            kv_paged=kv_paged,
            kv_block_size=kv_block_size,
            kv_num_blocks=kv_num_blocks,
            kv_shared=kv_shared,
            draft_k=draft_k if draft is not None else 0,
            kv_host_bytes=kv_host_bytes,
            profile=profile,
            metrics_port=metrics_port,
            forensics=forensics,
            runtime_health=runtime_health,
        ),
        draft=draft,
    ).start()
    stub = ServingStub(build_channel("localhost:%d" % server.port))

    # one warmup request outside the measurement: pays the jit compiles
    stub.generate(
        pb.GenerateRequest(prompt=[1, 2], max_new_tokens=2), timeout=300
    )
    # the runtime-health steady boundary: every compile from here on
    # of an ALREADY-COMPILED executable is a counted anomaly — the
    # "churn never recompiles" invariant this bench asserts at zero.
    # (First compiles of new bucket names mid-run are the cold path
    # working as designed and stay legal.)
    server.mark_steady()

    results = []
    lock = threading.Lock()

    def one(spec):
        t0 = time.monotonic()
        # mint the trace client-side (the server adopts inbound trace
        # context), so the bench can join its own latency rows back to
        # the in-process span trees — the --ramp tail_report path
        trace_id = new_trace_id()
        row = {"status": "OK", "tokens": 0, "ttft_ms": None,
               "phase": spec.get("phase"), "spec": spec,
               "out_tokens": [], "trace_id": trace_id}
        try:
            stream = stub.generate_stream(
                pb.GenerateRequest(
                    prompt=[int(t) for t in spec["prompt"]],
                    max_new_tokens=spec["new"],
                    temperature=args.temperature,
                    seed=spec["seed"],
                    deadline_ms=args.deadline_ms,
                    trace_id=trace_id,
                ),
                timeout=300,
            )
            for chunk in stream:
                if row["ttft_ms"] is None and chunk.tokens:
                    row["ttft_ms"] = (time.monotonic() - t0) * 1000.0
                row["tokens"] += len(chunk.tokens)
                row["out_tokens"].extend(int(t) for t in chunk.tokens)
        except Exception as e:  # noqa: BLE001 - status is the datum
            code = getattr(e, "code", None)
            row["status"] = (
                code().name if callable(code) else type(e).__name__
            )
        row["latency_ms"] = (time.monotonic() - t0) * 1000.0
        with lock:
            results.append(row)

    threads = []
    bench_t0 = time.monotonic()
    for spec in plan:
        time.sleep(spec["gap"])
        t = threading.Thread(target=one, args=(spec,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=600)
    wall = time.monotonic() - bench_t0

    status = stub.server_status(pb.ServerStatusRequest(), timeout=30)
    health_snap = (server.health.snapshot()
                   if server.health is not None else None)
    profile_snap = None
    if profile and server.engine.profiler is not None:
        profile_snap = server.engine.profiler.snapshot()
    scrape = None
    if server.metrics is not None:
        # one real scrape through the stdlib HTTP server, validated by
        # the INDEPENDENT parser — the exposition is part of the path
        # under test, not a decoration
        import urllib.request

        from elasticdl_tpu.observability.promparse import (
            parse_prometheus_text,
        )

        text = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % server.metrics.port,
            timeout=10,
        ).read().decode("utf-8")
        fams = parse_prometheus_text(text)
        scrape = {
            "families": len(fams),
            "samples": sum(len(f["samples"]) for f in fams.values()),
        }
    server.stop()

    ok = [r for r in results if r["status"] == "OK"]
    ttfts = [r["ttft_ms"] for r in ok if r["ttft_ms"] is not None]
    lats = [r["latency_ms"] for r in ok]
    tokens_ok = sum(r["tokens"] for r in ok)
    record = {
        "metric": "serving_goodput_tokens_per_sec",
        "value": round(tokens_ok / wall, 3) if wall else None,
        "unit": "tokens/sec",
        "platform": jax.default_backend(),
        "requests": len(plan),
        "rate_rps": args.rate,
        "ramp": args.ramp or None,
        "num_slots": num_slots,
        "queue_capacity": args.queue_capacity,
        "completed": len(ok),
        "rejected": sum(
            1 for r in results if r["status"] == "RESOURCE_EXHAUSTED"
        ),
        "expired": sum(
            1 for r in results if r["status"] == "DEADLINE_EXCEEDED"
        ),
        "goodput_rps": round(len(ok) / wall, 3) if wall else None,
        "tokens_per_sec": round(tokens_ok / wall, 3) if wall else None,
        "ttft_ms": percentiles(ttfts, (50, 90, 99)),
        "latency_ms": percentiles(lats, (50, 90, 99)),
        # the server's own histogram view of the same run (ServerStatus
        # percentile fields) — same bucket scheme as the client-side
        # numbers above
        "server_ttft_ms": {
            "p50": round(status.ttft_p50_ms, 3),
            "p90": round(status.ttft_p90_ms, 3),
            "p99": round(status.ttft_p99_ms, 3),
        },
        "server_queue_wait_ms": {
            "p50": round(status.queue_wait_p50_ms, 3),
            "p90": round(status.queue_wait_p90_ms, 3),
            "p99": round(status.queue_wait_p99_ms, 3),
        },
        "wall_secs": round(wall, 3),
        "max_active_slots": status.max_active_slots,
        "server_tokens_generated": status.tokens_generated,
        # memory-efficiency fields: the paged-vs-dense trajectory
        "kv": {
            "paged": bool(status.kv_paged),
            "shared": bool(status.kv_shared),
            "cache_dtype": status.kv_cache_dtype,
            "block_size": status.kv_block_size,
            "blocks_total": status.kv_blocks_total,
            "bytes_total": status.kv_bytes_total,
            "bytes_in_use_peak": status.kv_bytes_in_use_peak,
            "bytes_per_token": round(status.kv_bytes_per_token, 1),
            "admitted": status.admitted,
            "rejected": status.rejected,
            "prefix_hit_tokens": status.prefix_hit_tokens,
            "cow_copies": status.cow_copies,
            # tiered host spill (zeros with the tier off)
            "host_blocks": status.kv_host_blocks,
            "host_bytes": status.kv_host_bytes,
            "revive_uploads": status.revive_uploads,
            "prefill_tokens_revived": status.prefill_tokens_revived,
            "host_drops": status.host_drops,
            # windowed warm-capacity signal (time-series ring)
            "prefix_hit_rate_window": round(
                status.prefix_hit_rate_window, 4
            ),
        },
        # speculative-decode economy (zeros when --draft_k is off)
        "draft": {
            "k": status.draft_k,
            "proposed": status.draft_proposed,
            "accepted": status.draft_accepted,
            "accept_rate": round(
                status.draft_accepted / status.draft_proposed, 3
            ) if status.draft_proposed else 0.0,
        },
    }
    if health_snap is not None:
        # the runtime health plane's own verdict on the run: total
        # compiles, post-boundary recompiles (must be 0 — main()
        # gates on it), the watchdog state and the accountant's peak
        # unaccounted drift
        record["health"] = {
            "jit_compiles": health_snap["jit_compiles"],
            "recompiles": health_snap["recompiles"],
            "steady_recompiles": health_snap["steady_recompiles"],
            "health_state": health_snap["health_state"],
            "stalls": health_snap["stalls"],
            "memory_unaccounted_bytes":
                health_snap["memory_unaccounted_bytes"],
        }
    if profile_snap is not None:
        # the per-step decode profiler breakdown: p50/p99/count per
        # phase (serving/engine.py StepProfiler.snapshot shape)
        record["profile"] = profile_snap
    if scrape is not None:
        record["metrics_scrape"] = scrape
    if args.ramp:
        # per-phase percentiles: one entry per ramp phase, same
        # histogram code as everything else — the autoscale drill's
        # per-transition SLO reads exactly this shape
        record["phases"] = []
        for idx, (rate, secs) in enumerate(parse_ramp(args.ramp)):
            rows = [r for r in results if r["phase"] == idx]
            rows_ok = [r for r in rows if r["status"] == "OK"]
            record["phases"].append({
                "phase": idx,
                "rate_rps": rate,
                "secs": secs,
                "requests": len(rows),
                "completed": len(rows_ok),
                "rejected": sum(1 for r in rows
                                if r["status"] == "RESOURCE_EXHAUSTED"),
                "expired": sum(1 for r in rows
                               if r["status"] == "DEADLINE_EXCEEDED"),
                "ttft_ms": percentiles(
                    [r["ttft_ms"] for r in rows_ok
                     if r["ttft_ms"] is not None], (50, 90, 99)
                ),
                "latency_ms": percentiles(
                    [r["latency_ms"] for r in rows_ok], (50, 90, 99)
                ),
            })
    return record, results


def tail_report(results, phases):
    """Forensics over the RAMP's slowest requests: per phase, take the
    slowest TTFT decile of completed requests, pull their span trees
    from the in-process recorder, run forensics.attribute() on each,
    and histogram the dominant causes. The output is the quantified
    tail-latency evidence the disaggregated-prefill ROADMAP item asks
    for BEFORE scheduling work starts: "N% of the p99 TTFT tail is
    prefill monopolization" is a number here, not a hunch."""
    from elasticdl_tpu.observability import forensics
    from elasticdl_tpu.observability.tracing import (
        group_by_trace,
        recorder,
    )

    by_trace = group_by_trace(
        [s.to_dict() for s in recorder().snapshot()]
    )
    per_phase = []
    all_verdicts = []
    agg_ms = {c: 0.0 for c in forensics.CAUSES}
    for idx in range(len(phases)):
        rows = [
            r for r in results
            if r["phase"] == idx and r["status"] == "OK"
            and r["ttft_ms"] is not None and r["trace_id"] in by_trace
        ]
        rows.sort(key=lambda r: r["ttft_ms"], reverse=True)
        decile = rows[:max(1, len(rows) // 10)] if rows else []
        verdicts = [
            forensics.attribute(by_trace[r["trace_id"]])
            for r in decile
        ]
        for v in verdicts:
            for part in v["breakdown"]:
                agg_ms[part["cause"]] += part["ms"]
        all_verdicts.extend(verdicts)
        per_phase.append({
            "phase": idx,
            "rate_rps": phases[idx][0],
            "analyzed": len(verdicts),
            "dominant_causes": forensics.cause_histogram(verdicts),
        })
    total = forensics.cause_histogram(all_verdicts)
    total_ms = sum(agg_ms.values()) or 1e-9
    return {
        "decile": "slowest 10% by TTFT, per phase, completed only",
        "analyzed": len(all_verdicts),
        "per_phase": per_phase,
        "dominant_causes": total,
        "top_cause": max(total, key=total.get) if total else None,
        # aggregate wall-ms breakdown over the analyzed tail — the
        # shares the scheduler items cite (e.g. what fraction of the
        # tail is prefill_blocked_by_other)
        "breakdown_ms": {c: round(agg_ms[c], 3)
                         for c in forensics.CAUSES},
        "breakdown_share": {c: round(agg_ms[c] / total_ms, 4)
                            for c in forensics.CAUSES},
        "evidence_complete": all(
            v["evidence_complete"] for v in all_verdicts
        ) if all_verdicts else False,
    }


def greedy_match_rate(trainer, state, results, temperature):
    """Fraction of completed GREEDY streams whose tokens equal the
    offline `autoregressive_generate(use_cache=True)` oracle on
    `trainer` — for the int8 leg that oracle is the int8 DENSE decode
    (same quantizer), so a miss means the paged deferred scan diverged,
    not that quantization rounded differently."""
    import numpy as np

    from elasticdl_tpu.api.generation import autoregressive_generate

    if temperature > 0.0:
        return None  # sampled runs have no greedy oracle
    compared = matched = 0
    for row in results:
        if row["status"] != "OK" or not row["out_tokens"]:
            continue
        spec = row["spec"]
        off = np.asarray(autoregressive_generate(
            trainer, state,
            np.asarray([spec["prompt"]], np.int32), spec["new"],
            use_cache=True,
        ))[0]
        compared += 1
        if list(off[len(spec["prompt"]):]) == row["out_tokens"]:
            matched += 1
    return round(matched / compared, 4) if compared else None


#: the eviction-pressure A/B's own serving rig: long system prompts
#: over a real-ish context, so a re-paid prefill is real compute (the
#: tiny smoke model's 32-token prefill costs ~2 ms — cheaper than any
#: measurement overhead, so TTFT could not see the difference). At
#: this scale a full re-prefill seat measures ~29 ms vs ~13 ms for a
#: revive-by-upload seat on the CPU rig.
PRESS_MODEL_PARAMS = (
    "vocab_size=32; seq_len=256; embed_dim=256; num_heads=4; "
    "num_layers=4"
)
PRESS_PREFIX_LEN = 224
PRESS_BLOCK_SIZE = 16


def run_host_evict_ab(args):
    """The tiered-KV eviction-pressure A/B: a shared-prefix workload
    whose prefix WORKING SET deliberately exceeds the device pool, so
    reclaimable chains are forced out between hits — run twice at
    EQUAL DEVICE KV BYTES, host tier off (every evicted chain re-pays
    its prefill on the next hit) vs on (evicted chains spill and
    revive by upload). The headline ratio: what fraction of the
    prefill tokens the baseline re-pays after eviction does the host
    tier recover (`prefill_tokens_revived` vs the baseline's
    repeated-prefix re-prefill tokens)? Runs its own rig
    (PRESS_MODEL_PARAMS, int8 arenas when --kv_cache_dtype says so)
    with 96-token system prompts: long enough that a re-paid prefill
    costs real compute, which is what the TTFT comparison measures."""
    import numpy as np

    model_params = PRESS_MODEL_PARAMS
    if args.kv_cache_dtype:
        model_params += "; kv_cache_dtype=%r" % args.kv_cache_dtype
    trainer, state, _ = build_rig(args, model_params=model_params)
    vocab = int(trainer.model.vocab_size)
    bs = PRESS_BLOCK_SIZE
    o_lo, o_hi = _span(args.out_len)
    s_lo, s_hi = _span(args.suffix_len)
    prefix_len = (PRESS_PREFIX_LEN // bs) * bs  # full blocks only
    press_pool = 6   # distinct system prompts in the pressure pool
    passes = 4       # times each prompt comes back around
    # a seat's full commitment, in blocks — the device pool holds two
    # concurrent seats and nothing more, far below the working set
    seat_blocks = -(-(prefix_len + s_hi + o_hi - 1) // bs)
    device_blocks = 2 * seat_blocks
    working_set = press_pool * (prefix_len // bs)
    if working_set <= device_blocks:
        raise SystemExit(
            "eviction-pressure A/B needs the prefix working set "
            "(%d blocks) above the device pool (%d)"
            % (working_set, device_blocks)
        )
    host_blocks = working_set  # the tier holds the whole working set
    host_bytes = host_blocks * block_bytes_for(trainer, bs)
    rs = np.random.RandomState(args.seed + 17)
    pool = [rs.randint(0, vocab, size=prefix_len)
            for _ in range(press_pool)]
    # arrivals slow enough that TTFT is seat latency (prefill vs
    # revive), not queueing — the quantity under test
    rate = 1.5
    plan = []
    for i in range(passes * press_pool):
        # round-robin: consecutive hits of one prefix are press_pool
        # requests apart, so the tight pool has evicted it in between
        suffix = rs.randint(0, vocab,
                            size=rs.randint(s_lo, s_hi + 1))
        plan.append({
            "prompt": np.concatenate([pool[i % press_pool], suffix]),
            "new": int(rs.randint(o_lo, o_hi + 1)),
            "gap": float(rs.exponential(1.0 / rate)),
            "seed": int(i),
            "phase": None,
        })
    legs, rows = {}, {}
    for name, bytes_budget in (("baseline", 0), ("host", host_bytes)):
        legs[name], rows[name] = run_load(
            args, trainer, state, plan, 2,
            kv_paged=True,
            kv_block_size=bs,
            kv_num_blocks=device_blocks,
            kv_shared=True,
            kv_host_bytes=bytes_budget,
        )

    def post_evict_ttft(leg_rows):
        """TTFT percentiles over the STEADY post-eviction hits: the
        last two passes, by which point every compile (either leg's)
        is paid and every seat of a pooled prompt finds its chain
        evicted — re-prefilled by the baseline, revived by the host
        tier. Same histogram code as every other percentile."""
        steady = [
            r["ttft_ms"] for r in leg_rows
            if r["status"] == "OK" and r["ttft_ms"] is not None
            and r["spec"]["seed"] >= 2 * press_pool
        ]
        return percentiles(steady, (50, 90, 99))

    base, host = legs["baseline"], legs["host"]
    base_steady = post_evict_ttft(rows["baseline"]) or {}
    host_steady = post_evict_ttft(rows["host"]) or {}
    offered = len(plan) * prefix_len   # full-block prefix tokens sent
    cold = press_pool * prefix_len     # first-touch: unavoidable
    repaid_base = max(
        0, offered - base["kv"]["prefix_hit_tokens"] - cold
    )
    recovered = host["kv"]["prefill_tokens_revived"]
    return {
        "model_params": model_params,
        "block_size": bs,
        "device_blocks": device_blocks,
        "host_blocks": host_blocks,
        "prefix_pool": press_pool,
        "passes": passes,
        "prefix_working_set_blocks": working_set,
        "equal_device_kv_bytes": (
            base["kv"]["bytes_total"] == host["kv"]["bytes_total"]
        ),
        "prefix_tokens_offered": offered,
        "cold_prefix_tokens": cold,
        "baseline_repaid_prefix_tokens": repaid_base,
        "prefill_tokens_revived": recovered,
        "recovered_ratio": round(recovered / max(1, repaid_base), 3),
        "revive_uploads": host["kv"]["revive_uploads"],
        "host_drops": host["kv"]["host_drops"],
        "prefix_hit_tokens": [base["kv"]["prefix_hit_tokens"],
                              host["kv"]["prefix_hit_tokens"]],
        # steady-state post-eviction TTFT: the headline the tier buys
        "post_evict_ttft_ms": [base_steady, host_steady],
        "ttft_p50_improved": (
            (host_steady.get("p50") or 0.0)
            < (base_steady.get("p50") or 0.0)
        ),
        "ttft_p99_improved": (
            (host_steady.get("p99") or 0.0)
            < (base_steady.get("p99") or 0.0)
        ),
        "goodput_rps": [base["goodput_rps"], host["goodput_rps"]],
        "goodput_ratio": round(
            (host["goodput_rps"] or 0.0)
            / (base["goodput_rps"] or 1e-9), 3,
        ),
        "baseline": base,
        "host": host,
    }


def run_affinity_ab(args):
    """The prefix-affinity A/B at the ROUTER tier: the same
    shared-prefix Poisson plan dispatched through a real two-replica
    in-process fleet behind the real Router, affinity ON vs OFF.

    The off-leg's pathology is structural, not statistical: with
    load scores tied, the least-loaded order tie-breaks on free
    blocks, and the replica that just cached a family's prefix chain
    has FEWER free blocks — so consecutive hits of one family
    ping-pong between replicas and each bounce re-pays the family's
    prefill cold. The on-leg pins each family to the replica already
    holding its chain (the fingerprint ladder), so the fleet pays
    each family's prefill once. The headline: fleet re-paid prefix
    prefill tokens (offered minus hits minus the one unavoidable
    first touch per family) and the warm-pass TTFT percentiles."""
    import numpy as np

    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel
    from elasticdl_tpu.serving import GenerationServer, ServingConfig
    from elasticdl_tpu.serving.router import (
        Router,
        RouterConfig,
        RouterError,
    )

    trainer, state, _ = build_rig(args, model_params=PRESS_MODEL_PARAMS)
    vocab = int(trainer.model.vocab_size)
    bs = PRESS_BLOCK_SIZE
    o_lo, o_hi = _span(args.out_len)
    s_lo, s_hi = _span(args.suffix_len)
    prefix_len = (PRESS_PREFIX_LEN // bs) * bs  # full blocks only
    families = 4  # distinct system prompts
    passes = 6    # times each family comes back around
    # arrivals BELOW fleet capacity: with slots idle, load scores sit
    # near zero and the affinity_load_margin can hold — the A/B
    # measures placement, not saturation (under which the ladder's
    # load rung decays affinity to least-loaded, by design)
    rate = 1.0
    # roomy per-replica pools: every family's chain fits on BOTH
    # replicas plus full seats — zero eviction pressure, so the A/B
    # isolates WHERE a family lands, not whether its chain survives
    seat_blocks = -(-(prefix_len + s_hi + o_hi - 1) // bs)
    # +1 family of room for the full-shape warmup chain each replica
    # seats outside the measurement window
    num_blocks = ((families + 1) * (prefix_len // bs)
                  + 2 * seat_blocks + 8)
    rs = np.random.RandomState(args.seed + 29)
    pool = [rs.randint(0, vocab, size=prefix_len)
            for _ in range(families)]
    plan = []
    for i in range(passes * families):
        suffix = rs.randint(0, vocab,
                            size=rs.randint(s_lo, s_hi + 1))
        plan.append({
            "prompt": np.concatenate([pool[i % families], suffix]),
            "new": int(rs.randint(o_lo, o_hi + 1)),
            "gap": float(rs.exponential(1.0 / rate)),
            "seed": int(i),
        })

    def run_leg(affinity_on):
        servers, router = [], None
        try:
            for _ in range(2):
                srv = GenerationServer(
                    trainer, state,
                    ServingConfig(
                        num_slots=2,
                        queue_capacity=args.queue_capacity,
                        kv_paged=True, kv_block_size=bs,
                        kv_num_blocks=num_blocks, kv_shared=True,
                    ),
                ).start()
                servers.append(srv)
            warm_prompt = [0] * prefix_len + [1, 2]
            for srv in servers:
                # pay each replica's jit compiles outside the window
                # with a FULL-SHAPE request (block-aligned prefix +
                # suffix + decode): a cold family inside the window
                # must cost one prefill, never a multi-second compile
                # stall that blows the load margin and cascades
                ServingStub(
                    build_channel("localhost:%d" % srv.port)
                ).generate(
                    pb.GenerateRequest(prompt=warm_prompt,
                                       max_new_tokens=4),
                    timeout=600,
                )
                srv.mark_steady()
            router = Router(
                ["localhost:%d" % s.port for s in servers],
                config=RouterConfig(
                    poll_secs=0.2, lease_secs=2.0,
                    affinity=affinity_on,
                    affinity_block_tokens=bs,
                    # a couple of cold prefills stacked on the
                    # affine target (queue+slots+inflight) must not
                    # decay the whole family off its warm replica:
                    # the A/B's on-leg expresses "placement first",
                    # and the off-leg ignores the knob entirely
                    affinity_load_margin=8.0,
                ),
            )
            router.start(grpc_server=False)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if router.status_response().healthy >= len(servers):
                    break
                time.sleep(0.1)

            rows = []
            lock = threading.Lock()

            def one(spec):
                t0 = time.monotonic()
                row = {"status": "OK", "ttft_ms": None, "spec": spec}
                try:
                    for chunk in router.dispatch_stream(
                        pb.GenerateRequest(
                            prompt=[int(t) for t in spec["prompt"]],
                            max_new_tokens=spec["new"],
                            temperature=args.temperature,
                            seed=spec["seed"],
                        )
                    ):
                        if row["ttft_ms"] is None and chunk.tokens:
                            row["ttft_ms"] = (
                                (time.monotonic() - t0) * 1000.0
                            )
                except RouterError as e:
                    row["status"] = e.code
                with lock:
                    rows.append(row)

            threads = []
            for spec in plan:
                time.sleep(spec["gap"])
                t = threading.Thread(target=one, args=(spec,))
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=600)

            hits = sum(
                s.engine.kv_stats()["prefix_hit_tokens"]
                for s in servers
            )
            snap = router.telemetry.snapshot()
            warm = [
                r["ttft_ms"] for r in rows
                if r["status"] == "OK" and r["ttft_ms"] is not None
                and r["spec"]["seed"] >= families  # pass 2 onward
            ]
            offered = len(plan) * prefix_len
            cold = families * prefix_len  # first touch: unavoidable
            return {
                "completed": sum(
                    1 for r in rows if r["status"] == "OK"
                ),
                "prefix_hit_tokens": hits,
                "repaid_prefix_tokens": max(
                    0, offered - hits - cold
                ),
                "warm_ttft_ms": percentiles(warm, (50, 90, 99)) or {},
                "affinity_hits": snap["affinity_hits"],
                "affinity_misses": snap["affinity_misses"],
            }
        finally:
            if router is not None:
                router.stop()
            for srv in servers:
                srv.stop()

    on, off = run_leg(True), run_leg(False)
    return {
        "model_params": PRESS_MODEL_PARAMS,
        "block_size": bs,
        "replicas": 2,
        "prefix_families": families,
        "passes": passes,
        "prefix_tokens_offered": len(plan) * prefix_len,
        "cold_prefix_tokens": families * prefix_len,
        # the headline: prefill the FLEET re-pays because requests
        # landed away from the replica already holding their chain
        "repaid_prefix_tokens": [on["repaid_prefix_tokens"],
                                 off["repaid_prefix_tokens"]],
        "repaid_drop": (
            off["repaid_prefix_tokens"] - on["repaid_prefix_tokens"]
        ),
        "repaid_improved": (
            on["repaid_prefix_tokens"] < off["repaid_prefix_tokens"]
        ),
        "prefix_hit_tokens": [on["prefix_hit_tokens"],
                              off["prefix_hit_tokens"]],
        "affinity_hit_rate": round(
            on["affinity_hits"]
            / max(1, on["affinity_hits"] + on["affinity_misses"]), 3,
        ),
        "warm_ttft_ms": [on["warm_ttft_ms"], off["warm_ttft_ms"]],
        "warm_ttft_p99_improved": (
            (on["warm_ttft_ms"].get("p99") or 0.0)
            < (off["warm_ttft_ms"].get("p99") or 0.0)
        ),
        "completed": [on["completed"], off["completed"]],
        "affinity_on": on,
        "affinity_off": off,
    }


def run_disagg_ab(args):
    """The disaggregation A/B at EQUAL FLEET KV BYTES: one open-loop
    plan of long COLD prompts (every prompt unique — every prefill is
    paid inside the window) through a real two-replica in-process
    fleet behind the Router, three ways:

      monolithic      two unified replicas, chunking OFF — a 224-token
                      prefill monopolizes its scheduler tick, and
                      requests admitted meanwhile wait it out
                      (prefill_blocked_by_other)
      chunked         same fleet, prefill tiled (PRESS_BLOCK_SIZE
                      tokens per tile) under the per-tick budget —
                      decode steps and other admissions interleave
                      between tiles
      chunked_disagg  chunked + phase-split: replica 0 re-roles as a
                      dedicated PREFILL replica (out of rotation), the
                      router runs every cold prompt through a
                      prefill->TransferChain handoff, and the decode
                      replica seats the imported chain by prefix hit —
                      its scheduler never runs a cold prompt's prefill

    Every leg fires the SAME plan and holds the same fleet KV bytes
    (2 pools x num_blocks x block_bytes). Per leg, tail_report runs
    the slowest-TTFT-decile forensics — the headline is the
    prefill_blocked_by_other share of the tail breakdown, which
    chunking must REDUCE vs monolithic at goodput >= 0.95x."""
    import numpy as np

    from elasticdl_tpu.observability.tracing import new_trace_id
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel
    from elasticdl_tpu.serving import GenerationServer, ServingConfig
    from elasticdl_tpu.serving.router import (
        Router,
        RouterConfig,
        RouterError,
    )

    trainer, state, _ = build_rig(args, model_params=PRESS_MODEL_PARAMS)
    vocab = int(trainer.model.vocab_size)
    bs = PRESS_BLOCK_SIZE
    o_lo, o_hi = _span(args.out_len)
    s_lo, s_hi = _span(args.suffix_len)
    prompt_len = (PRESS_PREFIX_LEN // bs) * bs  # full blocks
    # 64-token tiles: big enough that per-tile dispatch overhead stays
    # noise on the CPU rig (4 tiles per prompt), small enough that a
    # cold prompt's monopolization window shrinks 4x
    chunk_tokens = 4 * bs
    # BURSTY arrivals — the contention is structural, not Poisson
    # luck: each burst lands burst_size cold prompts on 2 replicas at
    # once, so at least two share a replica and the later one's
    # admission waits out the earlier one's prefill (monolithic) or
    # only its current tile (chunked). Bursts are spaced so the fleet
    # drains between them — the A/B measures scheduling, not
    # saturation.
    bursts, burst_size, burst_gap = 8, 4, 1.2
    requests = bursts * burst_size
    rate = burst_size / burst_gap
    seat_blocks = -(-(prompt_len + s_hi + o_hi - 1) // bs)
    # pools hold EVERY chain the window creates (plus warmup and
    # seats): eviction must never clip a chain between its register
    # and its export, or between its import and its seat — a clipped
    # chain re-prefills an odd-length suffix whose tile bucket would
    # COMPILE inside the measurement window and swamp the tail with
    # compile stalls instead of scheduling
    num_blocks = (requests + 3) * seat_blocks
    rs = np.random.RandomState(args.seed + 43)
    plan = []
    for i in range(requests):
        suffix = rs.randint(0, vocab,
                            size=rs.randint(s_lo, s_hi + 1))
        plan.append({
            "prompt": np.concatenate([
                rs.randint(0, vocab, size=prompt_len), suffix,
            ]),
            "new": int(rs.randint(o_lo, o_hi + 1)),
            "gap": (burst_gap if i and i % burst_size == 0 else 0.0),
            "seed": int(i),
        })

    def run_leg(chunk_tokens, disagg):
        servers, router = [], None
        roles = ("prefill", "decode") if disagg else (None, None)
        try:
            for role in roles:
                srv = GenerationServer(
                    trainer, state,
                    ServingConfig(
                        num_slots=2, queue_capacity=32,
                        kv_paged=True, kv_block_size=bs,
                        kv_num_blocks=num_blocks, kv_shared=True,
                        role=role,
                        prefill_chunk_tokens=chunk_tokens,
                    ),
                ).start()
                servers.append(srv)
            warm_prompt = [0] * prompt_len + [1, 2]
            for srv in servers:
                # pay each replica's compiles outside the measurement
                # window: the full prefill (or its tiles) + decode
                # step first, then a same-prefix request whose short
                # suffix compiles the prefix-hit tile — the path every
                # imported chain's request runs on the decode side
                stub = ServingStub(
                    build_channel("localhost:%d" % srv.port)
                )
                stub.generate(
                    pb.GenerateRequest(prompt=warm_prompt,
                                       max_new_tokens=4),
                    timeout=600,
                )
                stub.generate(
                    pb.GenerateRequest(
                        prompt=[0] * prompt_len + [3],
                        max_new_tokens=4,
                    ),
                    timeout=600,
                )
                srv.mark_steady()
            router = Router(
                ["localhost:%d" % s.port for s in servers],
                config=RouterConfig(
                    poll_secs=0.2, lease_secs=2.0,
                    affinity=True, affinity_block_tokens=bs,
                    affinity_load_margin=8.0, disagg=disagg,
                ),
            )
            router.start(grpc_server=False)
            want = 1 if disagg else 2
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                st = router.status_response()
                roles_seen = sum(
                    1 for r in router.replicas() if r.role
                )
                if st.healthy >= want and (
                    not disagg or roles_seen >= 2
                ):
                    break
                time.sleep(0.1)

            rows = []
            lock = threading.Lock()

            def one(spec):
                t0 = time.monotonic()
                trace_id = new_trace_id()
                row = {"status": "OK", "ttft_ms": None, "phase": 0,
                       "tokens": 0, "trace_id": trace_id,
                       "spec": spec}
                try:
                    for chunk in router.dispatch_stream(
                        pb.GenerateRequest(
                            prompt=[int(t) for t in spec["prompt"]],
                            max_new_tokens=spec["new"],
                            temperature=args.temperature,
                            seed=spec["seed"],
                            trace_id=trace_id,
                        )
                    ):
                        if row["ttft_ms"] is None and chunk.tokens:
                            row["ttft_ms"] = (
                                (time.monotonic() - t0) * 1000.0
                            )
                        row["tokens"] += len(chunk.tokens)
                except RouterError as e:
                    row["status"] = e.code
                row["latency_ms"] = (time.monotonic() - t0) * 1000.0
                with lock:
                    rows.append(row)

            threads = []
            t_start = time.monotonic()
            for spec in plan:
                time.sleep(spec["gap"])
                t = threading.Thread(target=one, args=(spec,))
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=600)
            wall = time.monotonic() - t_start

            ok = [r for r in rows if r["status"] == "OK"]
            snap = router.telemetry.snapshot()
            pools = [s.engine.kv_stats() for s in servers]
            tail = tail_report(rows, [(rate, wall)])
            return {
                "chunk_tokens": chunk_tokens,
                "disagg": disagg,
                "completed": len(ok),
                "goodput_rps": round(len(ok) / wall, 3),
                "tokens_per_sec": round(
                    sum(r["tokens"] for r in ok) / wall, 3
                ),
                "ttft_ms": percentiles(
                    [r["ttft_ms"] for r in ok
                     if r["ttft_ms"] is not None], (50, 90, 99)
                ) or {},
                "fleet_kv_bytes": sum(
                    p["kv_bytes_total"] for p in pools
                ),
                "disagg_handoffs": snap.get("disagg_handoffs", 0),
                "disagg_fallbacks": snap.get("disagg_fallbacks", 0),
                "chain_exports": sum(
                    p.get("chain_exports", 0) for p in pools
                ),
                "chain_imports": sum(
                    p.get("chain_imports", 0) for p in pools
                ),
                # the two-pool post-drain ledger (drill-grade)
                "pools_clean": all(
                    p["kv_blocks_free"] == p["kv_blocks_total"]
                    for p in pools
                ),
                "tail_report": tail,
                "tail_blocked_share": tail["breakdown_share"][
                    "prefill_blocked_by_other"
                ],
                "tail_blocked_ms": tail["breakdown_ms"][
                    "prefill_blocked_by_other"
                ],
            }
        finally:
            if router is not None:
                router.stop()
            for srv in servers:
                srv.stop()

    mono = run_leg(0, False)
    chunked = run_leg(chunk_tokens, False)
    split = run_leg(chunk_tokens, True)
    mono_good = mono["goodput_rps"] or 1e-9
    return {
        "model_params": PRESS_MODEL_PARAMS,
        "block_size": bs,
        "prompt_len": prompt_len,
        "requests": requests,
        "rate_rps": rate,
        "replicas": 2,
        "equal_fleet_kv_bytes": (
            mono["fleet_kv_bytes"] == chunked["fleet_kv_bytes"]
            == split["fleet_kv_bytes"]
        ),
        # the headline: what share of the slowest-TTFT-decile wall is
        # sitting behind ANOTHER request's prefill, per leg
        "tail_blocked_share": [mono["tail_blocked_share"],
                               chunked["tail_blocked_share"],
                               split["tail_blocked_share"]],
        "tail_blocked_ms": [mono["tail_blocked_ms"],
                            chunked["tail_blocked_ms"],
                            split["tail_blocked_ms"]],
        "blocked_reduced_chunked_vs_mono": (
            chunked["tail_blocked_ms"] < mono["tail_blocked_ms"]
        ),
        "goodput_rps": [mono["goodput_rps"], chunked["goodput_rps"],
                        split["goodput_rps"]],
        "chunked_goodput_ratio": round(
            (chunked["goodput_rps"] or 0.0) / mono_good, 3
        ),
        "disagg_goodput_ratio": round(
            (split["goodput_rps"] or 0.0) / mono_good, 3
        ),
        "ttft_ms": [mono["ttft_ms"], chunked["ttft_ms"],
                    split["ttft_ms"]],
        "disagg_handoffs": split["disagg_handoffs"],
        "disagg_fallbacks": split["disagg_fallbacks"],
        "pools_clean": [mono["pools_clean"], chunked["pools_clean"],
                        split["pools_clean"]],
        "monolithic": mono,
        "chunked": chunked,
        "chunked_disagg": split,
    }


#: the enabled metrics+profiler plane may cost at most this fraction
#: of the disabled plane's tokens/sec (the PR 6 tracing bound, kept)
OVERHEAD_BOUND = 0.05


def run_overhead_ab(args, trainer, state, plan, num_slots,
                    num_blocks, draft):
    """The observability overhead A/B: the SAME arrival plan on the
    paged+shared pool, plane OFF (no profiler, no exposition, no
    forensics — exemplars, tail retention and slow-cause attribution
    all disarmed — and no runtime health: sentry, accountant and
    watchdog all absent) vs ON (profiler armed — split compiled
    steps — plus a live /metrics server that gets scraped at the end,
    the full forensics plane AND the runtime health plane: recompile
    sentry on every executable, ledger reconciliation, progress
    watchdog). tokens/sec must stay within OVERHEAD_BOUND; one
    retry forgives a scheduler hiccup on a noisy CI box, but two
    misses fail the bench (a >5% observability tax is a regression,
    not noise)."""
    ratios = []
    for _attempt in range(2):
        off, _ = run_load(
            args, trainer, state, plan, num_slots,
            kv_paged=True, kv_block_size=args.kv_block_size,
            kv_num_blocks=num_blocks, kv_shared=True,
            draft=draft, draft_k=args.draft_k,
            forensics=False, runtime_health=False,
        )
        on, _ = run_load(
            args, trainer, state, plan, num_slots,
            kv_paged=True, kv_block_size=args.kv_block_size,
            kv_num_blocks=num_blocks, kv_shared=True,
            draft=draft, draft_k=args.draft_k,
            profile=True, metrics_port=0, forensics=True,
            runtime_health=True,
        )
        ratio = ((on["tokens_per_sec"] or 0.0)
                 / (off["tokens_per_sec"] or 1e-9))
        ratios.append(round(ratio, 4))
        if ratio >= 1.0 - OVERHEAD_BOUND:
            break
    return {
        "bound": OVERHEAD_BOUND,
        "tokens_per_sec": [off["tokens_per_sec"],
                           on["tokens_per_sec"]],
        "goodput_rps": [off["goodput_rps"], on["goodput_rps"]],
        "ratios": ratios,
        "tokens_per_sec_ratio": ratios[-1],
        "within_bound": ratios[-1] >= 1.0 - OVERHEAD_BOUND,
        "profile": on.get("profile"),
        "metrics_scrape": on.get("metrics_scrape"),
    }


def run_bench(args):
    if args.kv_cache_dtype and not args.compare_paged:
        # single-run mode: the whole run serves quantized arenas
        args.model_params += (
            "; kv_cache_dtype=%r" % args.kv_cache_dtype
        )
    trainer, state, draft = build_rig(args)
    seq_len = int(trainer.model.seq_len)
    vocab = int(trainer.model.vocab_size)
    plan = build_plan(args, seq_len, vocab)
    if args.kv_block_size < 1 or seq_len % args.kv_block_size:
        raise SystemExit(
            "kv_block_size %d must divide seq_len %d"
            % (args.kv_block_size, seq_len)
        )
    # dense-equivalent block budget: the SAME KV bytes the dense pool
    # pins for --num_slots, expressed in blocks
    dense_blocks = args.num_slots * (seq_len // args.kv_block_size)
    num_blocks = args.kv_num_blocks or dense_blocks
    host_bytes = (
        args.kv_host_blocks * block_bytes_for(trainer,
                                              args.kv_block_size)
        if args.kv_host_blocks > 0 else 0
    )

    record, results = run_load(
        args, trainer, state, plan, args.num_slots,
        kv_paged=bool(args.kv_paged),
        kv_block_size=args.kv_block_size,
        kv_num_blocks=num_blocks if args.kv_paged else 0,
        kv_shared=bool(args.kv_paged and args.kv_shared),
        draft=draft if args.kv_paged else None,
        draft_k=args.draft_k,
        kv_host_bytes=host_bytes if args.kv_paged else 0,
        profile=args.profile,
        metrics_port=0 if args.profile else None,
    )
    if args.ramp:
        # forensics over the ramp's slow tail: which cause dominates
        # the slowest decile, per phase (the in-process span trees are
        # still in the recorder — the bench minted the trace ids)
        record["tail_report"] = tail_report(
            results, parse_ramp(args.ramp)
        )
    if args.overhead_ab:
        # metrics+profiler overhead A/B on the paged+shared shape (the
        # path with the most instrumented phases)
        record["profiler_overhead"] = run_overhead_ab(
            args, trainer, state, plan,
            args.paged_slots or 2 * args.num_slots, dense_blocks,
            draft,
        )
    if args.disagg:
        # the disaggregation A/B: monolithic vs chunked prefill vs
        # chunked + phase-split fleet at equal fleet KV bytes, with
        # the slowest-TTFT-decile cause breakdown per leg — its own
        # long-prompt rig, so it runs with or without --compare_paged
        record["disagg_ab"] = run_disagg_ab(args)
    if not args.compare_paged:
        return record

    # the A/B legs: equal KV bytes (the dense pool's budget), spread
    # over more slots — first the private paged pool (the concurrency
    # block granularity alone admits), then the prefix-SHARED pool
    # (+ speculative decode when --draft_k is on): what dedup converts
    # the same bytes into
    paged_slots = args.paged_slots or 2 * args.num_slots
    paged, _ = run_load(
        args, trainer, state, plan, paged_slots,
        kv_paged=True,
        kv_block_size=args.kv_block_size,
        kv_num_blocks=dense_blocks,
        kv_shared=False,
    )
    shared, _ = run_load(
        args, trainer, state, plan, paged_slots,
        kv_paged=True,
        kv_block_size=args.kv_block_size,
        kv_num_blocks=dense_blocks,
        kv_shared=True,
    )
    record["paged"] = paged
    record["paged_shared"] = shared
    if draft is not None:
        # the draft on/off A/B rides the shared leg: same plan, same
        # pool, plus the speculative draft-verify tick
        spec, _ = run_load(
            args, trainer, state, plan, paged_slots,
            kv_paged=True,
            kv_block_size=args.kv_block_size,
            kv_num_blocks=dense_blocks,
            kv_shared=True,
            draft=draft,
            draft_k=args.draft_k,
        )
        record["paged_shared_spec"] = spec
        shared_tok = shared["tokens_per_sec"] or 1e-9
        record["spec_vs_shared"] = {
            "draft_k": args.draft_k,
            "tokens_per_sec": [shared["tokens_per_sec"],
                               spec["tokens_per_sec"]],
            "tokens_per_sec_ratio": round(
                (spec["tokens_per_sec"] or 0.0) / shared_tok, 3
            ),
            "draft_accept_rate": spec["draft"]["accept_rate"],
        }
    if args.kv_cache_dtype == "int8":
        # the int8-arena leg: SAME byte budget, paid in ~2-3x as many
        # int8 blocks (block bytes shrink to int8 rows + f32 scales),
        # with slots raised to let the extra blocks become extra
        # concurrency; sharing (and the draft, when on) ride along —
        # the compounding the arenas exist for
        i8_trainer, i8_state, _ = build_rig(
            args,
            model_params=(args.model_params
                          + "; kv_cache_dtype='int8'"),
        )
        fp_bb = block_bytes_for(trainer, args.kv_block_size)
        i8_bb = block_bytes_for(i8_trainer, args.kv_block_size)
        i8_blocks = max(1, (dense_blocks * fp_bb) // i8_bb)
        i8_slots = 2 * paged_slots
        int8, i8_results = run_load(
            args, i8_trainer, i8_state, plan, i8_slots,
            kv_paged=True,
            kv_block_size=args.kv_block_size,
            kv_num_blocks=i8_blocks,
            kv_shared=True,
            draft=draft,
            draft_k=args.draft_k,
            # profiling the headline leg: its greedy-match rate below
            # then ALSO pins the SPLIT (profiled) step path against
            # the int8 dense oracle in a real serve
            profile=args.profile,
            metrics_port=0 if args.profile else None,
        )
        record["paged_int8"] = int8
        shared_tok = shared["tokens_per_sec"] or 1e-9
        shared_bpt = shared["kv"]["bytes_per_token"] or 1e-9
        record["int8_vs_shared"] = {
            # equal BYTES, not equal blocks: the whole point
            "equal_kv_bytes": abs(
                int8["kv"]["bytes_total"]
                - shared["kv"]["bytes_total"]
            ) <= i8_bb,
            "blocks": [shared["kv"]["blocks_total"],
                       int8["kv"]["blocks_total"]],
            "bytes_per_token": [shared["kv"]["bytes_per_token"],
                                int8["kv"]["bytes_per_token"]],
            "bytes_per_token_improvement": round(
                1.0 - (int8["kv"]["bytes_per_token"] or 0.0)
                / shared_bpt, 3,
            ),
            "max_active_slots": [shared["max_active_slots"],
                                 int8["max_active_slots"]],
            "goodput_rps": [shared["goodput_rps"],
                            int8["goodput_rps"]],
            "tokens_per_sec_ratio": round(
                (int8["tokens_per_sec"] or 0.0) / shared_tok, 3
            ),
            # token-level correctness of the quantized serving path:
            # completed greedy streams vs the int8 dense oracle
            "greedy_match_rate_vs_int8_dense": greedy_match_rate(
                i8_trainer, i8_state, i8_results, args.temperature
            ),
        }
    if args.kv_host_blocks > 0 and args.shared_prefix:
        # the tiered-KV eviction-pressure A/B: its own long-prefix
        # rig (int8 arenas when --kv_cache_dtype says so — the
        # serve-smoke shape, where one host GB buys ~3x the chains)
        record["host_vs_evict"] = run_host_evict_ab(args)
    if args.shared_prefix:
        # the router-tier prefix-affinity A/B: the same shared-prefix
        # shape one tier up — does fingerprint-affine dispatch stop
        # the fleet re-paying prefills it already holds?
        record["affinity_ab"] = run_affinity_ab(args)
    base_good = record["goodput_rps"] or 1e-9
    base_tok = record["tokens_per_sec"] or 1e-9
    record["paged_vs_dense"] = {
        "equal_kv_bytes": paged["kv"]["bytes_total"]
        == record["kv"]["bytes_total"],
        "goodput_ratio": round((paged["goodput_rps"] or 0.0)
                               / base_good, 3),
        "tokens_per_sec_ratio": round((paged["tokens_per_sec"] or 0.0)
                                      / base_tok, 3),
        "max_active_slots": [record["max_active_slots"],
                             paged["max_active_slots"]],
        "bytes_per_token": [record["kv"]["bytes_per_token"],
                            paged["kv"]["bytes_per_token"]],
    }
    paged_tok = paged["tokens_per_sec"] or 1e-9
    paged_bpt = paged["kv"]["bytes_per_token"] or 1e-9
    record["shared_vs_paged"] = {
        "equal_kv_bytes": shared["kv"]["bytes_total"]
        == paged["kv"]["bytes_total"],
        "tokens_per_sec_ratio": round(
            (shared["tokens_per_sec"] or 0.0) / paged_tok, 3
        ),
        "max_active_slots": [paged["max_active_slots"],
                             shared["max_active_slots"]],
        "bytes_per_token": [paged["kv"]["bytes_per_token"],
                            shared["kv"]["bytes_per_token"]],
        "bytes_per_token_improvement": round(
            1.0 - (shared["kv"]["bytes_per_token"] or 0.0) / paged_bpt,
            3,
        ),
        "prefix_hit_tokens": shared["kv"]["prefix_hit_tokens"],
    }
    return record


def main(argv=None):
    args = parse_args(argv)
    record = run_bench(args)
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    # a bench run that completed nothing is a failure, not a datum;
    # an observability plane that taxes the serve path past the bound
    # is one too
    overhead = record.get("profiler_overhead")
    if overhead is not None and not overhead["within_bound"]:
        print("profiler overhead A/B OUT OF BOUND: ratio %.4f < %.4f"
              % (overhead["tokens_per_sec_ratio"],
                 1.0 - OVERHEAD_BOUND), file=sys.stderr)
        return 1
    # the recompile sentry's steady-state invariant: once the warmup
    # boundary is marked, membership churn must never recompile an
    # existing executable — a nonzero count here is the compile-storm
    # failure class the health plane exists to catch, and it fails
    # the bench on every leg that carried the plane
    steady_violations = [
        (leg, rec["health"]["steady_recompiles"])
        for leg, rec in [("base", record)] + [
            (k, record[k]) for k in ("paged", "paged_shared",
                                     "paged_shared_spec", "paged_int8")
            if isinstance(record.get(k), dict)
        ]
        if isinstance(rec.get("health"), dict)
        and rec["health"]["steady_recompiles"]
    ]
    if steady_violations:
        print("STEADY-STATE RECOMPILES detected: %r (the zero-"
              "recompile invariant is broken)" % steady_violations,
              file=sys.stderr)
        return 1
    return 0 if record["completed"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
