#!/usr/bin/env python
"""Closed-loop serving load generator: the serving-throughput entry in
the bench trajectory (BENCH_* record family).

Runs the real stack in one process — GenerationServer (continuous-
batching engine + gRPC transport) and a Poisson open-loop arrival
process of streaming clients with mixed prompt/output lengths — and
emits ONE JSON line:

    {"metric": "serving_goodput_tokens_per_sec", "value": ...,
     "ttft_ms": {"p50": ..., "p99": ...}, "latency_ms": {...},
     "tokens_per_sec": ..., "goodput_rps": ..., "rejected": ...,
     "expired": ..., ...}

* TTFT is measured at the FIRST streamed chunk (prefill + queueing);
* tokens_per_sec counts only tokens of COMPLETED requests over the
  measurement wall; goodput_rps is completed requests per second —
  rejected (backpressure) and expired (deadline) requests score zero,
  which is what makes overload visible as a goodput plateau;
* arrivals are open-loop Poisson (exponential gaps at --rate), so
  backpressure actually engages instead of the clients self-throttling.

Defaults are CPU-smoke sized (`make serve-smoke`); on hardware raise
--requests/--rate and the model dims.

Usage:
    python scripts/bench_serving.py --requests 32 --rate 16 \
        --num_slots 4 --out BENCH_SERVING.json
"""

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=16.0,
                   help="mean arrival rate, requests/sec (Poisson)")
    p.add_argument("--num_slots", type=int, default=4)
    p.add_argument("--queue_capacity", type=int, default=16)
    p.add_argument("--prompt_len", default="2:6",
                   help="min:max prompt tokens (uniform)")
    p.add_argument("--out_len", default="4:12",
                   help="min:max generated tokens (uniform)")
    p.add_argument("--deadline_ms", type=int, default=0,
                   help="per-request deadline budget; 0 = none")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--model_params", default=(
        "vocab_size=32; seq_len=32; embed_dim=32; num_heads=2; "
        "num_layers=1"
    ))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="",
                   help="also write the JSON record to this path")
    return p.parse_args(argv)


def _span(text):
    lo, _, hi = text.partition(":")
    lo, hi = int(lo), int(hi or lo)
    if not 1 <= lo <= hi:
        raise ValueError("bad span %r" % text)
    return lo, hi


def percentile(values, q):
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, int(round(q / 100.0 * (len(vs) - 1))))
    return vs[idx]


def run_bench(args):
    import jax
    import numpy as np

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel
    from elasticdl_tpu.serving import GenerationServer, ServingConfig
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=args.model_params,
    )
    seq_len = int(trainer.model.seq_len)
    vocab = int(trainer.model.vocab_size)
    dummy = np.zeros((1, seq_len), np.int32)
    state = trainer.init_state(({"tokens": dummy}, dummy))
    server = GenerationServer(
        trainer, state,
        ServingConfig(
            num_slots=args.num_slots,
            queue_capacity=args.queue_capacity,
        ),
    ).start()
    stub = ServingStub(build_channel("localhost:%d" % server.port))

    p_lo, p_hi = _span(args.prompt_len)
    o_lo, o_hi = _span(args.out_len)
    if p_hi + o_hi > seq_len:
        raise SystemExit(
            "prompt_len max %d + out_len max %d exceeds seq_len %d"
            % (p_hi, o_hi, seq_len)
        )
    rs = np.random.RandomState(args.seed)
    plan = [
        {
            "prompt": rs.randint(0, vocab,
                                 size=rs.randint(p_lo, p_hi + 1)),
            "new": int(rs.randint(o_lo, o_hi + 1)),
            "gap": float(rs.exponential(1.0 / args.rate)),
            "seed": int(i),
        }
        for i in range(args.requests)
    ]

    # one warmup request outside the measurement: pays the jit compiles
    stub.generate(
        pb.GenerateRequest(prompt=[1, 2], max_new_tokens=2), timeout=300
    )

    results = []
    lock = threading.Lock()

    def one(spec):
        t0 = time.monotonic()
        row = {"status": "OK", "tokens": 0, "ttft_ms": None}
        try:
            stream = stub.generate_stream(
                pb.GenerateRequest(
                    prompt=[int(t) for t in spec["prompt"]],
                    max_new_tokens=spec["new"],
                    temperature=args.temperature,
                    seed=spec["seed"],
                    deadline_ms=args.deadline_ms,
                ),
                timeout=300,
            )
            for chunk in stream:
                if row["ttft_ms"] is None and chunk.tokens:
                    row["ttft_ms"] = (time.monotonic() - t0) * 1000.0
                row["tokens"] += len(chunk.tokens)
        except Exception as e:  # noqa: BLE001 - status is the datum
            code = getattr(e, "code", None)
            row["status"] = (
                code().name if callable(code) else type(e).__name__
            )
        row["latency_ms"] = (time.monotonic() - t0) * 1000.0
        with lock:
            results.append(row)

    threads = []
    bench_t0 = time.monotonic()
    for spec in plan:
        time.sleep(spec["gap"])
        t = threading.Thread(target=one, args=(spec,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=600)
    wall = time.monotonic() - bench_t0

    status = stub.server_status(pb.ServerStatusRequest(), timeout=30)
    server.stop()

    ok = [r for r in results if r["status"] == "OK"]
    ttfts = [r["ttft_ms"] for r in ok if r["ttft_ms"] is not None]
    lats = [r["latency_ms"] for r in ok]
    tokens_ok = sum(r["tokens"] for r in ok)
    record = {
        "metric": "serving_goodput_tokens_per_sec",
        "value": round(tokens_ok / wall, 3) if wall else None,
        "unit": "tokens/sec",
        "platform": jax.default_backend(),
        "requests": args.requests,
        "rate_rps": args.rate,
        "num_slots": args.num_slots,
        "queue_capacity": args.queue_capacity,
        "completed": len(ok),
        "rejected": sum(
            1 for r in results if r["status"] == "RESOURCE_EXHAUSTED"
        ),
        "expired": sum(
            1 for r in results if r["status"] == "DEADLINE_EXCEEDED"
        ),
        "goodput_rps": round(len(ok) / wall, 3) if wall else None,
        "tokens_per_sec": round(tokens_ok / wall, 3) if wall else None,
        "ttft_ms": {
            "p50": percentile(ttfts, 50), "p99": percentile(ttfts, 99),
        },
        "latency_ms": {
            "p50": percentile(lats, 50), "p99": percentile(lats, 99),
        },
        "wall_secs": round(wall, 3),
        "max_active_slots": status.max_active_slots,
        "server_tokens_generated": status.tokens_generated,
    }
    return record


def main(argv=None):
    args = parse_args(argv)
    record = run_bench(args)
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    # a bench run that completed nothing is a failure, not a datum
    return 0 if record["completed"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
