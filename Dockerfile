# Framework image: elasticdl_tpu + native libs + model zoo. Job images
# built by `elasticdl-tpu zoo build` layer a user zoo onto an image like
# this one (reference elasticdl/docker/Dockerfile).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make zlib1g-dev \
    && rm -rf /var/lib/apt/lists/*

# TPU-enabled jax on a TPU VM; the cpu extra works everywhere else.
ARG JAX_VARIANT=tpu
RUN pip install --no-cache-dir "jax[${JAX_VARIANT}]" flax optax \
        grpcio protobuf numpy kubernetes

COPY elasticdl_tpu /framework/elasticdl_tpu
COPY model_zoo /framework/model_zoo
COPY pyproject.toml README.md /framework/
RUN make -C /framework/elasticdl_tpu/native \
    && pip install --no-cache-dir -e /framework

ENV PYTHONPATH=/framework
WORKDIR /framework
