"""Feature-column equivalents.

The reference adds a ``ConcatenatedCategoricalColumn`` to TF's feature-column
system (elasticdl_preprocessing/feature_column/feature_column.py) plus an
``embedding_column`` backed by the distributed embedding delegate
(elasticdl/feature_column/feature_column.py). Without TF's column machinery,
this framework expresses the same two compositions functionally: a column is
a callable ``features_dict -> ids/values array`` plus metadata, composable
into model input pipelines.
"""

import numpy as np

from elasticdl_tpu.preprocessing.layers import ConcatenateWithOffset


class CategoricalColumn(object):
    """ids column: key into the features dict + its bucket count."""

    def __init__(self, key, num_buckets, transform=None):
        self.key = key
        self.num_buckets = int(num_buckets)
        self._transform = transform

    def __call__(self, features):
        v = features[self.key]
        return self._transform(v) if self._transform else v


def categorical_column_with_identity(key, num_buckets):
    return CategoricalColumn(key, num_buckets)


def concatenated_categorical_column(categorical_columns):
    """Concatenate several categorical columns into ONE id space by shifting
    each column's ids past the previous columns' bucket counts (reference
    ConcatenatedCategoricalColumn: offsets = cumulative num_buckets)."""
    offsets = np.cumsum(
        [0] + [c.num_buckets for c in categorical_columns[:-1]]
    ).tolist()
    concat = ConcatenateWithOffset(offsets=offsets, axis=-1)
    total = sum(c.num_buckets for c in categorical_columns)

    def column(features):
        parts = []
        for c in categorical_columns:
            ids = np.asarray(c(features))
            if ids.ndim == 1:
                ids = ids[:, None]
            parts.append(ids)
        return concat(parts)

    column.num_buckets = total
    column.keys = [c.key for c in categorical_columns]
    return column


def embedding_column(categorical_column, dimension, combiner="mean",
                     initializer="uniform"):
    """Pair a categorical column with an Embedding layer spec (reference
    elasticdl/feature_column/feature_column.py embedding_column: lookup
    delegated to the distributed table). Returns (column_fn, layer_factory):
    apply column_fn in dataset_fn, instantiate the layer inside the model."""
    from elasticdl_tpu.embedding.layer import Embedding

    num_buckets = getattr(categorical_column, "num_buckets")

    def layer_factory(name=None):
        return Embedding(
            input_dim=num_buckets,
            output_dim=dimension,
            combiner=combiner,
            embeddings_initializer=initializer,
            name=name,
        )

    return categorical_column, layer_factory
