"""Feature-preprocessing layers.

Parity with ``elasticdl_preprocessing/layers/`` (11 layers predating TF's own
preprocessing set). Design notes for the TPU build:

* Numeric transforms (Normalizer, RoundIdentity, LogRound, Discretization,
  ConcatenateWithOffset, Hashing-on-ints) are ``jnp``-traceable, so they can
  run either host-side inside ``dataset_fn`` or inside the jit-compiled
  model.
* String transforms (IndexLookup, ToNumber, Hashing-on-strings) are
  host-side numpy ops — strings never enter XLA. Use them in ``dataset_fn``.
* TF's SparseTensor/RaggedTensor input forms map to this framework's padded
  id matrices: PADDING_ID (-1) marks absent slots (see embedding/layer.py).
  Transforms preserve padding slots; ToSparse/ToRagged convert between dense
  and padded forms.
* Hashing parity note: the reference hashes with TF's
  ``strings.to_hash_bucket_fast`` (FarmHash64 — hashing.py). This build uses
  md5 (stable, seedless, dependency-free); bucket DISTRIBUTION properties
  match, exact bucket assignments differ from TF.
"""

import hashlib

import numpy as np

from elasticdl_tpu.embedding.layer import PADDING_ID


def _is_jax(x):
    import jax

    return isinstance(x, jax.Array)


def _np_mod(x):
    """numpy for host arrays, jax.numpy for traced/device arrays."""
    if _is_jax(x):
        import jax.numpy as jnp

        return jnp
    return np


class _Layer(object):
    """Callable-layer base (keras Layer stand-in)."""

    def __call__(self, inputs):
        return self.call(inputs)


class Normalizer(_Layer):
    """(x - subtractor) / divisor (reference normalizer.py)."""

    def __init__(self, subtractor, divisor):
        if divisor == 0:
            raise ValueError("The divisor cannot be 0")
        self.subtractor = subtractor
        self.divisor = divisor

    def call(self, inputs):
        xp = _np_mod(inputs)
        x = xp.asarray(inputs, dtype=xp.float32)
        return (x - self.subtractor) / self.divisor


class RoundIdentity(_Layer):
    """round(x) as an integer id; out-of-[0, num_buckets) → default_value
    (reference round_identity.py `_round_and_truncate`)."""

    def __init__(self, num_buckets, default_value=0):
        self.num_buckets = int(num_buckets)
        self.default_value = int(default_value)

    def call(self, inputs):
        xp = _np_mod(inputs)
        v = xp.round(xp.asarray(inputs, dtype=xp.float32)).astype(xp.int64)
        bad = (v < 0) | (v >= self.num_buckets)
        return xp.where(bad, xp.int64(self.default_value), v)


class LogRound(_Layer):
    """round(log_base(x)) as an integer id; out-of-[0, num_bins) →
    default_value (reference log_round.py)."""

    def __init__(self, num_bins, base=None, default_value=0):
        self.num_bins = int(num_bins)
        self.base = base
        self.default_value = int(default_value)

    def call(self, inputs):
        xp = _np_mod(inputs)
        x = xp.asarray(inputs, dtype=xp.float32)
        v = xp.log(x)
        if self.base is not None:
            v = v / xp.log(xp.float32(self.base))
        v = xp.round(v).astype(xp.int64)
        bad = (v < 0) | (v >= self.num_bins)
        return xp.where(bad, xp.int64(self.default_value), v)


class Discretization(_Layer):
    """Bucketize by boundaries: output = #boundaries <= x, so `bins=[0,1,2]`
    yields buckets (-inf,0) [0,1) [1,2) [2,inf) (reference
    discretization.py)."""

    def __init__(self, bins):
        self.bins = list(bins)

    def num_bins(self):
        return len(self.bins) + 1

    def call(self, inputs):
        if _is_jax(inputs):
            import jax.numpy as jnp

            x = jnp.asarray(inputs)
            b = jnp.asarray(self.bins, dtype=x.dtype)
            return jnp.searchsorted(b, x, side="right").astype(jnp.int64)
        x = np.asarray(inputs)
        return np.digitize(x, self.bins, right=False).astype(np.int64)


class Hashing(_Layer):
    """value → md5(str(value)) % num_bins (reference hashing.py uses
    FarmHash64 via strings.to_hash_bucket_fast; see module docstring for the
    divergence). Int inputs are stringified first, exactly like the
    reference. Padding slots (PADDING_ID) pass through untouched."""

    def __init__(self, num_bins):
        if num_bins is None or num_bins <= 0:
            raise ValueError(
                "`num_bins` cannot be `None` or non-positive values."
            )
        self.num_bins = int(num_bins)

    def _hash_one(self, v):
        if isinstance(v, bytes):
            s = v
        else:
            s = str(v).encode("utf-8")
        return int.from_bytes(
            hashlib.md5(s).digest()[:8], "little"
        ) % self.num_bins

    def call(self, inputs):
        arr = np.asarray(inputs)
        if arr.dtype.kind in ("i", "u"):
            out = np.empty(arr.shape, np.int64)
            flat_in, flat_out = arr.reshape(-1), out.reshape(-1)
            for i, v in enumerate(flat_in):
                flat_out[i] = (
                    PADDING_ID if v == PADDING_ID else self._hash_one(int(v))
                )
            return out
        out = np.empty(arr.shape, np.int64)
        flat_in, flat_out = arr.reshape(-1), out.reshape(-1)
        for i, v in enumerate(flat_in):
            flat_out[i] = self._hash_one(v)
        return out


class IndexLookup(_Layer):
    """String → zero-based index by vocabulary; OOV maps to
    ``hash(v) % num_oov_tokens + len(vocab)`` (reference index_lookup.py:
    with the default num_oov_tokens=1 every OOV value becomes len(vocab))."""

    def __init__(self, vocabulary=None, num_oov_tokens=1):
        if isinstance(vocabulary, str):
            with open(vocabulary) as f:
                vocabulary = [line.rstrip("\n") for line in f if line.strip()]
        vocabulary = list(vocabulary or [])
        if len(set(vocabulary)) != len(vocabulary):
            raise ValueError(
                "The vocabulary has repeated items: %s"
                % [v for v in set(vocabulary) if vocabulary.count(v) > 1]
            )
        self.vocabulary = vocabulary
        self.num_oov_tokens = int(num_oov_tokens)
        self._table = {self._norm(v): i for i, v in enumerate(vocabulary)}
        self._hash = Hashing(max(self.num_oov_tokens, 1))

    @staticmethod
    def _norm(v):
        return v.decode("utf-8") if isinstance(v, bytes) else str(v)

    def vocab_size(self):
        return len(self.vocabulary) + self.num_oov_tokens

    def call(self, inputs):
        arr = np.asarray(inputs)
        out = np.empty(arr.shape, np.int64)
        flat_in, flat_out = arr.reshape(-1), out.reshape(-1)
        n = len(self.vocabulary)
        for i, v in enumerate(flat_in):
            key = self._norm(v)
            idx = self._table.get(key)
            if idx is None:
                if self.num_oov_tokens > 1:
                    idx = n + self._hash._hash_one(key)
                else:
                    idx = n
            flat_out[i] = idx
        return out


class ConcatenateWithOffset(_Layer):
    """Add offsets[i] to each id tensor, then concatenate (reference
    concatenate_with_offset.py). Padding slots keep PADDING_ID so combiner
    lookups still ignore them."""

    def __init__(self, offsets, axis=-1):
        self.offsets = offsets
        self.axis = axis

    def call(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return inputs
        if self.offsets is not None and len(self.offsets) != len(inputs):
            raise ValueError(
                "The offsets length is not equal to inputs length: "
                "inputs %d, offsets %d" % (len(inputs), len(self.offsets))
            )
        xp = _np_mod(inputs[0])
        shifted = []
        for i, t in enumerate(inputs):
            t = xp.asarray(t)
            if self.offsets is not None:
                off = self.offsets[i]
                t = xp.where(t == PADDING_ID, t, t + off)
            shifted.append(t)
        return xp.concatenate(shifted, axis=self.axis)


class ToNumber(_Layer):
    """Parse strings to numbers; unparseable/empty → default_value
    (reference to_number.py)."""

    def __init__(self, out_type, default_value):
        self.out_type = np.dtype(out_type)
        self.default_value = default_value

    def call(self, inputs):
        arr = np.asarray(inputs)
        if arr.dtype.kind in ("i", "u", "f"):
            return arr.astype(self.out_type)
        out = np.empty(arr.shape, self.out_type)
        flat_in, flat_out = arr.reshape(-1), out.reshape(-1)
        caster = float if self.out_type.kind == "f" else lambda s: int(
            float(s)
        )
        for i, v in enumerate(flat_in):
            s = v.decode("utf-8") if isinstance(v, bytes) else str(v)
            try:
                flat_out[i] = caster(s)
            except (ValueError, TypeError):
                flat_out[i] = self.default_value
        return out


class ToRagged(_Layer):
    """Dense → ragged, dropping `ignore_value` entries (reference
    to_ragged.py). Padded-dense equivalent: surviving values are compacted
    left and the tail filled with PADDING_ID, so downstream combiner lookups
    see the same id multiset per row."""

    def __init__(self, ignore_value=-1):
        self.ignore_value = ignore_value

    def call(self, inputs):
        arr = np.asarray(inputs)
        if arr.ndim == 1:
            arr = arr[:, None]
        out = np.full(arr.shape, PADDING_ID, np.int64)
        for r in range(arr.shape[0]):
            keep = [
                v for v in arr[r]
                if not self._ignored(v)
            ]
            out[r, : len(keep)] = [int(v) for v in keep]
        return out

    def _ignored(self, v):
        if isinstance(v, (bytes, str)):
            s = v.decode("utf-8") if isinstance(v, bytes) else v
            return s == str(self.ignore_value) or s == ""
        return v == self.ignore_value


class ToSparse(ToRagged):
    """Dense → sparse keeping positions (reference to_sparse.py). In the
    padded-dense representation positions are preserved: ignored entries
    simply become PADDING_ID."""

    def call(self, inputs):
        arr = np.asarray(inputs)
        if arr.dtype.kind in ("i", "u"):
            return np.where(
                arr == self.ignore_value, np.int64(PADDING_ID), arr
            ).astype(np.int64)
        out = np.empty(arr.shape, np.int64)
        flat_in, flat_out = arr.reshape(-1), out.reshape(-1)
        for i, v in enumerate(flat_in):
            flat_out[i] = PADDING_ID if self._ignored(v) else int(
                float(v.decode() if isinstance(v, bytes) else v)
            )
        return out


def SparseEmbedding(
    input_dim, output_dim, combiner="sum", embeddings_initializer="uniform"
):
    """Embedding over padded sparse ids with a combiner (reference
    sparse_embedding.py: safe_embedding_lookup_sparse over a SparseTensor).
    Returns the framework's Embedding module configured with the combiner —
    the two layers share one implementation here by construction."""
    from elasticdl_tpu.embedding.layer import Embedding

    return Embedding(
        input_dim=input_dim,
        output_dim=output_dim,
        combiner=combiner,
        embeddings_initializer=embeddings_initializer,
    )
