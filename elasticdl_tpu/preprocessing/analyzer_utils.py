"""Placeholder transform-parameter accessors.

Parity with elasticdl_preprocessing/utils/analyzer_utils.py: in the reference
these return placeholder values that a SQLFlow table-analysis pass substitutes
at template-expansion time. This build computes them directly from a numpy
column when given one, falling back to the same pass-through placeholders.
"""

import numpy as np


def get_min(column=None, default=0.0):
    return float(np.min(column)) if column is not None else default


def get_max(column=None, default=1.0):
    return float(np.max(column)) if column is not None else default


def get_avg(column=None, default=0.0):
    return float(np.mean(column)) if column is not None else default


def get_stddev(column=None, default=1.0):
    return float(np.std(column)) if column is not None else default


def get_bucket_boundaries(column=None, num_buckets=10, default=None):
    """Quantile boundaries (len = num_buckets - 1)."""
    if column is None:
        return default if default is not None else []
    qs = np.linspace(0, 100, num_buckets + 1)[1:-1]
    return np.percentile(np.asarray(column), qs).tolist()


def get_vocabulary(column=None, default=None):
    if column is None:
        return default if default is not None else []
    values = np.asarray(column).reshape(-1)
    seen = {}
    for v in values:
        s = v.decode("utf-8") if isinstance(v, bytes) else str(v)
        seen.setdefault(s, None)
    return list(seen)
