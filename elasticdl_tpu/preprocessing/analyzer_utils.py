"""Transform-parameter accessors for the SQLFlow analysis pass.

Parity with elasticdl_preprocessing/utils/analyzer_utils.py:23-160 and
constants.py:15-22 (`AnalysisEnvTemplate`): in the reference, a SQLFlow
table-analysis pass exports per-feature statistics into environment
variables (``_<feature>_min``, ``_<feature>_stddev``, ...) and these
accessors read them by feature NAME, falling back to a caller default
(so unit tests run without the pass). All seven accessors are here,
including ``get_distinct_count``.

TPU-first addition: each accessor also accepts a numpy column directly
(the analysis result does not have to ride the environment), and
``publish_analysis`` is the analysis pass itself — it computes a
column's statistics and exports them under the reference's env names,
so name-keyed reads round-trip without SQLFlow.
"""

import os

import numpy as np


class AnalysisEnvTemplate(object):
    """Reference elasticdl_preprocessing/constants.py:15-22."""

    MIN_ENV = "_{}_min"
    MAX_ENV = "_{}_max"
    AVG_ENV = "_{}_avg"
    STDDEV_ENV = "_{}_stddev"
    BUCKET_BOUNDARIES_ENV = "_{}_boundaries"
    DISTINCT_COUNT_ENV = "_{}_distinct_count"
    VOCABULARY_ENV = "_{}_vocab"


def _env(template, name):
    return os.getenv(template.format(name), None)


def _scalar(feature, default, template, reduce_fn):
    if feature is None:
        return default
    if isinstance(feature, str):
        value = _env(template, feature)
        return default if value is None else float(value)
    return float(reduce_fn(np.asarray(feature)))


def get_min(feature=None, default=0.0):
    """Min of a numeric feature: by column array, or by feature name
    from the analysis environment (reference analyzer_utils.py:23-40)."""
    return _scalar(feature, default, AnalysisEnvTemplate.MIN_ENV, np.min)


def get_max(feature=None, default=1.0):
    return _scalar(feature, default, AnalysisEnvTemplate.MAX_ENV, np.max)


def get_avg(feature=None, default=0.0):
    return _scalar(feature, default, AnalysisEnvTemplate.AVG_ENV, np.mean)


def get_stddev(feature=None, default=1.0):
    return _scalar(
        feature, default, AnalysisEnvTemplate.STDDEV_ENV, np.std
    )


def get_bucket_boundaries(feature=None, num_buckets=10, default=None):
    """Quantile boundaries (len = num_buckets - 1) from a column, or the
    sorted-deduped comma-separated env list by feature name (reference
    analyzer_utils.py:102-121)."""
    fallback = default if default is not None else []
    if feature is None:
        return fallback
    if isinstance(feature, str):
        value = _env(AnalysisEnvTemplate.BUCKET_BOUNDARIES_ENV, feature)
        if not value:  # unset OR published-empty (num_buckets <= 1)
            return fallback
        return sorted(set(map(float, value.split(","))))
    qs = np.linspace(0, 100, num_buckets + 1)[1:-1]
    return np.percentile(np.asarray(feature), qs).tolist()


def get_distinct_count(feature=None, default=0):
    """Count of distinct feature values (reference
    analyzer_utils.py:123-140)."""
    if feature is None:
        return default
    if isinstance(feature, str):
        value = _env(AnalysisEnvTemplate.DISTINCT_COUNT_ENV, feature)
        return default if value is None else int(value)
    return int(np.unique(np.asarray(feature).reshape(-1)).size)


def get_vocabulary(feature=None, default=None):
    """Vocabulary of a categorical feature: first-seen order from a
    column, or the env value by feature name — which the reference
    passes through verbatim (a vocabulary file path OR a
    comma-separated list; analyzer_utils.py:142-160). A comma-separated
    env value is split here so callers get a list either way; a path
    (no comma, has a separator) passes through."""
    fallback = default if default is not None else []
    if feature is None:
        return fallback
    if isinstance(feature, str):
        value = _env(AnalysisEnvTemplate.VOCABULARY_ENV, feature)
        if value is None:
            return fallback
        if value.startswith("["):
            # publish_analysis writes JSON so values containing commas
            # or path separators round-trip exactly
            import json

            try:
                return json.loads(value)
            except ValueError:
                pass
        if "," not in value and os.sep in value:
            return value  # vocabulary file path, reference passthrough
        return value.split(",")
    values = np.asarray(feature).reshape(-1)
    seen = {}
    for v in values:
        s = v.decode("utf-8") if isinstance(v, bytes) else str(v)
        seen.setdefault(s, None)
    return list(seen)


def publish_analysis(feature_name, column, num_buckets=10,
                     is_categorical=None):
    """The analysis pass itself: compute `column`'s statistics and
    export them under the reference env names, so subsequent name-keyed
    accessor calls (e.g. inside a generated SQLFlow model) resolve. The
    reference left this to SQLFlow's table analyzer; here it is one
    call. Returns the {env_name: value} map it set."""
    column = np.asarray(column)
    if is_categorical is None:
        is_categorical = not np.issubdtype(column.dtype, np.number)
    t = AnalysisEnvTemplate
    out = {}
    if is_categorical:
        import json as _json

        out[t.VOCABULARY_ENV.format(feature_name)] = _json.dumps(
            get_vocabulary(column)
        )
    else:
        out[t.MIN_ENV.format(feature_name)] = repr(get_min(column))
        out[t.MAX_ENV.format(feature_name)] = repr(get_max(column))
        out[t.AVG_ENV.format(feature_name)] = repr(get_avg(column))
        out[t.STDDEV_ENV.format(feature_name)] = repr(get_stddev(column))
        out[t.BUCKET_BOUNDARIES_ENV.format(feature_name)] = ",".join(
            repr(b) for b in get_bucket_boundaries(
                column, num_buckets=num_buckets
            )
        )
    out[t.DISTINCT_COUNT_ENV.format(feature_name)] = str(
        get_distinct_count(column)
    )
    os.environ.update(out)
    return out
