from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    LogRound,
    Normalizer,
    RoundIdentity,
    SparseEmbedding,
    ToNumber,
    ToRagged,
    ToSparse,
)
