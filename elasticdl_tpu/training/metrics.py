"""Evaluation metric aggregation.

The reference ships raw model outputs+labels from workers to the master,
which updates Keras metric objects in ≤300-row chunks
(common/evaluation_utils.py, master/evaluation_service.py:55-62). Here the
same dataflow exists (workers report outputs+labels; the eval service owns
aggregation), with two metric kinds:

* per-sample callables ``fn(labels, predictions) -> array`` (the zoo
  convention, e.g. accuracy) — aggregated as a running weighted mean;
* stateful metric objects with ``update(labels, predictions)`` / ``result()``
  (for metrics needing global state, e.g. AUC).
"""

import numpy as np


class StreamingMetric(object):
    """Base for stateful metrics (subclass with update/result)."""

    def update(self, labels, predictions):
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


class AUC(StreamingMetric):
    """Binary AUC via a fixed-bin score histogram (XLA/EVAL-friendly,
    memory-bounded like the reference's chunked Keras AUC updates)."""

    def __init__(self, num_thresholds=200):
        self._bins = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self._bins, np.int64)
        self._neg = np.zeros(self._bins, np.int64)

    def update(self, labels, predictions):
        labels = np.asarray(labels).reshape(-1)
        scores = np.asarray(predictions).reshape(-1)
        # squash logits into [0, 1) bin space
        probs = 1.0 / (1.0 + np.exp(-scores.astype(np.float64)))
        idx = np.clip((probs * self._bins).astype(int), 0, self._bins - 1)
        np.add.at(self._pos, idx[labels > 0], 1)
        np.add.at(self._neg, idx[labels <= 0], 1)

    def result(self):
        # trapezoid over ROC from histogram tails
        pos_c = np.cumsum(self._pos[::-1])
        neg_c = np.cumsum(self._neg[::-1])
        tp = pos_c / max(1, pos_c[-1])
        fp = neg_c / max(1, neg_c[-1])
        trapezoid = getattr(np, "trapezoid", None) or np.trapz
        return float(trapezoid(tp, fp))


def flatten_metrics_dict(metrics_dict):
    """Reference parity (common/evaluation_utils.py): ``eval_metrics_fn`` may
    return the flat form {metric: fn} or, for dict-output models, the nested
    form {output_name: {metric: fn}}. Flatten the nested form into
    {"output_metric": fn'} where fn' selects predictions[output] (and
    labels[output] when labels are also a dict)."""
    flat = {}
    for name, fn in metrics_dict.items():
        if isinstance(fn, dict):
            for metric_name, metric_fn in fn.items():
                flat["%s_%s" % (name, metric_name)] = _bind_output(
                    metric_fn, name
                )
        else:
            flat[name] = fn
    return flat


def _bind_output(metric_fn, output_name):
    if isinstance(metric_fn, StreamingMetric):

        class _Bound(StreamingMetric):
            def update(self, labels, predictions):
                metric_fn.update(
                    _pick(labels, output_name), _pick(predictions, output_name)
                )

            def result(self):
                return metric_fn.result()

            def reset(self):
                metric_fn.reset()

        return _Bound()
    return lambda labels, predictions: metric_fn(
        _pick(labels, output_name), _pick(predictions, output_name)
    )


def _pick(x, key):
    if isinstance(x, dict):
        if key not in x:
            raise KeyError(
                "eval_metrics_fn references output %r but the model "
                "produced outputs %r" % (key, sorted(x))
            )
        return x[key]
    return x


class MetricsAggregator(object):
    def __init__(self, metrics_dict):
        metrics_dict = flatten_metrics_dict(metrics_dict)
        self._metrics = metrics_dict
        self._sums = {k: 0.0 for k in metrics_dict}
        self._counts = {k: 0 for k in metrics_dict}

    def update(self, labels, predictions, chunk_size=4096):
        """Feed one batch of raw (labels, outputs). Chunked so huge eval
        reports stay memory-bounded."""
        n = _leading(labels if labels is not None else predictions)
        for lo in range(0, n, chunk_size):
            hi = min(n, lo + chunk_size)
            lab = _slice(labels, lo, hi)
            pred = _slice(predictions, lo, hi)
            for name, fn in self._metrics.items():
                if isinstance(fn, StreamingMetric):
                    fn.update(lab, pred)
                else:
                    vals = np.asarray(fn(lab, pred), np.float64).reshape(-1)
                    self._sums[name] += float(vals.sum())
                    self._counts[name] += vals.size

    def result(self):
        out = {}
        for name, fn in self._metrics.items():
            if isinstance(fn, StreamingMetric):
                out[name] = fn.result()
            else:
                out[name] = self._sums[name] / max(1, self._counts[name])
        return out


def _leading(x):
    if isinstance(x, dict):
        return next(iter(x.values())).shape[0]
    return np.asarray(x).shape[0]


def _slice(x, lo, hi):
    if x is None:
        return None
    if isinstance(x, dict):
        return {k: np.asarray(v)[lo:hi] for k, v in x.items()}
    return np.asarray(x)[lo:hi]
