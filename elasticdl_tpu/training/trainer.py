"""The jit-compiled compute plane: train / evaluate / predict steps.

This replaces the reference worker's TF2-eager gradient path
(worker/worker.py:730-870: forward, tape.gradient, report_gradient to PS) and
the entire PS apply path (ps/servicer.py push_gradients →
OptimizerWrapper.apply_gradients; Go server.go → optimizer.go → Eigen
kernels). On TPU all of that is ONE compiled XLA program per step:

    forward + backward + optax update, sharded over the mesh —
    gradient reduction is not an RPC but the psum XLA inserts because the
    batch is sharded over (dp, fsdp) while params are replicated/sharded.

Design notes (TPU-first):
* static shapes everywhere — partial batches are padded host-side
  (data/dataset.pad_batch) and masked via each example's weight column;
* state is donated (`donate_argnums`) so params/opt-state update in place
  in HBM;
* models come from the zoo convention (flax.linen Module whose __call__
  takes a feature dict and `training` flag);
* loss signature parity with the reference zoo: loss(labels, predictions),
  with an optional 3rd `sample_weights` arg picked up by introspection.
"""

import inspect

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from flax.core import FrozenDict

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.sharding import (
    infer_state_pspec,
    pspec_to_sharding,
)


@struct.dataclass
class TrainState:
    step: jax.Array
    params: any = struct.field(pytree_node=True)
    opt_state: any = struct.field(pytree_node=True)
    model_state: any = struct.field(pytree_node=True)  # batch_stats etc.
    rng: jax.Array = struct.field(pytree_node=True)
    # Per-table row-optimizer slots for sparse-grad embedding tables
    # ({table_path_str: optax state}); empty for dense-only models.
    # See embedding/sparse_update.py.
    embed_opt_state: any = struct.field(pytree_node=True, default_factory=dict)

    @property
    def version(self):
        """Model version = step count (the reference's PS `version` that
        workers/eval sync on is the number of applied updates)."""
        return int(self.step)


def _split_label(batch):
    """Zoo datasets yield (features_dict, labels) for train/eval and bare
    features for prediction (reference dataset_fn convention)."""
    if isinstance(batch, tuple) and len(batch) == 2:
        return batch[0], batch[1]
    return batch, None


class Trainer(object):
    """Owns the model/optimizer from a ModelSpec and the compiled steps.

    One Trainer per process; the same object backs the LocalExecutor
    (reference elasticdl/local_executor.py) and the distributed Worker
    (reference worker/worker.py).
    """

    def __init__(self, model_spec, mesh=None, model_params="", seed=0,
                 compute_dtype=None, callbacks=None,
                 embedding_partition_threshold=None, grad_accum_steps=1,
                 trainable_pattern=None):
        self.spec = model_spec
        self.model = model_spec.create_model(model_params)
        from elasticdl_tpu.embedding.sparse_optim import make_row_sparse

        tx = model_spec.optimizer()
        if callbacks is None and model_spec.callbacks_fn is not None:
            callbacks = model_spec.callbacks_fn()
        tx, self._lr_multiplier_fn = _apply_lr_scheduler(tx, callbacks)
        # The raw transform: reused per-table by the row-sparse engine
        # (embedding/sparse_update.py — optax state leaves are
        # elementwise, so applying the same tx to gathered rows is the
        # reference OptimizerWrapper's "stock optimizer on looked-up
        # rows+slots", ps/optimizer_wrapper.py:70-351).
        self._base_tx = tx
        # Row-sparse embedding semantics for small (non-tapped) tables
        # (dense update + mask: untouched rows and slots don't move).
        # Identity for models without embedding tables.
        self.tx = make_row_sparse(tx)
        # Gradient accumulation (the reference worker's local-update mode,
        # worker.py:822-828/1007-1089: accumulate per-minibatch gradients
        # and push to the PS every `get_model_steps`). Here the PS round
        # trip is gone, so the TPU-native semantics are optax.MultiSteps:
        # each train_step call is one microbatch; the dense optimizer
        # applies the averaged gradient every Nth call and emits zero
        # updates in between. Sparse-tapped embedding tables and host-
        # spill tables keep their per-microbatch row updates (the
        # reference likewise pushed embedding grads through the
        # OptimizerWrapper on every report).
        self.grad_accum_steps = max(1, int(grad_accum_steps))
        # Fine-tuning: regex over '/'-joined param paths (e.g.
        # "head|block_7" trains the LM head and the last block).
        # Non-matching params are FROZEN via optax.set_to_zero inside
        # the transform — not by zeroing gradients, which would still
        # let decoupled weight decay (adamw) move frozen weights.
        # Applies to the dense optimizer path; sparse-row/host-spill
        # embedding engines keep their own update schedule.
        self.trainable_pattern = trainable_pattern
        # Filled by init_state once the model structure is known:
        self._sparse_paths = {}
        self._train_tx = None
        self._perturb_shapes = {}
        self.embedding_partition_threshold = embedding_partition_threshold
        self.mesh = mesh if mesh is not None else mesh_lib.local_mesh()
        self.seed = seed
        self.compute_dtype = compute_dtype
        self._loss_takes_weights = (
            len(inspect.signature(model_spec.loss).parameters) >= 3
        )
        if not self._loss_takes_weights:
            logger.warning(
                "loss() takes no sample_weights arg: padded rows of partial "
                "final batches will enter the loss unmasked (add a 3rd "
                "`sample_weights` parameter for exact partial-batch math)"
            )
        self._train_step = None
        self._eval_step = None
        self._predict_step = None
        self._state_sharding = None
        self._defer_sparse = False
        self._sparse_stage = []
        self._apply_rows_fn = None
        # Host-spill embedding bridge (embedding/host_bridge.py): pulls
        # rows before the compiled step, applies row grads after it.
        self._host_manager = None
        # Tier-health counters: host-tier apply/stage failures degrade
        # to "those rows miss one update" by design (see _host_apply);
        # these make the degradation observable instead of grep-able.
        # Cumulative for the Trainer's lifetime; the worker forwards
        # them to the master as tier/ exec counters, which the master
        # turns into TensorBoard gauges.
        self.tier_health = {
            "host_failed_cycles": 0,
            "host_dropped_row_updates": 0,
        }

    # ------------------------------------------------------- host bridge

    def attach_host_embeddings(self, manager):
        """Register a HostEmbeddingManager. Must happen before the first
        init_state/train_step so the compiled signature includes the
        pulled-row inputs. Multi-host SPMD: enable_spmd the manager and
        drive training through the assembled path (worker._spmd_step) —
        the local train_step/forward entry points reject SPMD-mode
        managers."""
        if self._train_step is not None or self._eval_step is not None:
            raise RuntimeError(
                "attach_host_embeddings must precede step compilation"
            )
        self._host_manager = manager
        return self

    @property
    def host_manager(self):
        return self._host_manager

    def _host_prepare(self, features):
        if self._host_manager:
            return self._host_manager.prepare(features)
        return features

    # ---------------------------------------------------------------- init

    def init_state(self, example_batch):
        """Initialize params/opt-state sharded over the mesh.

        The reference initializes variables lazily on the worker's first
        minibatch and pushes them to the PS (worker.py:664-701
        `_run_model_call_before_training`); here the same "first batch
        defines the variables" contract seeds a sharded jit init.
        """
        from elasticdl_tpu.embedding import sparse_update

        features, _ = _split_label(example_batch)
        features = self._host_prepare(features)
        features = jax.tree.map(jnp.asarray, features)
        root_rng = jax.random.PRNGKey(self.seed)
        init_rng, state_rng = jax.random.split(root_rng)

        # Structure pass: discover sparse-grad embedding taps (flax
        # perturbations the layer creates at init) and derive the dense
        # transform that excludes those tables.
        var_shapes = jax.eval_shape(
            lambda r, f: self.model.init(
                {"params": r, "dropout": r}, f, training=False
            ),
            init_rng, features,
        )
        perturb_shapes = dict(var_shapes).get(
            sparse_update.PERTURB_COLLECTION, {}
        )
        # nn.with_partitioning annotations (TP model families): collected
        # from the boxed init shapes, honored by infer_state_pspec, and
        # stripped from the stored params below (unbox).
        from elasticdl_tpu.parallel.sharding import collect_annotations

        self._param_annotations = collect_annotations(
            dict(var_shapes).get("params", {})
        )
        self._perturb_shapes = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
            perturb_shapes,
        )
        self._sparse_paths = sparse_update.sparse_table_paths(
            perturb_shapes
        )
        self._train_tx = sparse_update.split_dense_tx(
            self.tx, set(self._sparse_paths)
        )
        if self.trainable_pattern:
            # the freeze wraps the DENSE transform only; sparse-row and
            # host-spill embedding tiers run their own update engines
            # and would silently keep training — refuse instead of
            # breaking the "non-matching params do not move" contract
            import re as _re

            _rex = _re.compile(self.trainable_pattern)
            escaped = [
                p for p in self._sparse_paths
                if not _rex.search("/".join(str(k) for k in p))
            ]
            if escaped or self._host_manager is not None:
                raise NotImplementedError(
                    "trainable_pattern freezes the dense optimizer "
                    "path only; %s run their own update engines. "
                    "Match them in the pattern, or disable the tier "
                    "(sparse_grads=False / no host_embeddings) for "
                    "fine-tuning."
                    % (
                        "host-spill tables" if self._host_manager
                        else "sparse-row tables %s" % (escaped,)
                    )
                )
            self._train_tx = _freeze_except(
                self._train_tx, self.trainable_pattern
            )
        if self.grad_accum_steps > 1:
            # Every tier shares ONE schedule (k microbatches -> one
            # applied update): the dense tier through optax.MultiSteps
            # (mean of k grads), the sparse-row tier by staging each
            # microbatch's (ids, row grads)/k host-side and applying the
            # concatenation at the macro boundary (apply_flat_row_updates
            # — dedup sums across microbatches), and the host-spill tier
            # via HostEmbeddingManager.stage/apply_staged. Engines and
            # row_tx step counters therefore advance once per macro step,
            # exactly like a k-times-larger batch.
            import optax

            self._train_tx = optax.MultiSteps(
                self._train_tx, every_k_schedule=self.grad_accum_steps
            )
        self._defer_sparse = bool(
            self._sparse_paths and self.grad_accum_steps > 1
        )
        self._sparse_stage = []
        self._apply_rows_fn = None

        def init_fn(rng, feats):
            from flax.linen import meta as nn_meta

            variables = self.model.init(
                {"params": rng, "dropout": rng}, feats, training=False
            )
            variables = dict(nn_meta.unbox(variables))
            params = variables.pop("params")
            variables.pop(sparse_update.PERTURB_COLLECTION, None)
            variables.pop(sparse_update.SPARSE_IDS_COLLECTION, None)
            opt_state = self._train_tx.init(params)
            embed_opt = sparse_update.init_row_opt_states(
                self._base_tx, params, self._sparse_paths
            )
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=opt_state,
                model_state=FrozenDict(variables),
                rng=state_rng,
                embed_opt_state=embed_opt,
            )

        state_shapes = jax.eval_shape(init_fn, init_rng, features)
        kwargs = {"annotations": self._param_annotations}
        if self.embedding_partition_threshold is not None:
            kwargs["embedding_threshold_bytes"] = (
                self.embedding_partition_threshold
            )
        pspecs = infer_state_pspec(state_shapes, self.mesh, **kwargs)
        self._state_sharding = pspec_to_sharding(pspecs, self.mesh)
        with self.mesh:
            state = jax.jit(
                init_fn, out_shardings=self._state_sharding
            )(init_rng, features)
        n_params = sum(
            int(np.prod(x.shape))
            for x in jax.tree.leaves(state.params)
        )
        logger.info(
            "Initialized model: %d parameters, mesh axes %s",
            n_params, dict(self.mesh.shape),
        )
        return state

    # --------------------------------------------------------------- steps

    def _compute_loss(self, labels, predictions, weights):
        if self._loss_takes_weights:
            return self.spec.loss(labels, predictions, weights)
        return self.spec.loss(labels, predictions)

    def _build_train_step(self):
        from elasticdl_tpu.embedding import sparse_update

        batch_sh = mesh_lib.batch_sharding(self.mesh)
        repl = mesh_lib.replicated(self.mesh)
        tx = self._train_tx if self._train_tx is not None else self.tx
        sparse_paths = self._sparse_paths
        perturb_shapes = self._perturb_shapes
        ids_coll = sparse_update.SPARSE_IDS_COLLECTION
        # Pulled host-table rows are differentiable inputs: their grads
        # (the backward scatter-add of rows[idx]) are the per-unique-row
        # gradients the host engines apply (embedding/host_bridge.py).
        host_keys = (
            self._host_manager.rows_keys() if self._host_manager else ()
        )

        def train_step(state, features, labels, weights):
            dropout_rng = jax.random.fold_in(state.rng, state.step)
            # The row-grad taps are identically-zero perturbations
            # rebuilt every step (XLA folds the zeros); their gradients
            # are the per-row embedding grads.
            perturbs = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), perturb_shapes
            )
            host_rows = {k: features[k] for k in host_keys}
            base_features = {
                k: v for k, v in features.items() if k not in host_keys
            }

            def loss_fn(params, perturbs, host_rows):
                features = dict(base_features, **host_rows)
                variables = {"params": params, **state.model_state}
                if sparse_paths:
                    variables[sparse_update.PERTURB_COLLECTION] = perturbs
                mutable = [k for k in state.model_state if k != "params"]
                if sparse_paths:
                    mutable = mutable + [ids_coll]
                # `mutable` is collection NAMES from the state pytree —
                # static structure, not traced values
                if mutable:  # edl-lint: disable=EDL102
                    preds, new_mut = self.model.apply(
                        variables,
                        features,
                        training=True,
                        mutable=mutable,
                        rngs={"dropout": dropout_rng},
                    )
                    new_mut = dict(new_mut)
                    ids = new_mut.pop(ids_coll, {})
                    new_model_state = new_mut
                else:
                    preds = self.model.apply(
                        variables,
                        features,
                        training=True,
                        rngs={"dropout": dropout_rng},
                    )
                    new_model_state = state.model_state
                    ids = {}
                return (
                    self._compute_loss(labels, preds, weights),
                    (new_model_state, ids),
                )

            (loss_val, (new_model_state, ids)), grads = jax.value_and_grad(
                loss_fn, argnums=(0, 1, 2), has_aux=True
            )(state.params, perturbs, host_rows)
            param_grads, perturb_grads, host_grads = grads
            updates, new_opt_state = tx.update(
                param_grads, state.opt_state, state.params
            )
            new_params = jax.tree.map(
                lambda p, u: (p + u).astype(p.dtype),
                state.params,
                updates,
            )
            embed_opt = state.embed_opt_state
            sparse_aux = {}
            if sparse_paths and not self._defer_sparse:
                new_params, embed_opt = sparse_update.apply_row_updates(
                    self._base_tx, new_params, embed_opt,
                    perturb_grads, ids, sparse_paths,
                )
            elif sparse_paths:
                # gradient accumulation: defer the row update — emit this
                # microbatch's (ids, row grads) per table for host-side
                # staging; the macro boundary applies the concatenation
                # (apply_flat_row_updates)
                pg_flat = {}
                from flax import traverse_util

                flat = traverse_util.flatten_dict(dict(perturb_grads))
                for table_path, perturb_path in sparse_paths.items():
                    key = sparse_update.path_str(table_path)
                    ids_flat = jnp.asarray(
                        sparse_update.extract_ids(ids, perturb_path),
                        jnp.int32,
                    ).reshape(-1)
                    grads = flat[perturb_path]
                    pg_flat[key] = (
                        ids_flat,
                        grads.reshape(ids_flat.shape[0], -1),
                    )
                sparse_aux = pg_flat
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                model_state=FrozenDict(new_model_state),
                embed_opt_state=embed_opt,
            )
            return new_state, loss_val, host_grads, sparse_aux

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._state_sharding, batch_sh, batch_sh, batch_sh),
            out_shardings=(self._state_sharding, repl, repl, repl),
        )

    def _build_eval_step(self):
        batch_sh = mesh_lib.batch_sharding(self.mesh)
        repl = mesh_lib.replicated(self.mesh)

        def eval_step(state, features):
            variables = {"params": state.params, **state.model_state}
            preds = self.model.apply(variables, features, training=False)
            return preds

        return jax.jit(
            eval_step,
            in_shardings=(self._state_sharding, batch_sh),
            out_shardings=repl,
        )

    # ---------------------------------------------------------------- API

    def train_step(self, state, batch, true_count=None):
        """One optimizer update. `batch` = (features, labels) numpy dicts
        already padded to the static batch size; `true_count` masks padding.
        Returns (new_state, float loss)."""
        features, labels = _split_label(batch)
        bsz = _leading_dim(features)
        weights = _make_weights(bsz, true_count)
        self._reject_spmd_host_local_path("train_step")
        features = self._host_prepare(features)
        # int(state.step) forces a host sync (blocks on the previous
        # step's output); only pay it when a host/sparse tier actually
        # consumes it, so dense models keep async dispatch overlap
        tiers = self._host_manager is not None or self._defer_sparse
        pre_step = int(state.step) if tiers else 0
        scale = self._host_lr_scale(pre_step) if tiers else 1.0
        state, loss, host_grads, sparse_aux = self._run_train_step(
            state, features, labels, weights
        )
        if tiers:
            state = self._post_step_tiers(
                pre_step, state, host_grads, sparse_aux, scale
            )
        return state, loss

    def _host_lr_scale(self, pre_step):
        """scale_by_schedule counts applied updates from 0, i.e. the
        pre-update step number — mirror it for the host tier (under
        gradient accumulation: the macro-step index). The multiplier
        runs BEFORE the donating compiled step: a user schedule that
        raises must fail while the caller's state buffers are still
        alive and the batch retryable."""
        if self._host_manager and self._lr_multiplier_fn is not None:
            return float(
                self._lr_multiplier_fn(pre_step // self.grad_accum_steps)
            )
        return 1.0

    def _post_step_tiers(self, pre_step, state, host_grads, sparse_aux,
                         scale):
        """Apply (or stage) the host-spill and sparse-row tiers after
        the compiled step. With grad_accum_steps == 1 this is the
        immediate apply; otherwise each microbatch stages its row grads
        weighted 1/k and the macro boundary (every k-th microbatch)
        applies the merged cycle, keeping every tier on the MultiSteps
        schedule."""
        accum = self.grad_accum_steps
        boundary = accum == 1 or pre_step % accum == accum - 1
        if self._host_manager:
            if accum == 1:
                self._host_apply(host_grads, scale)
            else:
                # Separate accounting per op: a failed stage() loses
                # only the CURRENT microbatch (the buffer is untouched
                # and prior microbatches still apply at the boundary),
                # while a failed apply_staged() loses everything it
                # drained — snapshot staged_row_count BEFORE the drain.
                try:
                    self._host_manager.stage(host_grads,
                                             weight=1.0 / accum)
                except Exception:
                    self._count_dropped_host_rows(
                        self._host_rows_at_risk(staged=False)
                    )
                    logger.exception(
                        "host-embedding stage failed; this "
                        "microbatch's rows miss the cycle (no retry: "
                        "state donated)"
                    )
                if boundary:
                    at_risk = self._host_rows_at_risk(pending=False)
                    try:
                        self._host_manager.apply_staged(lr_scale=scale)
                    except Exception:
                        self._count_dropped_host_rows(at_risk)
                        logger.exception(
                            "host-embedding apply_staged failed; the "
                            "staged cycle's rows miss this update (no "
                            "retry: state donated)"
                        )
        if self._defer_sparse:
            self._sparse_stage.append(
                jax.tree.map(np.asarray, sparse_aux)
            )
            if boundary:
                state = self._apply_sparse_staged(state)
        return state

    def _apply_sparse_staged(self, state):
        """Macro-boundary sparse-row apply: concatenate the staged
        microbatches per table (grads pre-scaled by 1/k at stage time)
        and run ONE row_sparse update — identical math to a k-times
        batch (dedup sums repeats across microbatches; row_tx scalar
        step advances once)."""
        from elasticdl_tpu.embedding import sparse_update

        staged, self._sparse_stage = self._sparse_stage, []
        merged = {}
        for key in staged[0]:
            ids = np.concatenate([m[key][0] for m in staged])
            grads = np.concatenate(
                [m[key][1] / self.grad_accum_steps for m in staged]
            )
            merged[key] = (ids, grads)
        if self._apply_rows_fn is None:
            repl = mesh_lib.replicated(self.mesh)

            def apply_rows(state, merged):
                new_params, new_embed = (
                    sparse_update.apply_flat_row_updates(
                        self._base_tx, state.params,
                        state.embed_opt_state, merged,
                        self._sparse_paths,
                    )
                )
                return state.replace(
                    params=new_params, embed_opt_state=new_embed
                )

            self._apply_rows_fn = jax.jit(
                apply_rows,
                donate_argnums=(0,),
                in_shardings=(self._state_sharding, repl),
                out_shardings=self._state_sharding,
            )
        with self.mesh:
            return self._apply_rows_fn(state, merged)

    def _host_apply(self, host_grads, scale):
        """Apply host-tier row grads after the compiled step. A failure
        here must NOT propagate: the compiled step donated the caller's
        old state buffers, so a retry would replay on deleted arrays
        (bricking the worker's 64-retry loop) and double-apply any
        engine that did step. Instead the affected rows miss this one
        update — the degradation the reference's PS path also accepted
        (dropped grads on PS restart; fault tolerance is
        task-requeue-first, README.md:62-66)."""
        if not self._host_manager:
            return
        at_risk = self._host_rows_at_risk(staged=False)
        try:
            self._host_manager.apply(host_grads, lr_scale=scale)
        except Exception:
            self._count_dropped_host_rows(at_risk)
            # The log itself must not touch device values: with an
            # async device error poisoning this step's outputs,
            # int(state.step) would re-raise the very exception this
            # handler exists to contain.
            logger.exception(
                "host-embedding apply failed; affected rows miss "
                "this update (no retry: state is donated)"
            )

    def _host_rows_at_risk(self, pending=True, staged=True):
        """Row updates a tier failure would drop: the current
        microbatch's pulled rows (`pending`) and/or the accumulation
        buffer (`staged`) — callers pick the component the failing op
        actually loses. Never raises (feeds exception handlers)."""
        try:
            rows = 0
            if pending:
                rows += self._host_manager.pending_row_count()
            if staged:
                rows += self._host_manager.staged_row_count()
            return rows
        except Exception:
            return 0

    def _count_dropped_host_rows(self, rows):
        """Record one failed host-tier cycle in tier_health. Runs inside
        the apply/stage exception handlers, so it must never raise."""
        self.tier_health["host_failed_cycles"] += 1
        self.tier_health["host_dropped_row_updates"] += int(rows)

    def train_step_assembled(self, state, features, labels, weights):
        """Run the compiled step on already-prepared (possibly global
        multi-host) arrays — the SPMD path (parallel/spmd.py). Host-spill
        features must already be prepared (the worker calls
        host_manager.prepare BEFORE assembling, since the multi-host
        prepare is itself a host-level collective); the row grads are
        applied here, each host updating its owned id partition."""
        tiers = self._host_manager is not None or self._defer_sparse
        pre_step = int(state.step) if tiers else 0
        scale = self._host_lr_scale(pre_step) if tiers else 1.0
        state, loss, host_grads, sparse_aux = self._run_train_step(
            state, features, labels, weights
        )
        if tiers:
            state = self._post_step_tiers(
                pre_step, state, host_grads, sparse_aux, scale
            )
        return state, loss

    def _run_train_step(self, state, features, labels, weights):
        if self._train_step is None:
            self._train_step = self._build_train_step()
        with self.mesh:
            return self._train_step(state, features, labels, weights)

    def forward(self, state, features):
        """Inference forward pass (evaluation / prediction). Output is
        replicated to every host."""
        self._reject_spmd_host_local_path("forward")
        features = self._host_prepare(features)
        return self.forward_assembled(state, features)

    def _reject_spmd_host_local_path(self, entry):
        """With the host manager in SPMD mode, prepare() emits idx over
        GLOBAL row positions — feeding that to the local (un-assembled)
        step would make jnp.take clamp out-of-range rows silently. Fail
        fast instead: the worker's assembled path is the only correct
        entry."""
        if (self._host_manager is not None
                and self._host_manager.spmd_ctx is not None):
            raise ValueError(
                "%s() is the local single-host path, but the host-"
                "embedding manager is in SPMD mode; prepare locally and "
                "use train_step_assembled / forward_assembled (see "
                "worker._spmd_step)" % entry
            )

    def forward_assembled(self, state, features):
        """Forward on already-prepared (possibly global multi-host)
        arrays — the SPMD eval path; host-spill features must already be
        prepared (worker._spmd_eval_step prepares before assembling)."""
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        with self.mesh:
            return self._eval_step(state, features)

    def make_weights(self, batch_size, true_count):
        return _make_weights(batch_size, true_count)

    def evaluate_batch(self, state, batch, true_count=None):
        """Returns (outputs, labels) trimmed to true_count, for master-side
        metric aggregation (reference worker.py report_evaluation_metrics).
        Outputs may be a dict for multi-output models."""
        features, labels = _split_label(batch)
        preds = self.forward(state, features)

        def trim(x):
            x = np.asarray(x)
            return x[:true_count] if true_count is not None else x

        if isinstance(preds, dict):
            preds = {k: trim(v) for k, v in preds.items()}
        else:
            preds = trim(preds)
        labels = trim(labels) if labels is not None else None
        return preds, labels


def _freeze_except(tx, pattern):
    """Wrap `tx` so only params whose '/'-joined path matches the regex
    train; everything else gets optax.set_to_zero() (true freezing —
    no optimizer-side movement, including adamw's decoupled weight
    decay). Labels are derived from the params pytree at init time, so
    any model structure works."""
    import re

    import optax

    rex = re.compile(pattern)

    def labels(params):
        def one(path, _):
            name = "/".join(
                str(getattr(k, "key", k)) for k in path
            )
            return "train" if rex.search(name) else "freeze"

        out = jax.tree_util.tree_map_with_path(one, params)
        flat = jax.tree_util.tree_leaves(out)
        n_train = sum(1 for v in flat if v == "train")
        logger.info(
            "trainable_pattern %r: %d/%d param tensors train",
            pattern, n_train, len(flat),
        )
        if n_train == 0:
            logger.warning(
                "trainable_pattern %r matches NOTHING — every "
                "parameter is frozen and training is a no-op", pattern,
            )
        return out

    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )


def _apply_lr_scheduler(tx, callbacks):
    """Chain an optax scale_by_schedule when a LearningRateScheduler
    callback is present (api/callbacks.py: version → LR multiplier,
    compiled into the step). Returns (tx, multiplier_fn or None) — the
    multiplier also scales host-engine row updates so every parameter
    tier sees the same schedule."""
    import optax

    from elasticdl_tpu.api.callbacks import LearningRateScheduler

    for cb in callbacks or []:
        if isinstance(cb, LearningRateScheduler):
            return optax.chain(
                tx, optax.scale_by_schedule(cb.multiplier_fn)
            ), cb.multiplier_fn
    return tx, None


def _leading_dim(features):
    if isinstance(features, dict):
        return next(iter(features.values())).shape[0]
    return features.shape[0]


def _make_weights(batch_size, true_count):
    if true_count is None or true_count >= batch_size:
        return np.ones((batch_size,), np.float32)
    w = np.zeros((batch_size,), np.float32)
    w[:true_count] = 1.0
    return w
