"""elasticdl_tpu — a TPU-native elastic deep-learning framework.

A ground-up rebuild of the capabilities of ElasticDL (Kubernetes-native elastic
training with dynamic data sharding, fault tolerance, parameter-server-class
sparse embeddings, and a train/evaluate/predict CLI over a model zoo) designed
idiomatically for TPUs:

* the compute plane is a single jit-compiled JAX train step sharded over a
  ``jax.sharding.Mesh`` (XLA collectives over ICI replace the reference's
  gRPC parameter-server push/pull data plane),
* sparse embedding tables live sharded across device HBM and are updated with
  static-shape gather/scatter (the reference keeps them in PS pod RAM),
* the control plane (master task queue, dynamic data sharding, elasticity)
  remains a small Python + gRPC service, as in the reference
  (``/root/reference/elasticdl/python/master``).
"""

from elasticdl_tpu.version import __version__  # noqa: F401
