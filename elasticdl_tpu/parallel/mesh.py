"""Device-mesh construction: the TPU-native replacement for the reference's
process topology (N worker pods + M PS pods over gRPC).

Where the reference scales by adding pods, this framework scales by widening a
``jax.sharding.Mesh`` whose named axes carry the parallelism taxonomy
(SURVEY.md §2.5): ``dp`` (data), ``fsdp`` (sharded params over the data axis),
``ep`` (embedding/expert shards — the PS-equivalent axis for sparse tables),
``tp`` (tensor), ``sp`` (sequence/context for ring attention). Elastic
re-formation on membership change = rebuilding the mesh and re-jitting
(reference: FTLib re-init, collective_ops/communicator.py:37-144).
"""

import math

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.common.log_utils import default_logger as logger


def parse_mesh_spec(spec):
    """Parse 'dp=4,ep=2' style mesh specs into an axis-size dict.

    -1 (at most once) means "fill with all remaining devices" — the default
    for dp, which is how elasticity shows up: the same job spec runs on any
    device count.
    """
    sizes = {ax: 1 for ax in MeshAxis.ALL}
    if not spec:
        sizes[MeshAxis.DP] = -1
        return sizes
    seen_fill = False
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        ax, _, val = part.partition("=")
        ax = ax.strip()
        if ax not in sizes:
            raise ValueError(
                "Unknown mesh axis %r (valid: %s)" % (ax, MeshAxis.ALL)
            )
        val = int(val)
        if val == -1:
            if seen_fill:
                raise ValueError("Only one mesh axis may be -1")
            seen_fill = True
        sizes[ax] = val
    if not seen_fill and math.prod(
        v for v in sizes.values()
    ) <= 0:
        raise ValueError("Invalid mesh spec %r" % spec)
    return sizes


def build_mesh(mesh_spec=None, devices=None):
    """Build a Mesh over `devices` (default: all) from a spec string/dict.

    Axes of size 1 are kept in the mesh so PartitionSpecs referencing any
    canonical axis always resolve; XLA treats size-1 axes as free.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if isinstance(mesh_spec, dict):
        sizes = {ax: 1 for ax in MeshAxis.ALL}
        sizes.update(mesh_spec)
    else:
        sizes = parse_mesh_spec(mesh_spec)
    fixed = math.prod(v for v in sizes.values() if v != -1)
    for ax, v in sizes.items():
        if v == -1:
            if n % fixed != 0:
                raise ValueError(
                    "Cannot fill axis %s: %d devices not divisible by %d"
                    % (ax, n, fixed)
                )
            sizes[ax] = n // fixed
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(
            "Mesh %r needs %d devices but %d are available"
            % (sizes, total, n)
        )
    shape = tuple(sizes[ax] for ax in MeshAxis.ALL)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        # create_device_mesh optimizes ICI adjacency; fall back to a plain
        # reshape for virtual/CPU device sets where it can bail out.
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MeshAxis.ALL)
    logger.info("Built mesh %s over %d devices", dict(sizes), n)
    return mesh


def batch_sharding(mesh):
    """Input batches shard their leading axis over (dp, fsdp) — fsdp is a
    data-parallel axis for the batch too."""
    return NamedSharding(mesh, P((MeshAxis.DP, MeshAxis.FSDP)))


def replicated(mesh):
    return NamedSharding(mesh, P())


def local_mesh():
    """A 1-device mesh (single-chip / local-executor path)."""
    return build_mesh({MeshAxis.DP: 1}, devices=jax.devices()[:1])


def current_mesh():
    """The Mesh active via `with mesh:` (how model code — e.g. the
    transformer's attention — discovers the sp axis at trace time inside
    the Trainer's compiled step), or None outside any mesh context."""
    try:
        from jax._src.mesh import thread_resources
    except ImportError:  # older jax
        from jax.interpreters.pxla import thread_resources

    mesh = thread_resources.env.physical_mesh
    if mesh is None or mesh.empty:
        return None
    return mesh
