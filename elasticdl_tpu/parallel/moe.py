"""Mixture-of-experts over the ``ep`` mesh axis (expert parallelism).

Net-new beyond the reference (which has no expert axis — SURVEY.md §2.5;
``ep`` existed for embedding-row sharding only). The design is the
GShard/Switch static-shape formulation, which is what XLA wants:

* top-1 routing with a CAPACITY per expert (ceil(tokens/E) *
  capacity_factor): every tensor keeps a static shape; tokens over
  capacity are dropped from the expert path (their combine weight is 0,
  so they pass through the residual only);
* dispatch and combine are one-hot einsums — no gather/scatter with
  dynamic shapes;
* expert weights are stacked [E, ...] and annotated over ``ep``
  (nn.with_partitioning); GSPMD inserts the all-to-alls when the einsums
  cross the token (dp-sharded) and expert (ep-sharded) dims;
* the load-balancing auxiliary loss is the standard fraction*prob dot
  (Switch Transformer eq. 4), returned to the caller to add to the task
  loss.
"""

import jax
import jax.numpy as jnp


def top1_dispatch(router_logits, capacity):
    """Static-shape top-1 routing.

    router_logits: [T, E]; capacity: int C.
    Returns (dispatch [T, E, C] 0/1, combine [T, E, C] float, aux_loss
    scalar, stats dict). combine = dispatch * router prob of the chosen
    expert; tokens beyond an expert's capacity have all-zero rows.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=probs.dtype)  # [T, E]

    # position of each token within its expert's queue (arrival order)
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # [T, E]
    within = (position >= 0) & (position < capacity)
    kept = onehot * within.astype(probs.dtype)

    pos_onehot = jax.nn.one_hot(
        jnp.clip(position, 0, capacity - 1).astype(jnp.int32),
        capacity,
        dtype=probs.dtype,
    )  # [T, E, C]
    dispatch = kept[..., None] * pos_onehot
    gate = jnp.sum(probs * kept, axis=-1)  # chosen prob, 0 if dropped
    combine = dispatch * gate[:, None, None]

    # Switch aux loss: E * sum_e fraction_e * mean-prob_e
    fraction = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(fraction * mean_prob)
    stats = {
        "dropped_fraction": 1.0 - jnp.sum(kept) / t,
        "expert_fraction": fraction,
    }
    return dispatch, combine, aux_loss, stats


def expert_capacity(num_tokens, num_experts, capacity_factor):
    return max(1, int(num_tokens * capacity_factor / num_experts + 0.5))


def moe_mlp_apply(params, x, capacity_factor=1.25, activation=jax.nn.gelu):
    """Functional MoE MLP: x [T, D] through E expert FFNs.

    params: {"router": [D, E], "w_up": [E, D, H], "b_up": [E, H],
             "w_down": [E, H, D], "b_down": [E, D]} — stacked expert
    leaves sharded over ep by the caller's annotations.
    Returns (y [T, D], aux_loss, stats).
    """
    t = x.shape[0]
    e = params["router"].shape[-1]
    capacity = expert_capacity(t, e, capacity_factor)
    logits = x @ params["router"]
    dispatch, combine, aux_loss, stats = top1_dispatch(logits, capacity)
    # [T,E,C] x [T,D] -> [E,C,D]: the all-to-all boundary under GSPMD
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    h = activation(
        jnp.einsum("ecd,edh->ech", expert_in, params["w_up"])
        + params["b_up"][:, None, :]
    )
    expert_out = (
        jnp.einsum("ech,ehd->ecd", h, params["w_down"])
        + params["b_down"][:, None, :]
    )
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y, aux_loss, stats


def moe_reference(params, x, capacity_factor=1.25,
                  activation=jax.nn.gelu):
    """Oracle: loop over tokens/experts in plain numpy-style code (tests
    compare the einsum formulation against this)."""
    import numpy as np

    x = np.asarray(x, np.float32)
    router = np.asarray(params["router"], np.float32)
    t, _ = x.shape
    e = router.shape[-1]
    capacity = expert_capacity(t, e, capacity_factor)
    logits = x @ router
    exps = np.exp(logits - logits.max(-1, keepdims=True))
    probs = exps / exps.sum(-1, keepdims=True)
    chosen = probs.argmax(-1)
    counts = {i: 0 for i in range(e)}
    y = np.zeros_like(x)
    for ti in range(t):
        ei = int(chosen[ti])
        if counts[ei] >= capacity:
            continue
        counts[ei] += 1
        h = np.asarray(activation(
            jnp.asarray(x[ti] @ np.asarray(params["w_up"][ei])
                        + np.asarray(params["b_up"][ei]))
        ))
        out = h @ np.asarray(params["w_down"][ei]) + np.asarray(
            params["b_down"][ei]
        )
        y[ti] = probs[ti, ei] * out
    return y
