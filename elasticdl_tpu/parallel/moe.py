"""Mixture-of-experts over the ``ep`` mesh axis (expert parallelism).

Net-new beyond the reference (which has no expert axis — SURVEY.md §2.5;
``ep`` existed for embedding-row sharding only). The design is the
GShard/Switch static-shape formulation, which is what XLA wants:

* top-k routing (k=1 Switch, k=2 GShard) with a CAPACITY per expert
  (round(k * tokens * capacity_factor / E), expert_capacity()): every
  tensor keeps a static shape; choices over capacity are dropped from the expert path (their
  combine weight is 0, so over-capacity tokens pass through the
  residual only);
* dispatch and combine are one-hot einsums — no gather/scatter with
  dynamic shapes;
* expert weights are stacked [E, ...] and annotated over ``ep``
  (nn.with_partitioning); GSPMD inserts the all-to-alls when the einsums
  cross the token (dp-sharded) and expert (ep-sharded) dims;
* the load-balancing auxiliary loss is the standard fraction*prob dot
  (Switch Transformer eq. 4), returned to the caller to add to the task
  loss.

Two dispatch implementations share those semantics:

* :func:`moe_mlp_apply` — sharding-annotated einsums; GSPMD infers the
  collectives (the default; single-chip and small meshes);
* :func:`moe_mlp_apply_a2a` — EXPLICIT shard_map dispatch: tokens are
  sharded into (dp, fsdp, ep) groups, each group routes locally into a
  capacity-bounded [E, C, D] send buffer, one ``all_to_all`` over
  ``ep`` delivers each expert its ep receive buffers, the expert FFNs
  run on their [E/ep, ep*C, D] batch, and a reverse ``all_to_all``
  brings outputs home for the combine. Capacity is per GROUP
  (GShard's groups: round(k * T_group * cf / E)) rather than global-T,
  so the a2a cost is bounded at 2 * E * C * D * itemsize bytes per
  group regardless of routing skew. Drop-free configurations produce
  exactly the einsum path's outputs (the aux loss is assembled from
  pmean'd fraction/prob so it matches the global formula); under
  saturation the paths differ only in WHICH over-capacity choices drop
  (global queue vs per-group queues).
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.parallel.pipeline import shard_map


def top1_dispatch(router_logits, capacity):
    """Static-shape top-1 routing (Switch). See topk_dispatch."""
    return topk_dispatch(router_logits, capacity, k=1)


def topk_dispatch(router_logits, capacity, k=1):
    """Static-shape top-k routing (k=1 Switch, k=2 GShard).

    router_logits: [T, E]; capacity: int C per expert.
    Returns (dispatch [T, E, C] 0/1, combine [T, E, C] float, aux_loss
    scalar, stats dict). Each token routes to its k highest-probability
    experts; capacity queues fill primary choices first (all rank-0
    picks, then rank-1, ...), so under load the second choices are the
    ones dropped — GShard's policy. Combine weights follow GShard's
    g1/g2 normalization: each chosen expert's router prob is normalized
    over ALL k chosen experts BEFORE capacity drops, so a dropped choice
    contributes zero while the surviving choice keeps its pre-drop
    weight (e.g. p2/(p1+p2) — never amplified to 1.0). A token whose
    every choice was dropped has an all-zero combine row and rides the
    residual only.
    """
    t, e = router_logits.shape
    if not 1 <= k <= e:
        raise ValueError("top-k k=%d must be in [1, %d experts]" % (k, e))
    probs = jax.nn.softmax(router_logits, axis=-1)

    # lax.top_k guarantees k DISTINCT indices per token (an iterative
    # mask-and-argmax can pick an expert twice when the masked row
    # underflows to all zeros under a saturated router)
    _, topk_idx = jax.lax.top_k(probs, k)  # [T, k]
    onehots = [
        jax.nn.one_hot(topk_idx[:, r], e, dtype=probs.dtype)
        for r in range(k)
    ]

    # queue positions over (rank, arrival) order: rank-0 choices claim
    # capacity before any rank-1 choice
    flat = jnp.concatenate(onehots, axis=0)  # [k*T, E], rank-major
    position = jnp.cumsum(flat, axis=0) * flat - 1.0  # [k*T, E]
    within = (position >= 0) & (position < capacity)
    kept_flat = flat * within.astype(probs.dtype)
    pos_onehot = jax.nn.one_hot(
        jnp.clip(position, 0, capacity - 1).astype(jnp.int32),
        capacity,
        dtype=probs.dtype,
    )  # [k*T, E, C]
    dispatch_flat = kept_flat[..., None] * pos_onehot
    dispatch = dispatch_flat.reshape(k, t, e, capacity).sum(0)  # [T,E,C]

    # combine weights: k=1 keeps the raw chosen prob (Switch eq. 2 — the
    # magnitude is the router's gradient path); k>1 normalizes each
    # chosen prob over the CHOSEN set before capacity drops (GShard
    # g1/g2): a capacity-dropped primary zeroes its own weight but does
    # not inflate the secondary's.
    kept = kept_flat.reshape(k, t, e).sum(0)  # [T, E] post-drop
    chosen = flat.reshape(k, t, e).sum(0)     # [T, E] pre-drop
    if k == 1:
        combine = dispatch * (probs * kept)[..., None]
    else:
        denom = jnp.maximum(
            jnp.sum(probs * chosen, axis=-1, keepdims=True), 1e-9
        )
        combine = dispatch * (probs * kept / denom)[..., None]

    # Switch aux loss on the primary choice: E * sum_e frac_e * prob_e
    fraction = jnp.mean(onehots[0], axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(fraction * mean_prob)
    stats = {
        "dropped_fraction": 1.0 - jnp.sum(kept) / (k * t),
        "expert_fraction": fraction,
    }
    return dispatch, combine, aux_loss, stats


def expert_capacity(num_tokens, num_experts, capacity_factor):
    return max(1, int(num_tokens * capacity_factor / num_experts + 0.5))


def moe_mlp_apply(params, x, capacity_factor=1.25, activation=jax.nn.gelu,
                  router_top_k=1):
    """Functional MoE MLP: x [T, D] through E expert FFNs.

    params: {"router": [D, E], "w_up": [E, D, H], "b_up": [E, H],
             "w_down": [E, H, D], "b_down": [E, D]} — stacked expert
    leaves sharded over ep by the caller's annotations.
    Returns (y [T, D], aux_loss, stats).
    """
    t = x.shape[0]
    e = params["router"].shape[-1]
    capacity = expert_capacity(
        t * router_top_k, e, capacity_factor
    )
    logits = x @ params["router"]
    dispatch, combine, aux_loss, stats = topk_dispatch(
        logits, capacity, k=router_top_k
    )
    # [T,E,C] x [T,D] -> [E,C,D]: the all-to-all boundary under GSPMD
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    h = activation(
        jnp.einsum("ecd,edh->ech", expert_in, params["w_up"])
        + params["b_up"][:, None, :]
    )
    expert_out = (
        jnp.einsum("ech,ehd->ecd", h, params["w_down"])
        + params["b_down"][:, None, :]
    )
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y, aux_loss, stats


def moe_mlp_apply_a2a(params, x, mesh, capacity_factor=1.25,
                      activation=jax.nn.gelu, router_top_k=1):
    """Explicit expert-parallel dispatch (module docstring): shard_map
    over (dp, fsdp, ep) token groups with capacity-bounded all_to_all
    send/recv buffers over ``ep``.

    Same signature/result contract as :func:`moe_mlp_apply` plus the
    mesh. x [T, D] may arrive with any sharding — the shard_map in_spec
    reshards rows over (dp, fsdp, ep). Requires T % (dp*fsdp*ep) == 0
    and E % ep == 0.
    """
    dp = mesh.shape[MeshAxis.DP]
    fsdp = mesh.shape[MeshAxis.FSDP]
    ep = mesh.shape[MeshAxis.EP]
    shards = dp * fsdp * ep
    t, d = x.shape
    e = params["router"].shape[-1]
    if t % shards:
        raise ValueError(
            "a2a dispatch: %d tokens not divisible by dp*fsdp*ep=%d"
            % (t, shards)
        )
    if e % ep:
        raise ValueError(
            "a2a dispatch: %d experts not divisible by ep=%d" % (e, ep)
        )
    t_loc = t // shards
    e_loc = e // ep
    cap = expert_capacity(t_loc * router_top_k, e, capacity_factor)
    token_spec = P((MeshAxis.DP, MeshAxis.FSDP, MeshAxis.EP))
    param_specs = {
        "router": P(None, None),
        "w_up": P(MeshAxis.EP, None, None),
        "b_up": P(MeshAxis.EP, None),
        "w_down": P(MeshAxis.EP, None, None),
        "b_down": P(MeshAxis.EP, None),
    }
    token_axes = (MeshAxis.DP, MeshAxis.FSDP, MeshAxis.EP)

    def body(p, xl):
        logits = xl @ p["router"]
        dispatch, combine, _, stats = topk_dispatch(
            logits, cap, k=router_top_k
        )
        # capacity-bounded send buffers: [E, C, D] -> [ep(dst), E/ep, C, D]
        send = jnp.einsum("tec,td->ecd", dispatch, xl)
        send = send.reshape(ep, e_loc, cap, d)
        recv = jax.lax.all_to_all(
            send, MeshAxis.EP, split_axis=0, concat_axis=0
        )  # [ep(src), E/ep, C, D]
        # each local expert's batch: its C-slot buffer from every peer
        xin = recv.transpose(1, 0, 2, 3).reshape(e_loc, ep * cap, d)
        h = activation(
            jnp.einsum("egd,edh->egh", xin, p["w_up"])
            + p["b_up"][:, None, :]
        )
        out = (
            jnp.einsum("egh,ehd->egd", h, p["w_down"])
            + p["b_down"][:, None, :]
        )
        out = out.reshape(e_loc, ep, cap, d).transpose(1, 0, 2, 3)
        back = jax.lax.all_to_all(
            out, MeshAxis.EP, split_axis=0, concat_axis=0
        )  # [ep(expert group), E/ep, C, D] == local [E, C, D] order
        y = jnp.einsum("tec,ecd->td", combine,
                       back.reshape(e, cap, d))
        # aux loss assembled GLOBALLY (equal-size groups: the mean of
        # group means IS the global mean), so drop-free runs match the
        # einsum path's aux bit-for-bit up to reduction order
        probs = jax.nn.softmax(logits, axis=-1)
        fraction = jax.lax.pmean(
            stats["expert_fraction"], token_axes)
        mean_prob = jax.lax.pmean(jnp.mean(probs, axis=0), token_axes)
        aux = e * jnp.sum(fraction * mean_prob)
        out_stats = {
            "dropped_fraction": jax.lax.pmean(
                stats["dropped_fraction"], token_axes),
            "expert_fraction": fraction,
        }
        return y, aux, out_stats

    return shard_map(
        body,
        mesh,
        ({k: param_specs[k] for k in params}, token_spec),
        (token_spec, P(), {"dropped_fraction": P(),
                           "expert_fraction": P()}),
    )(dict(params), x)


def _router_gates(params, x, k):
    """Shared drop-free routing for the inference formulations: f32
    softmax router probs, top-k choice, and the combine-weight rule —
    raw chosen prob for k=1 (Switch), chosen-set-normalized for k>1
    (GShard g1/g2). Returns (gates [T, k] f32, top_i [T, k])."""
    probs = jax.nn.softmax(
        (x @ params["router"]).astype(jnp.float32), axis=-1
    )
    top_v, top_i = jax.lax.top_k(probs, k)
    if k == 1:
        gates = top_v
    else:
        gates = top_v / jnp.maximum(top_v.sum(-1, keepdims=True), 1e-9)
    return gates, top_i


def moe_mlp_infer(params, x, activation=jax.nn.gelu, router_top_k=1):
    """Drop-free top-k MoE MLP for DECODE/PREFILL: every token reaches
    all k chosen experts, no capacity queues, no [T, E, C] dispatch
    tensor (whose drop-free form is O(T^2 E) memory — unusable for a
    long-prompt prefill). Instead each expert runs densely over all T
    tokens and the combine mask zeroes non-chosen pairs: E-times the
    dense-MLP FLOPs, O(T*H) memory. The right trade exactly where this
    is used — decode steps (T = batch, tiny) and the one-time prefill
    pass — and the reason cached MoE decode is deterministic: a token's
    routing can't depend on which other tokens share its pass.

    Combine weights match topk_dispatch with no drops (shared
    _router_gates). Returns y [T, D]."""
    e = params["router"].shape[-1]
    gates, top_i = _router_gates(params, x, router_top_k)
    # f32 gates and accumulator, like moe_mlp_apply's combine — the
    # bit-parity of the two formulations (and so cached-vs-uncached
    # decode equality) must hold for bf16-configured models too
    y = jnp.zeros(x.shape, jnp.float32)
    for ei in range(e):  # static unroll; E is a model-size constant
        h = activation(
            x @ params["w_up"][ei] + params["b_up"][ei]
        )
        out = h @ params["w_down"][ei] + params["b_down"][ei]
        w_e = jnp.sum(jnp.where(top_i == ei, gates, 0.0), axis=-1)
        y = y + w_e[:, None] * out.astype(jnp.float32)
    return y


def moe_mlp_infer_gather(params, x, activation=jax.nn.gelu,
                         router_top_k=1):
    """Drop-free top-k MoE MLP via sort + ``jax.lax.ragged_dot``
    (MegaBlocks-style dropless dispatch): the (token, choice) pairs are
    sorted by expert, each expert multiplies exactly its own
    contiguous row group against its weights, and outputs scatter-add
    home weighted by the gates.

    Same routing/combine semantics as :func:`moe_mlp_infer` (raw
    chosen prob for k=1, chosen-set-normalized for k>1, f32
    accumulator) at k/E of its FLOPs — moe_mlp_infer runs EVERY expert
    densely over all T tokens (E x dense-MLP), this runs each token
    through only its k experts: the right prefill path once expert
    counts grow. Opt-in via the model knob ``moe_infer_impl='gather'``
    (dense stays the default: for tiny decode batches the sort/gather
    overhead outweighs the FLOP win, and the dense form is the
    long-standing determinism baseline)."""
    t, d = x.shape
    e = params["router"].shape[-1]
    k = router_top_k
    gates, top_i = _router_gates(params, x, k)
    flat_e = top_i.reshape(-1)                      # [T*k]
    flat_t = jnp.repeat(jnp.arange(t), k)           # token of each pair
    order = jnp.argsort(flat_e)                     # stable: ties keep
    sorted_e = flat_e[order]                        # token order
    sorted_t = flat_t[order]
    xs = x[sorted_t]                                # [T*k, D]
    group_sizes = jnp.bincount(flat_e, length=e).astype(jnp.int32)
    h = activation(
        jax.lax.ragged_dot(xs, params["w_up"], group_sizes)
        + params["b_up"][sorted_e]
    )
    out = (
        jax.lax.ragged_dot(h, params["w_down"], group_sizes)
        + params["b_down"][sorted_e]
    )
    gate_sorted = gates.reshape(-1)[order]
    return jnp.zeros((t, d), jnp.float32).at[sorted_t].add(
        gate_sorted[:, None] * out.astype(jnp.float32)
    )


def moe_reference(params, x, capacity_factor=1.25,
                  activation=jax.nn.gelu, router_top_k=1):
    """Oracle: loop over tokens/experts in plain numpy-style code (tests
    compare the einsum formulation against this). Mirrors topk_dispatch:
    rank-0 choices claim capacity before rank-1, combine weights are raw
    probs for k=1 and, for k>1, normalized over the CHOSEN (pre-drop)
    experts — GShard g1/g2, drops zero their own weight only."""
    import numpy as np

    x = np.asarray(x, np.float32)
    router = np.asarray(params["router"], np.float32)
    t, _ = x.shape
    e = router.shape[-1]
    k = router_top_k
    capacity = expert_capacity(t * k, e, capacity_factor)
    logits = x @ router
    exps = np.exp(logits - logits.max(-1, keepdims=True))
    probs = exps / exps.sum(-1, keepdims=True)
    order = np.argsort(-probs, axis=-1)[:, :k]  # [T, k]
    counts = {i: 0 for i in range(e)}
    kept = [[] for _ in range(t)]  # (expert, prob) kept per token
    for rank in range(k):
        for ti in range(t):
            ei = int(order[ti, rank])
            if counts[ei] >= capacity:
                continue
            counts[ei] += 1
            kept[ti].append((ei, probs[ti, ei]))

    def expert_out(ti, ei):
        h = np.asarray(activation(
            jnp.asarray(x[ti] @ np.asarray(params["w_up"][ei])
                        + np.asarray(params["b_up"][ei]))
        ))
        return h @ np.asarray(params["w_down"][ei]) + np.asarray(
            params["b_down"][ei]
        )

    y = np.zeros_like(x)
    for ti in range(t):
        if not kept[ti]:
            continue
        # g1/g2: normalize over the CHOSEN experts, drops excluded from
        # the numerator only
        denom = (
            sum(probs[ti, int(order[ti, r])] for r in range(k))
            if k > 1 else 1.0
        )
        for ei, p in kept[ti]:
            y[ti] += (p / denom) * expert_out(ti, ei)
    return y
