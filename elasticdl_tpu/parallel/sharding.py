"""Parameter/optimizer-state sharding rules.

The reference places whole dense variables on PS pods by name hash
(hash_utils.string_to_id — SURVEY.md §2.5). On TPU, dense parameters are
either replicated (pure DP) or sharded over the ``fsdp`` axis (ZeRO-style),
and the optimizer state follows the parameter sharding — XLA then inserts the
all-gathers/reduce-scatters that the reference did with explicit pull/push
RPCs.

Two mechanisms compose:
1. explicit logical annotations via ``flax.linen.with_partitioning`` in model
   code (used by the TP/SP model families), surfaced here through
   ``nn.get_partition_spec``;
2. an automatic rule for unannotated params: shard the largest axis that
   divides by the fsdp size, else replicate.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


def _auto_pspec(shape, fsdp_size, min_size_to_shard=2**14):
    """Shard the largest divisible axis over fsdp; tiny params replicate."""
    if fsdp_size <= 1 or not shape:
        return P()
    if int(np.prod(shape)) < min_size_to_shard:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[i] = MeshAxis.FSDP
            return P(*spec)
    return P()


def _embedding_pspec(shape, ep_size, fsdp_size, threshold_bytes, itemsize=4):
    """Embedding tables shard rows over (ep, fsdp) — the analogue of rows
    living `id % num_ps` across PS pods. Falls back to (ep,) then the auto
    fsdp rule when the vocab doesn't divide.

    Tables smaller than `threshold_bytes` use the plain auto rule instead —
    the reference's 2 MB cutoff below which an embedding stays a native
    (replicated) layer rather than moving to the PS
    (common/model_handler.py:98-102)."""
    if not shape:
        return P()
    if int(np.prod(shape)) * itemsize < threshold_bytes:
        return _auto_pspec(shape, fsdp_size)
    rest = (None,) * (len(shape) - 1)
    if ep_size * fsdp_size > 1 and shape[0] % (ep_size * fsdp_size) == 0:
        return P((MeshAxis.EP, MeshAxis.FSDP), *rest)
    if ep_size > 1 and shape[0] % ep_size == 0:
        return P(MeshAxis.EP, *rest)
    return _auto_pspec(shape, fsdp_size)


def collect_annotations(boxed_params):
    """{param path tuple -> PartitionSpec} for every flax ``Partitioned``
    leaf (``nn.with_partitioning`` annotations in model code — the TP
    model families). Paths are within the params tree."""
    import flax.linen as nn
    from flax import traverse_util

    try:
        from flax.core import unfreeze

        tree = unfreeze(boxed_params)
    except Exception:
        tree = dict(boxed_params)
    flat = traverse_util.flatten_dict(
        tree, is_leaf=lambda _, v: isinstance(v, nn.Partitioned)
    )
    return {
        tuple(str(k) for k in path): P(*leaf.names)
        for path, leaf in flat.items()
        if isinstance(leaf, nn.Partitioned)
    }


def infer_state_pspec(state_shapes, mesh, embedding_threshold_bytes=None,
                      annotations=None):
    """PartitionSpecs for a whole TrainState from its eval_shape pytree.

    Precedence per leaf:
    1. an explicit ``nn.with_partitioning`` annotation (`annotations`:
       {param path tuple -> PartitionSpec}, see collect_annotations) —
       matched by path SUFFIX so optax moments (mu/nu mirror their
       param's path under opt_state) co-shard with their param;
    2. embedding-table leaves (key path containing EMBEDDING_PARAM_NAME):
       row sharding over (ep, fsdp);
    3. the automatic fsdp rule.
    The suffix matching gives optimizer state the same placement the
    reference gets by keeping slot tables next to embedding shards on the
    same PS pod (ps/parameters.py create_slot_params).
    """
    from elasticdl_tpu.common.constants import (
        EMBEDDING_PARTITION_THRESHOLD_BYTES,
    )
    from elasticdl_tpu.embedding.layer import is_embedding_path

    if embedding_threshold_bytes is None:
        embedding_threshold_bytes = EMBEDDING_PARTITION_THRESHOLD_BYTES
    fsdp = mesh.shape[MeshAxis.FSDP]
    ep = mesh.shape[MeshAxis.EP]
    annotations = annotations or {}

    def annotated_spec(keys, shape):
        for param_path, spec in annotations.items():
            if (
                len(keys) >= len(param_path)
                and keys[-len(param_path):] == param_path
                and len(spec) <= len(shape)
            ):
                return spec
        return None

    def leaf_spec(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        keys = tuple(
            str(getattr(k, "key", getattr(k, "name", k))) for k in path
        )
        spec = annotated_spec(keys, shape)
        if spec is not None:
            return spec
        if is_embedding_path(path):
            itemsize = getattr(
                getattr(leaf, "dtype", None), "itemsize", 4
            )
            return _embedding_pspec(
                shape, ep, fsdp, embedding_threshold_bytes, itemsize
            )
        return _auto_pspec(shape, fsdp)

    return jax.tree_util.tree_map_with_path(leaf_spec, state_shapes)


def pspec_to_sharding(pspecs, mesh):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )
