"""Parameter/optimizer-state sharding rules.

The reference places whole dense variables on PS pods by name hash
(hash_utils.string_to_id — SURVEY.md §2.5). On TPU, dense parameters are
either replicated (pure DP) or sharded over the ``fsdp`` axis (ZeRO-style),
and the optimizer state follows the parameter sharding — XLA then inserts the
all-gathers/reduce-scatters that the reference did with explicit pull/push
RPCs.

Two mechanisms compose:
1. explicit logical annotations via ``flax.linen.with_partitioning`` in model
   code (used by the TP/SP model families), surfaced here through
   ``nn.get_partition_spec``;
2. an automatic rule for unannotated params: shard the largest axis that
   divides by the fsdp size, else replicate.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


def _auto_pspec(shape, fsdp_size, min_size_to_shard=2**14):
    """Shard the largest divisible axis over fsdp; tiny params replicate."""
    if fsdp_size <= 1 or not shape:
        return P()
    if int(np.prod(shape)) < min_size_to_shard:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[i] = MeshAxis.FSDP
            return P(*spec)
    return P()


def infer_state_pspec(state_shapes, mesh):
    """PartitionSpecs for a whole TrainState from its eval_shape pytree.

    Applies the automatic fsdp rule uniformly: optimizer moments (mu/nu)
    share their param's shape, so they land on the same spec — the
    co-sharding the reference gets by keeping slot tables next to embedding
    shards on the same PS pod (ps/parameters.py create_slot_params).
    """
    fsdp = mesh.shape[MeshAxis.FSDP]
    return jax.tree.map(
        lambda leaf: _auto_pspec(tuple(getattr(leaf, "shape", ())), fsdp),
        state_shapes,
    )


def pspec_to_sharding(pspecs, mesh):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )
