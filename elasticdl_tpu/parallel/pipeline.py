"""Pipeline parallelism over the ``pp`` mesh axis: GPipe-style
microbatch streaming built from shard_map + ppermute.

Net-new capability like ring attention (the reference has no pipeline
axis anywhere — SURVEY.md §2.5 "TP / PP / SP ... absent"); the design is
the standard TPU recipe (jax-ml scaling-book "pipelining"): each device
holds a contiguous chunk of the layer stack (leading dim of the stacked
params sharded over ``pp``), microbatches stream through the stages, and
the activation handoff between consecutive stages is a ``ppermute`` ring
step. The whole pipeline is a pure function, so jax AD derives the
backward pipeline (reverse ppermutes, transposed schedule) for free and
the Trainer's compiled step needs no changes.

Schedule: plain GPipe — M microbatches over P stages take M + P - 1
ticks; the (P-1)/(M+P-1) bubble fraction shrinks as M grows. Stages
compute garbage during fill/drain ticks (masked out at collection), the
same trade the canonical SPMD pipelines make: a no-op tick would still
have to execute the stage body under SPMD.
"""

import jax
import jax.numpy as jnp
try:
    from jax import shard_map as _shard_map  # jax >= 0.8

    def shard_map(f, mesh, in_specs, out_specs):
        # manual-collectives mode: the body mixes per-stage values with
        # replicated ones, which the varying-manual-axes checker rejects
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


def stage_size(mesh):
    return mesh.shape[MeshAxis.PP]


def pipeline_apply(stage_fn, stacked_params, x, mesh, num_microbatches,
                   batch_spec=None):
    """Run `x` through all pipeline stages in order.

    stage_fn(local_params, x_mb) -> y_mb: one STAGE's computation (the
        local chunk of the layer stack; same output shape as input).
    stacked_params: pytree whose every leaf has leading dim == total
        layers (or stages) divisible by pp, sharded P("pp") on dim 0 —
        each device receives its contiguous chunk.
    x: [batch, ...]; batch must divide into num_microbatches, and the
        per-device batch (after dp/fsdp sharding) too.
    batch_spec: PartitionSpec of x (default: batch over (dp, fsdp)).

    Returns y with x's shape/sharding (replicated over pp).
    """
    n_stages = stage_size(mesh)
    m = int(num_microbatches)
    if m < 1:
        raise ValueError("num_microbatches must be >= 1")
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] % n_stages != 0:
            raise ValueError(
                "stacked param leading dim %d not divisible by pp=%d"
                % (leaf.shape[0], n_stages)
            )
    if batch_spec is None:
        batch_spec = P((MeshAxis.DP, MeshAxis.FSDP))

    def body(params, xb):
        stage = jax.lax.axis_index(MeshAxis.PP)
        b_loc = xb.shape[0]
        if b_loc % m:
            raise ValueError(
                "per-device batch %d not divisible by %d microbatches"
                % (b_loc, m)
            )
        mbs = xb.reshape((m, b_loc // m) + xb.shape[1:])
        outs0 = jnp.zeros_like(mbs)
        act0 = jnp.zeros_like(mbs[0])
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            act, outs = carry
            # stage 0 ingests microbatch t (clipped: fill/drain ticks
            # compute garbage that never leaves the pipe)
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            inp = jnp.where(stage == 0, feed, act)
            out = stage_fn(params, inp)
            # the LAST stage banks microbatch t-(P-1)'s result
            idx = t - (n_stages - 1)
            idx_c = jnp.clip(idx, 0, m - 1)
            current = jax.lax.dynamic_index_in_dim(
                outs, idx_c, 0, keepdims=False
            )
            banked = jnp.where(idx >= 0, out, current)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, banked, idx_c, 0
            )
            act = jax.lax.ppermute(out, MeshAxis.PP, fwd)
            return (act, outs), None

        (act, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(m + n_stages - 1)
        )
        # broadcast the last stage's banked outputs to every pp rank
        mask = (stage == n_stages - 1).astype(outs.dtype)
        outs = jax.lax.psum(outs * mask, MeshAxis.PP)
        return outs.reshape(xb.shape)

    return shard_map(
        body,
        mesh,
        (P(MeshAxis.PP), batch_spec),
        batch_spec,
    )(stacked_params, x)


def sequential_apply(stage_fn, stacked_params, x, n_stages):
    """Oracle: the same stages run one after another without the mesh —
    what pipeline_apply must equal numerically (tests + the pp=1 path).
    """
    chunk = jax.tree.leaves(stacked_params)[0].shape[0] // n_stages

    def one(i, xv):
        local = jax.tree.map(
            lambda l: jax.lax.dynamic_slice_in_dim(l, i * chunk, chunk, 0),
            stacked_params,
        )
        return stage_fn(local, xv)

    for i in range(n_stages):
        x = one(i, x)
    return x
