"""Pipeline parallelism over the ``pp`` mesh axis: GPipe-style
microbatch streaming built from shard_map + ppermute.

Net-new capability like ring attention (the reference has no pipeline
axis anywhere — SURVEY.md §2.5 "TP / PP / SP ... absent"); the design is
the standard TPU recipe (jax-ml scaling-book "pipelining"): each device
holds a contiguous chunk of the layer stack (leading dim of the stacked
params sharded over ``pp``), microbatches stream through the stages, and
the activation handoff between consecutive stages is a ``ppermute`` ring
step. The whole pipeline is a pure function, so jax AD derives the
backward pipeline (reverse ppermutes, transposed schedule) for free and
the Trainer's compiled step needs no changes.

Two schedules:

- ``gpipe`` (default): M microbatches over P stages take M + P - 1
  ticks of one full stage body each; bubble fraction (P-1)/(M+P-1).
- ``interleaved``: the Megatron-style circular schedule. Each device
  holds ``v`` NON-contiguous chunks of 1/(vP) of the layers (virtual
  stage s runs on device s mod P) and microbatches are injected in
  groups of P, so the pipe runs vM + P - 1 ticks of 1/v-size bodies —
  total stage-work (M + (P-1)/v) vs GPipe's (M + P - 1): the fill/drain
  bubble shrinks by the interleave factor (27% -> 16% at M=8, P=4,
  v=2). Requires M % P == 0 and layers % (vP) == 0, and the stacked
  params in ring-ordered ("interleaved") layout — device-major rows so
  each device's local chunk rows are exactly its v virtual stages; use
  :func:`interleave_layers` / :func:`deinterleave_layers` to convert a
  semantically-ordered stack (e.g. a checkpoint) to/from this layout.

Both schedules compute garbage during fill/drain ticks (masked out at
collection), the same trade the canonical SPMD pipelines make: a no-op
tick would still have to execute the stage body under SPMD.

Activation staging: ``remat=True`` wraps the per-tick body in
``jax.checkpoint`` — the AD-derived backward pipeline then stores ONLY
the inter-stage activation per tick (one microbatch-sized tensor) and
recomputes stage interiors, the per-microbatch staging 1F1B exists for.
The backward schedule itself is jax AD's transpose of the forward scan:
reverse ppermutes, ticks reversed — fwd+bwd totals 2(M+P-1) stage-times
for gpipe, exactly textbook non-interleaved 1F1B's critical path (1F1B
re-orders those same ticks to bound in-flight activations, which remat
achieves here), and 2(M + (P-1)/v) for the interleaved schedule, which
is where the real bubble shrink lives.
"""

import jax
import jax.numpy as jnp
try:
    from jax import shard_map as _shard_map  # jax >= 0.8

    def shard_map(f, mesh, in_specs, out_specs):
        # manual-collectives mode: the body mixes per-stage values with
        # replicated ones, which the varying-manual-axes checker rejects
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


def stage_size(mesh):
    return mesh.shape[MeshAxis.PP]


def _ring_perm(n_layers, n_stages, interleave):
    """Row permutation: semantic layer order -> interleaved layout.

    Virtual stage s (ring order, s in [0, v*P)) covers semantic layers
    [s*cl, (s+1)*cl), cl = L/(vP), and runs on device s mod P, local
    slot s // P. The interleaved layout is device-major: device d's
    contiguous block holds its slots j=0..v-1 = virtual stages j*P+d.
    """
    if n_layers % (n_stages * interleave) != 0:
        raise ValueError(
            "layer stack of %d rows not divisible by pp=%d x "
            "interleave=%d" % (n_layers, n_stages, interleave)
        )
    cl = n_layers // (n_stages * interleave)
    return [
        (j * n_stages + d) * cl + k
        for d in range(n_stages)
        for j in range(interleave)
        for k in range(cl)
    ]


def interleave_layers(stacked, n_stages, interleave):
    """Convert a semantically-ordered layer stack (leading dim = L) to
    the interleaved-schedule layout (see module docstring). Use on
    checkpoints trained with the gpipe schedule (or torn down via
    :func:`deinterleave_layers`) before applying schedule="interleaved".
    """
    import numpy as np

    def one(leaf):
        perm = np.asarray(
            _ring_perm(leaf.shape[0], n_stages, interleave))
        return jnp.take(leaf, perm, axis=0)

    return jax.tree.map(one, stacked)


def deinterleave_layers(stacked, n_stages, interleave):
    """Inverse of :func:`interleave_layers` (back to semantic order)."""
    import numpy as np

    def one(leaf):
        perm = np.asarray(
            _ring_perm(leaf.shape[0], n_stages, interleave))
        inv = np.empty_like(perm)
        inv[perm] = np.arange(len(perm))
        return jnp.take(leaf, inv, axis=0)

    return jax.tree.map(one, stacked)


def convert_params_to_interleaved(params, n_stages, interleave,
                                  like=None, stacked_key_prefix="blk_"):
    """Convert a gpipe-trained param dict (e.g. a checkpoint restored
    into a TrainState) to the interleaved-schedule layout: leaves whose
    top-level key starts with ``stacked_key_prefix`` get
    :func:`interleave_layers`; everything else passes through. When
    ``like`` (a same-structure params tree, e.g. the interleaved
    trainer's freshly-initialized state.params) is given, every leaf is
    re-placed onto its sharding via a host round-trip — the jnp.take
    gather de-shards, and the fresh buffers also keep a later donating
    train_step on the SOURCE state from tearing shared leaves out of
    the converted tree."""
    import numpy as np

    conv = {
        k: (interleave_layers(val, n_stages, interleave)
            if k.startswith(stacked_key_prefix) else val)
        for k, val in dict(params).items()
    }
    if like is not None:
        conv = jax.tree.map(
            lambda new, old: jax.device_put(
                np.asarray(new), old.sharding),
            conv, dict(like),
        )
    if isinstance(params, dict):
        return conv
    return type(params)(conv)


def pipeline_apply(stage_fn, stacked_params, x, mesh, num_microbatches,
                   batch_spec=None, schedule="gpipe", interleave=2,
                   remat=False):
    """Run `x` through all pipeline stages in order.

    stage_fn(local_params, x_mb) -> y_mb: one STAGE's computation (its
        chunk of the layer stack — for the interleaved schedule it is
        called per 1/(vP)-size chunk; same output shape as input).
    stacked_params: pytree whose every leaf has leading dim == total
        layers (or stages) divisible by pp, sharded P("pp") on dim 0 —
        each device receives its contiguous chunk. For
        schedule="interleaved" the rows must be in interleaved layout
        (:func:`interleave_layers`; fresh random inits need no
        conversion — row order is a labeling).
    x: [batch, ...]; batch must divide into num_microbatches, and the
        per-device batch (after dp/fsdp sharding) too.
    batch_spec: PartitionSpec of x (default: batch over (dp, fsdp)).
    schedule: "gpipe" | "interleaved" (module docstring).
    interleave: v, virtual chunks per device (interleaved schedule).
    remat: checkpoint the per-tick body — backward stores only the
        inter-stage activations and recomputes stage interiors.

    Returns y with x's shape/sharding (replicated over pp).
    """
    n_stages = stage_size(mesh)
    m = int(num_microbatches)
    if m < 1:
        raise ValueError("num_microbatches must be >= 1")
    if schedule not in ("gpipe", "interleaved"):
        raise ValueError("unknown schedule %r" % (schedule,))
    v = int(interleave) if schedule == "interleaved" else 1
    if v < 1:
        raise ValueError("interleave must be >= 1")
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] % (n_stages * v) != 0:
            raise ValueError(
                "stacked param leading dim %d not divisible by "
                "pp=%d x interleave=%d"
                % (leaf.shape[0], n_stages, v)
            )
    if schedule == "interleaved" and m % n_stages != 0:
        raise ValueError(
            "interleaved schedule injects microbatches in groups of "
            "pp: num_microbatches=%d %% pp=%d != 0 (use gpipe or pad)"
            % (m, n_stages)
        )
    if batch_spec is None:
        batch_spec = P((MeshAxis.DP, MeshAxis.FSDP))
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    # One body serves both schedules: v=1 reduces the circular
    # schedule exactly to GPipe (slot always 0, injection every tick,
    # banking at t - (P-1)) — proven by the (pp,m,v)=(2,2,1) oracle
    # test and the schedule-parity dryrun sub-run.
    return _interleaved_apply(
        stage_fn, stacked_params, x, mesh, m, v, batch_spec)


def _interleaved_apply(stage_fn, stacked_params, x, mesh, m, v,
                       batch_spec):
    """Circular schedule, both flavors: vM + P - 1 ticks of 1/v-size
    chunk bodies (v=1 IS GPipe). Device d at tick t runs its local slot
    j = ((t - d) // P) mod v (= virtual stage jP + d); device 0 injects
    fresh microbatches in groups of P during its slot-0 phases; device
    P-1 (owner of the final virtual stage vP-1) banks completed
    microbatches; every tick ends in one forward ring ppermute — the
    slot formula is exactly consistent with that single hop (virtual
    stage s's output arrives where s+1 lives, including the v-pass
    wrap-around)."""
    n_stages = stage_size(mesh)

    def body(params, xb):
        stage = jax.lax.axis_index(MeshAxis.PP)
        b_loc = xb.shape[0]
        if b_loc % m:
            raise ValueError(
                "per-device batch %d not divisible by %d microbatches"
                % (b_loc, m)
            )
        mbs = xb.reshape((m, b_loc // m) + xb.shape[1:])
        outs0 = jnp.zeros_like(mbs)
        act0 = jnp.zeros_like(mbs[0])
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def slot_params(j):
            def slc(leaf):
                rows = leaf.shape[0] // v
                return jax.lax.dynamic_slice_in_dim(
                    leaf, j * rows, rows, 0)

            return jax.tree.map(slc, params)

        def tick(carry, t):
            act, outs = carry
            # local slot: floor-divide keeps pre-arrival ticks (t < d)
            # harmless — the chunk computes garbage never banked
            j = jnp.mod((t - stage) // n_stages, v)
            # injection: device 0, slot-0 phase, next group not done
            m_idx = t % n_stages + n_stages * (t // (v * n_stages))
            inject = ((stage == 0)
                      & ((t // n_stages) % v == 0)
                      & (m_idx < m))
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(m_idx, 0, m - 1), 0, keepdims=False
            )
            inp = jnp.where(inject, feed, act)
            out = stage_fn(slot_params(j), inp)
            # banking: mb bm finishes virtual stage vP-1 on device P-1
            # at t = (bm % P) + P(v-1) + (P-1) + vP*(bm // P)
            tp = t - (n_stages * (v - 1) + n_stages - 1)
            q = tp % (v * n_stages)
            bm = (tp // (v * n_stages)) * n_stages + q
            bank = ((stage == n_stages - 1) & (tp >= 0)
                    & (q < n_stages) & (bm < m))
            idx_c = jnp.clip(bm, 0, m - 1)
            current = jax.lax.dynamic_index_in_dim(
                outs, idx_c, 0, keepdims=False
            )
            banked = jnp.where(bank, out, current)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, banked, idx_c, 0
            )
            act = jax.lax.ppermute(out, MeshAxis.PP, fwd)
            return (act, outs), None

        (act, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(v * m + n_stages - 1)
        )
        mask = (stage == n_stages - 1).astype(outs.dtype)
        outs = jax.lax.psum(outs * mask, MeshAxis.PP)
        return outs.reshape(xb.shape)

    return shard_map(
        body,
        mesh,
        (P(MeshAxis.PP), batch_spec),
        batch_spec,
    )(stacked_params, x)


def sequential_apply(stage_fn, stacked_params, x, n_stages):
    """Oracle: the same stages run one after another without the mesh —
    what pipeline_apply must equal numerically (tests + the pp=1 path).
    """
    chunk = jax.tree.leaves(stacked_params)[0].shape[0] // n_stages

    def one(i, xv):
        local = jax.tree.map(
            lambda l: jax.lax.dynamic_slice_in_dim(l, i * chunk, chunk, 0),
            stacked_params,
        )
        return stage_fn(local, xv)

    for i in range(n_stages):
        x = one(i, x)
    return x
