"""Host-level collective communicator (reference
collective_ops/communicator.py:37-144).

The reference wrapped FTLib (gossip membership + torch.distributed) for
its allreduce strategy; on TPU the *gradient* collectives are XLA psums
inside the compiled step (parallel/spmd.py), so this wrapper's remit
shrinks to what it was actually load-bearing for: control-plane
collectives between worker processes (parameter re-broadcast after a
membership change, barriers, liveness consensus) — now carried by
jax.distributed / multihost_utils over ICI/DCN.

Contract parity with the reference:
* allreduce(MEAN)/broadcast/barrier return (status, data) with
  SUCCEEDED/FAILED statuses;
* with no backend (single process — the reference's "FTLib not
  installed" laptop path, communicator.py:32-34, 91-93) every op
  SUCCEEDS as identity, which is what lets the robust-retry control
  flow be tested without a cluster
  (worker_allreduce_strategy_test.py:59-80)."""

import numpy as np

import jax

from elasticdl_tpu.common.log_utils import default_logger as logger


class CollectiveCommunicatorStatus(object):
    SUCCEEDED = "succeeded"
    FAILED = "failed"


_SUPPORTED_REDUCE_OPS = ("MEAN", "SUM")


class CollectiveCommunicator(object):
    def __init__(self, use_backend=None):
        """use_backend: force the multihost backend on/off; default =
        on iff jax.distributed is initialized with >1 processes."""
        if use_backend is None:
            use_backend = jax.process_count() > 1
        self._use_backend = use_backend
        if not use_backend:
            logger.warning(
                "CollectiveCommunicator running without a multi-process "
                "backend; all ops succeed as identity (reference "
                "communicator.py:32-34)"
            )

    def has_backend(self):
        return self._use_backend

    def allreduce(self, data, op="MEAN"):
        if op not in _SUPPORTED_REDUCE_OPS:
            logger.error("Unsupported reduce op %s", op)
            return CollectiveCommunicatorStatus.FAILED, data
        if data is None:
            logger.error("Data is required for allreduce")
            return CollectiveCommunicatorStatus.FAILED, data
        if not self._use_backend:
            return CollectiveCommunicatorStatus.SUCCEEDED, data
        try:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(
                np.asarray(data)
            )
            if op == "MEAN":
                result = np.mean(gathered, axis=0)
            else:
                result = np.sum(gathered, axis=0)
            return CollectiveCommunicatorStatus.SUCCEEDED, result
        except Exception as e:
            logger.warning("allreduce failed: %s", e)
            return CollectiveCommunicatorStatus.FAILED, data

    def broadcast(self, data, root_rank=0):
        """Root's data wins (reference broadcast; rank-0 re-broadcasts
        params after membership change, worker.py:794-820). `root_rank`
        is a process index — IP addressing from the reference's FTLib
        surface has no jax.distributed equivalent and is rejected
        loudly, not swallowed."""
        root = int(root_rank)  # raises for non-rank input by design
        if not self._use_backend:
            return CollectiveCommunicatorStatus.SUCCEEDED, data
        try:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(
                np.asarray(data)
            )
            return CollectiveCommunicatorStatus.SUCCEEDED, gathered[root]
        except Exception as e:
            logger.warning("broadcast failed: %s", e)
            return CollectiveCommunicatorStatus.FAILED, data

    def barrier(self, tag="barrier"):
        if not self._use_backend:
            return CollectiveCommunicatorStatus.SUCCEEDED
        try:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)
            return CollectiveCommunicatorStatus.SUCCEEDED
        except Exception as e:
            logger.warning("barrier failed: %s", e)
            return CollectiveCommunicatorStatus.FAILED
