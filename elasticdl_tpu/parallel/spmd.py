"""SPMD multi-host execution: lockstep elastic training over a global mesh.

This is the TPU-native answer to the reference's PS data plane. In the
reference, workers progress independently and exchange gradients with PS pods
over gRPC (async or grads_to_wait sync — ps/servicer.py:120-227). On TPU,
every host participates in ONE jit-compiled step over a global
``jax.sharding.Mesh``; gradient aggregation is the psum XLA inserts for the
batch-sharded loss. That imposes lockstep: all hosts must invoke the same
compiled computation the same number of times.

Lockstep + elastic task dispatch are reconciled here:

* each host pulls record-range tasks from the master independently (dynamic
  sharding preserved — the worker count can change between jobs, and task
  re-queue covers host loss),
* every round, hosts that have a local batch contribute it; hosts that are
  starved contribute a ZERO-WEIGHT batch (the global weighted-mean loss
  ignores them exactly — sum(ce*w)/sum(w) reductions are global),
* the loop ends only when ALL hosts are done, agreed via a host-level
  allgather of done-flags (jax.experimental.multihost_utils), so no host
  abandons a collective.

Single-process (1 host, N local devices) degenerates to device_put with the
batch sharding — same code path the tests exercise on the 8-device CPU mesh.
"""

import jax
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.parallel import mesh as mesh_lib


def initialize_distributed(coordinator_addr=None, num_processes=None,
                           process_id=None, platform=None):
    """jax.distributed bootstrap (multi-host). On CPU test rigs, selects the
    gloo collectives implementation. No-op when single-process args given."""
    if num_processes is None or num_processes <= 1:
        return
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_addr,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        process_id, num_processes, len(jax.devices()),
    )
    # Create the global communicator clique NOW, while every host sits
    # at the same program point. The first collective's address exchange
    # has a hard 30 s deadline inside XLA's rendezvous (gloo on CPU
    # rigs: GetKeyValue DEADLINE_EXCEEDED), and deferring it to the
    # first train step puts a variable-length jit compile between init
    # and rendezvous — under machine load that skew exceeds the
    # deadline. Here the inter-host skew is process-start noise only.
    # Failure is non-fatal: the training step's own failure handling
    # (task re-queue + host-loss recovery) owns that path.
    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("edl_spmd_init_warmup")
        logger.info("communicator warm-up barrier passed")
    except Exception as e:  # noqa: BLE001
        logger.warning("communicator warm-up barrier failed: %s", e)


class SPMDContext(object):
    """Global-batch assembly + host-level agreement primitives."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._batch_sharding = mesh_lib.batch_sharding(mesh)
        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        self._proc_rows_cache = {}
        self._batch_partitions = None

    @property
    def is_multiprocess(self):
        return self.num_processes > 1

    def local_rows(self, global_batch_size):
        """This host's global row positions for the batch sharding
        (cached via rows_positions)."""
        return self.rows_positions(global_batch_size)[self.process_index]

    def assemble(self, local_pytree):
        """Host-local numpy (leading dim = per-host batch) -> global sharded
        jax.Arrays (leading dim = per-host batch * num_processes)."""
        if not self.is_multiprocess:
            return jax.device_put(local_pytree, self._batch_sharding)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                self._batch_sharding, np.asarray(x)
            ),
            local_pytree,
        )

    def allgather(self, local_np):
        """Host-level allgather: local [s...] -> [num_processes, s...],
        identical on every host (deterministic process order)."""
        if not self.is_multiprocess:
            return np.asarray(local_np)[None]
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.asarray(local_np))
        )

    def rows_positions(self, global_len):
        """{process_index: global row positions} for a length-global_len
        dim-0 batch-sharded array, each in the host-local row order of
        assemble()/make_array_from_process_local_data. Cached per length
        (the mapping is static for a given mesh)."""
        cached = self._proc_rows_cache.get(global_len)
        if cached is None:
            cached = process_row_positions(
                self._batch_sharding, global_len
            )
            self._proc_rows_cache[global_len] = cached
        return cached

    @property
    def batch_partitions(self):
        """Number of distinct dim-0 partitions of the batch sharding (the
        divisor global batch-like lengths must honor)."""
        if self._batch_partitions is None:
            spec = self._batch_sharding.spec
            axes = spec[0] if spec else None
            if axes is None:
                self._batch_partitions = 1
            else:
                if isinstance(axes, str):
                    axes = (axes,)
                size = 1
                for a in axes:
                    size *= self.mesh.shape[a]
                self._batch_partitions = size
        return self._batch_partitions

    def all_done(self, local_done):
        """True iff every host reports done (host-level consensus)."""
        if not self.is_multiprocess:
            return bool(local_done)
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.array([1 if local_done else 0], np.int32)
        )
        return bool(np.asarray(flags).sum() == self.num_processes)

    def broadcast_scalar(self, value, root=0):
        if not self.is_multiprocess:
            return value
        from jax.experimental import multihost_utils

        arr = multihost_utils.broadcast_one_to_all(
            np.asarray(value), is_source=jax.process_index() == root
        )
        return np.asarray(arr)


def local_row_positions(batch_sharding, global_batch_size):
    """Global row indices owned by this host's devices, in the order
    make_array_from_process_local_data consumed the host-local rows.

    Used to slice a replicated global output back down to the rows this
    host contributed (robust against device-mesh reordering on real ICI
    topologies, where host rows need not be one contiguous block)."""
    return process_row_positions(batch_sharding, global_batch_size)[
        jax.process_index()
    ]


def process_row_positions(batch_sharding, global_len):
    """{process_index: global row indices}, each in that host's local row
    order (distinct index blocks sorted by start — the order both
    make_array_from_process_local_data consumes host-local rows and
    addressable shards enumerate). Replicated devices (e.g. tp) map to
    the same block; duplicates are dropped."""
    index_map = batch_sharding.devices_indices_map((global_len,))
    per_proc = {}
    for dev, idx in index_map.items():
        sl = idx[0]
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else global_len
        per_proc.setdefault(dev.process_index, {})[start] = stop
    out = {}
    for p, blocks in per_proc.items():
        segs = [np.arange(s, e) for s, e in sorted(blocks.items())]
        out[p] = np.concatenate(segs) if segs else np.arange(0)
    return out


# Round modes, in priority order (lower wins the consensus):
MODE_EVAL = 0     # at least one host holds an evaluation batch
MODE_TRAIN = 1    # at least one host holds a training batch
MODE_IDLE = 2     # nobody has data now, but the master said WAIT
MODE_STOP = 3     # every host got "no more tasks"


class ElasticSPMDLoop(object):
    """The lockstep state machine reconciling SPMD collectives with elastic
    task dispatch.

    Every round, each host polls its local sources and proposes a mode;
    the global mode is the MINIMUM over hosts (allgathered), i.e. highest
    priority wins: EVAL > TRAIN > IDLE > STOP. Then EVERY host executes that
    round's compiled program — with a zero-weight padding batch if it has no
    real data — so no host ever abandons a collective. Eval-before-train
    priority mirrors the reference worker, which gives evaluation a chance
    before every training minibatch (worker.py:1041-1047).

    poll_eval()  -> eval item or None
    poll_train() -> ("item", batch) | ("wait",) | ("done",)
    train_step(item_or_None), eval_step(item_or_None): must submit the same
    compiled computation regardless of padding.
    """

    def __init__(self, ctx, poll_train=None, poll_eval=None,
                 train_step=None, eval_step=None, idle_sleep_secs=0.2):
        self.ctx = ctx
        self.poll_train = poll_train
        self.poll_eval = poll_eval
        self.train_step = train_step
        self.eval_step = eval_step
        self.idle_sleep_secs = idle_sleep_secs

    def _gather_mode(self, local_mode):
        if not self.ctx.is_multiprocess:
            return local_mode
        from jax.experimental import multihost_utils

        modes = multihost_utils.process_allgather(
            np.array([local_mode], np.int32)
        )
        return int(np.asarray(modes).min())

    def run(self):
        import time

        pending_train = None
        pending_eval = None
        train_done = self.poll_train is None
        rounds = {MODE_TRAIN: 0, MODE_EVAL: 0}
        while True:
            if pending_eval is None and self.poll_eval is not None:
                pending_eval = self.poll_eval()
            if (
                pending_train is None
                and not train_done
            ):
                kind = self.poll_train()
                if kind[0] == "item":
                    pending_train = kind[1]
                elif kind[0] == "done":
                    train_done = True
                # "wait": leave pending_train None this round

            if pending_eval is not None:
                local_mode = MODE_EVAL
            elif pending_train is not None:
                local_mode = MODE_TRAIN
            elif not train_done:
                local_mode = MODE_IDLE
            else:
                local_mode = MODE_STOP

            mode = self._gather_mode(local_mode)
            if mode == MODE_STOP:
                break
            if mode == MODE_IDLE:
                time.sleep(self.idle_sleep_secs)
                continue
            if mode == MODE_EVAL:
                item, pending_eval = pending_eval, None
                if item is not None:
                    rounds[MODE_EVAL] += 1
                self.eval_step(item)
            else:
                item, pending_train = pending_train, None
                if item is not None:
                    rounds[MODE_TRAIN] += 1
                self.train_step(item)
        return rounds
