"""Sequence/context parallelism over the `sp` mesh axis: ring attention
and Ulysses (all-to-all head/sequence transpose).

The reference has no long-context story (SURVEY.md §5: no ring attention,
no sequence parallelism anywhere in the tree); this module is the
TPU-native design the rebuild reserves the `sp` axis for. Two schemes,
both inside `jit` via `shard_map` and differentiable (ppermute and
all_to_all have transpose rules), so the same code paths train:

* **Ring** (`ring_attention`): the sequence axis of q/k/v is sharded
  over `sp`; key/value shards rotate around the ring with
  `jax.lax.ppermute` (ICI neighbor exchange) while partial softmax
  results merge online — the full sequence never materializes anywhere.
  Works for any head count; communication is 2(sp-1) neighbor hops of
  the local kv shard per attention.
* **Ulysses** (`ulysses_attention`): one `all_to_all` re-shards heads
  against sequence so each device holds heads/sp *full-sequence* heads,
  runs the local flash/blockwise kernel over the whole sequence, and
  transposes back. Requires heads % sp == 0; communication is 4
  all-to-alls of the activations per attention, and the inner kernel
  sees the full sequence (better MXU tiling than sp-chunked ring steps).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.parallel.pipeline import shard_map
from elasticdl_tpu.ops.attention import (
    NEG_INF as _NEG_INF,
    attention_backward_lse,
    attention_forward_lse,
    blockwise_attention,
    flash_attention,
    jax_flash_attention,
    lse_merge,
    resolve_block,
    segments_float0,
)


def _win_live(shard_len, window, size):
    """Number of statically-reachable windowed-rotation branches:
    offset r is live iff its closest pair (q=first row, k=last key)
    is inside the window, r*shard_len - (shard_len-1) < window. All
    inputs are static python ints at trace time."""
    return min(size, (window + shard_len - 2) // shard_len + 1)


def _win_offsets(shard_len, window, size, causal):
    """The static branch-offset list matching _win_case's indexing:
    causal -> [0..live), non-causal -> [-(live-1)..live). The skip
    branch goes LAST; fwd and bwd build their switches from this one
    list so they cannot desynchronize."""
    live = _win_live(shard_len, window, size)
    if causal:
        return list(range(live))
    return list(range(-(live - 1), live))


def _win_case(src, my, shard_len, window, size, causal):
    """Switch index for a windowed rotation, shared by the forward and
    backward rings so the skip invariant cannot desynchronize
    gradients from outputs (cf. _ring_case).

    Causal: shard offset r = my - src selects branch r; r < 0
    (strictly newer) and band-empty offsets map to the skip branch at
    index _win_live(...).
    Non-causal: signed offsets in (-live, live) select branch
    off + live - 1 (the two-sided band at |off| shards); |off| outside
    the band maps to the skip branch at index 2*live - 1."""
    off = my - src
    live = _win_live(shard_len, window, size)
    if causal:
        return jnp.where(
            (off < 0) | (off * shard_len - (shard_len - 1) >= window),
            live, off,
        ).astype(jnp.int32)
    empty = jnp.abs(off) * shard_len - (shard_len - 1) >= window
    return jnp.where(
        empty, 2 * live - 1, off + live - 1
    ).astype(jnp.int32)


def _ring_case(src, my):
    """Causal visibility of kv shard `src` from query shard `my` with
    equal shard lengths: 0 = fully visible (src strictly older), 1 =
    diagonal (local causal mask), 2 = fully masked (src strictly newer —
    skipped, no compute). This is why the per-shard kernels never need a
    dynamic position offset: the offsets only matter on the diagonal,
    where they cancel."""
    return jnp.where(src == my, 1, jnp.where(src < my, 0, 2)).astype(
        jnp.int32
    )


def _ring_fwd_impl(q, k, v, seg, axis_name, causal, scale, block_q,
                   block_k, window):
    """Ring forward: per rotation, the LOCAL flash kernel produces a
    normalized partial (o_i, lse_i) for the currently-held kv shard,
    merged online via lse_merge; kv shards rotate with ppermute. The full
    sequence never materializes. Returns (o [q.dtype], lse [f32]).

    `seg` (packed sequences): the LOCAL [b, lq] segment ids. The k-side
    ids travel WITH their kv shard around the ring, and each rotation
    masks with the rectangular (local q ids, held k ids) pair; a
    rotation whose shard shares no segment with a query row yields a
    (0, -inf) partial that the merge ignores (attention_forward_lse
    guarantees that sentinel form)."""
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, lq, _ = q.shape
    perm = [((j + 1) % size, j) for j in range(size)]
    f32 = jnp.float32
    has_seg = seg is not None

    def _pair(kseg_cur):
        return (seg, kseg_cur) if has_seg else None

    def full(qq, kk, vv, kseg_cur):
        o, lse = attention_forward_lse(qq, kk, vv, causal=False,
                                       scale=scale, block_q=block_q,
                                       block_k=block_k,
                                       segments=_pair(kseg_cur))
        return o.astype(f32), lse

    def diag(qq, kk, vv, kseg_cur):
        o, lse = attention_forward_lse(qq, kk, vv, causal=True,
                                       scale=scale, block_q=block_q,
                                       block_k=block_k,
                                       segments=_pair(kseg_cur))
        return o.astype(f32), lse

    def skip(qq, kk, vv, kseg_cur):
        return (jnp.zeros(qq.shape, f32),
                jnp.full((b, h, lq), _NEG_INF, f32))

    # windowed: one statically-compiled branch per shard offset — the
    # global window mask of a rotation IS the local window mask with q
    # positions shifted by offset*shard_len (causal: offsets >= 0,
    # causality auto-holds off-diagonal and the symmetric lower bound
    # is auto-true; non-causal: signed offsets give the two-sided
    # band). `size` is a static int (psum of a literal), so the branch
    # list is a python list; only the selector is traced.
    def _win_branch(r):
        def br(qq, kk, vv, kseg_cur):
            o, lse = attention_forward_lse(
                qq, kk, vv, causal=(causal and r == 0), scale=scale,
                block_q=block_q, block_k=block_k,
                segments=_pair(kseg_cur), pos_offset=r * lq,
                window=window,
            )
            return o.astype(f32), lse

        return br

    def _win_branches():
        return [
            _win_branch(off)
            for off in _win_offsets(lq, window, size, causal)
        ] + [skip]

    def merge(o, lse, k_cur, v_cur, kseg_cur, i):
        # after i rotations device `my` holds the shard born on my+i
        if window is not None:
            o_i, lse_i = jax.lax.switch(
                _win_case((my + i) % size, my, lq, window, size,
                          causal),
                _win_branches(),
                q, k_cur, v_cur, kseg_cur,
            )
        elif causal:
            o_i, lse_i = jax.lax.switch(
                _ring_case((my + i) % size, my), (full, diag, skip),
                q, k_cur, v_cur, kseg_cur,
            )
        else:
            o_i, lse_i = full(q, k_cur, v_cur, kseg_cur)
        return lse_merge(o, lse, o_i, lse_i)

    def step(carry, i):
        # kseg rides the ring ONLY when packing is on (has_seg is
        # trace-static): the default path keeps its original
        # two-operand collective-permute shape
        if has_seg:
            o, lse, k_cur, v_cur, kseg_cur = carry
        else:
            (o, lse, k_cur, v_cur), kseg_cur = carry, None
        # rotation FIRST, local attention second: the ppermute depends
        # only on the held shard, so issuing it before the compute lets
        # XLA's latency-hiding scheduler run the ICI transfer UNDER the
        # flash kernel instead of after it (comm/compute overlap — the
        # point of ring attention)
        rot = (k_cur, v_cur, kseg_cur) if has_seg else (k_cur, v_cur)
        rot = jax.lax.ppermute(rot, axis_name, perm)
        o, lse = merge(o, lse, k_cur, v_cur, kseg_cur, i)
        return (o, lse) + rot, None

    o0 = jnp.zeros(q.shape, f32)
    lse0 = jnp.full((b, h, lq), _NEG_INF, f32)
    carry0 = (o0, lse0, k, v) + ((seg,) if has_seg else ())
    # the last shard's rotation would be discarded — merge it outside the
    # scan so each step pays exactly the ppermutes it uses
    final, _ = jax.lax.scan(step, carry0, jnp.arange(size - 1))
    if has_seg:
        o, lse, k_last, v_last, kseg_last = final
    else:
        (o, lse, k_last, v_last), kseg_last = final, None
    o, lse = merge(o, lse, k_last, v_last, kseg_last, size - 1)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_attention(q, k, v, seg, axis_name, causal, scale, block_q,
                    block_k, window):
    o, _ = _ring_fwd_impl(q, k, v, seg, axis_name, causal, scale,
                          block_q, block_k, window)
    return o


def _ring_vjp_fwd(q, k, v, seg, axis_name, causal, scale, block_q,
                  block_k, window):
    o, lse = _ring_fwd_impl(q, k, v, seg, axis_name, causal, scale,
                            block_q, block_k, window)
    return o, (q, k, v, seg, o, lse)


def _ring_vjp_bwd(axis_name, causal, scale, block_q, block_k, window,
                  res, g):
    """Ring backward: a second ring pass. Each rotation recomputes this
    shard's slice of the global softmax from the saved global logsumexp
    (attention_backward_lse — the Pallas two-pass kernels on TPU), adds
    dq locally, and accumulates dk/dv into buffers that TRAVEL WITH
    their kv shard around the ring; after the full cycle of ppermutes
    every dk/dv accumulator is back on the device that owns its shard."""
    q, k, v, seg, o, lse = res
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [((j + 1) % size, j) for j in range(size)]
    f32 = jnp.float32
    has_seg = seg is not None

    def _pair(kseg_cur):
        return (seg, kseg_cur) if has_seg else None

    def full(kk, vv, kseg_cur):
        return attention_backward_lse(q, kk, vv, o, lse, g, causal=False,
                                      scale=scale, block_q=block_q,
                                      block_k=block_k, grad_dtype=f32,
                                      segments=_pair(kseg_cur))

    def diag(kk, vv, kseg_cur):
        return attention_backward_lse(q, kk, vv, o, lse, g, causal=True,
                                      scale=scale, block_q=block_q,
                                      block_k=block_k, grad_dtype=f32,
                                      segments=_pair(kseg_cur))

    def skip(kk, vv, kseg_cur):
        return (jnp.zeros(q.shape, f32), jnp.zeros(kk.shape, f32),
                jnp.zeros(vv.shape, f32))

    lq = q.shape[2]

    def _win_branch(r):
        def br(kk, vv, kseg_cur):
            return attention_backward_lse(
                q, kk, vv, o, lse, g, causal=(causal and r == 0),
                scale=scale,
                block_q=block_q, block_k=block_k, grad_dtype=f32,
                segments=_pair(kseg_cur), pos_offset=r * lq,
                window=window,
            )

        return br

    def _win_branches():
        return [
            _win_branch(off)
            for off in _win_offsets(lq, window, size, causal)
        ] + [skip]

    def grads(k_cur, v_cur, kseg_cur, i):
        if window is not None:
            return jax.lax.switch(
                _win_case((my + i) % size, my, lq, window, size,
                          causal),
                _win_branches(),
                k_cur, v_cur, kseg_cur,
            )
        if causal:
            return jax.lax.switch(
                _ring_case((my + i) % size, my), (full, diag, skip),
                k_cur, v_cur, kseg_cur,
            )
        return full(k_cur, v_cur, kseg_cur)

    def step(carry, i):
        if has_seg:
            dq, k_cur, v_cur, kseg_cur, dk_acc, dv_acc = carry
        else:
            (dq, k_cur, v_cur, dk_acc, dv_acc), kseg_cur = carry, None
        # two permutes instead of one: the kv shards don't depend on
        # this step's gradients, so their (large) transfer is issued
        # BEFORE the kernels and can ride ICI under the compute; only
        # the dk/dv accumulators — which need this step's results — pay
        # an exposed hop
        kv_rot = jax.lax.ppermute(
            (k_cur, v_cur) + ((kseg_cur,) if has_seg else ()),
            axis_name, perm,
        )
        dq_i, dk_i, dv_i = grads(k_cur, v_cur, kseg_cur, i)
        dq = dq + dq_i
        acc_rot = jax.lax.ppermute(
            (dk_acc + dk_i, dv_acc + dv_i), axis_name, perm
        )
        return (dq,) + kv_rot + acc_rot, None

    carry0 = (
        (jnp.zeros(q.shape, f32), k, v)
        + ((seg,) if has_seg else ())
        + (jnp.zeros(k.shape, f32), jnp.zeros(v.shape, f32))
    )
    final, _ = jax.lax.scan(step, carry0, jnp.arange(size - 1))
    if has_seg:
        dq, k_last, v_last, kseg_last, dk_acc, dv_acc = final
    else:
        (dq, k_last, v_last, dk_acc, dv_acc), kseg_last = final, None
    # final shard: compute in place, then one last hop delivers the
    # accumulators home (kv shards themselves are done rotating)
    dq_i, dk_i, dv_i = grads(k_last, v_last, kseg_last, size - 1)
    dq = dq + dq_i
    dk_acc, dv_acc = jax.lax.ppermute(
        (dk_acc + dk_i, dv_acc + dv_i), axis_name, perm
    )
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype), segments_float0(seg))


_ring_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention_local(q, k, v, axis_name, causal=False, scale=None,
                         block_q=None, block_k=None, segments=None,
                         window=None):
    """Per-device body: q/k/v are the local sequence shards
    [batch, heads, local_len, dim]. Call inside shard_map/pjit with a
    named `axis_name` axis; returns the local output shard. The local
    compute per rotation is the Pallas flash kernel (fwd + two-pass bwd)
    when it can run, with a blockwise/dense jnp fallback. `segments`:
    the LOCAL [b, local_len] packed-sequence ids (k-side ids rotate
    with their kv shard)."""
    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    # resolve tuned defaults here: the custom_vjp's nondiff args must be
    # concrete ints
    block_q = resolve_block(block_q, "q")
    block_k = resolve_block(block_k, "k")
    if causal and q.shape[2] != k.shape[2]:
        # The three-way shard classification (_ring_case) relies on
        # equal-length q/kv shards so diagonal offsets cancel; unequal
        # lengths would need per-shard position offsets in the kernel.
        raise ValueError(
            "causal ring attention requires equal q/kv sequence lengths "
            "per shard, got lq=%d lk=%d" % (q.shape[2], k.shape[2])
        )
    if window is not None:
        window = int(window)
        if window < 1:
            raise ValueError("window must be >= 1, got %r" % (window,))
    if segments is not None:
        segments = jnp.asarray(segments, jnp.int32)
    return _ring_attention(q, k, v, segments, axis_name, causal, scale,
                           block_q, block_k, window)


def ring_attention(q, k, v, mesh, causal=False, scale=None,
                   block_q=None, block_k=None, segments=None,
                   window=None,
                   seq_axis=MeshAxis.SP, batch_axes=(MeshAxis.DP,
                                                     MeshAxis.FSDP)):
    """Global-view ring attention: q/k/v are [batch, heads, seq, dim]
    arrays (sharded or not); the sequence axis is laid out over
    `seq_axis` and batch over `batch_axes`, and XLA inserts only the
    ring ppermutes — no full-sequence gather. `segments` [batch, seq]:
    packed-sequence ids, sequence-sharded like q (long-context packed
    training; each rotation masks with the held shard's ids).

    With an sp=1 mesh this degenerates to one shard_map program == plain
    attention.
    """
    spec = P(batch_axes, None, seq_axis, None)
    seg_spec = P(batch_axes, seq_axis)
    local = functools.partial(
        ring_attention_local,
        axis_name=seq_axis,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        window=window,
    )
    if segments is None:
        fn = shard_map(
            local, mesh, (spec, spec, spec), spec,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda qq, kk, vv, ss: local(qq, kk, vv, segments=ss),
        mesh, (spec, spec, spec, seg_spec), spec,
    )
    return fn(q, k, v, jnp.asarray(segments, jnp.int32))


# Local full-sequence attention per Ulysses impl choice; "jax_flash" is
# jax's bundled TPU kernel (ops/attention.jax_flash_attention). Unknown
# values are validated in ulysses_attention before tracing.
_ULYSSES_LOCAL_ATTN = {
    "auto": flash_attention,
    "xla": blockwise_attention,
    "jax_flash": jax_flash_attention,
}


def ulysses_attention_local(q, k, v, axis_name, causal=False, scale=None,
                            attn_impl="auto", segments=None,
                            window=None):
    """Per-device body: q/k/v are local sequence shards
    [batch, heads, local_len, dim]. One tiled all_to_all turns them into
    [batch, heads/sp, full_len, dim] (device i holds head block i), the
    full-sequence attention kernel runs locally, and the inverse
    all_to_all restores the sequence-sharded layout. `segments`: local
    [b, local_len] packed ids — all-gathered to the full sequence (ints
    are tiny next to the activation all-to-alls) since the local kernel
    sees the whole sequence."""

    def to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    local_attn = _ULYSSES_LOCAL_ATTN[attn_impl]
    kwargs = {}
    if window is not None:
        # each device holds FULL-sequence heads after the all_to_all,
        # so the plain single-shard window mask applies directly
        kwargs["window"] = window
    if segments is not None:
        kwargs["segments"] = jax.lax.all_gather(
            jnp.asarray(segments, jnp.int32), axis_name, axis=1,
            tiled=True,
        )
    out = local_attn(
        to_heads(q), to_heads(k), to_heads(v), causal=causal,
        scale=scale, **kwargs
    )
    return jax.lax.all_to_all(
        out, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention(q, k, v, mesh, causal=False, scale=None,
                      attn_impl="auto", segments=None, window=None,
                      seq_axis=MeshAxis.SP, batch_axes=(MeshAxis.DP,
                                                        MeshAxis.FSDP)):
    """Global-view Ulysses attention: q/k/v are [batch, heads, seq, dim];
    the sequence axis is laid out over `seq_axis`. Each device computes
    heads/sp full-sequence heads between two all-to-all transposes.

    With an sp=1 mesh this degenerates to one shard_map program == plain
    attention. Requires heads to divide evenly over the sp axis — use
    ring attention otherwise.
    """
    if attn_impl not in _ULYSSES_LOCAL_ATTN:
        raise ValueError(
            "Unknown attn_impl %r (valid: %s)"
            % (attn_impl, ", ".join(sorted(_ULYSSES_LOCAL_ATTN)))
        )
    if segments is not None and attn_impl == "jax_flash":
        raise ValueError(
            "attn_impl='jax_flash' does not support packed-sequence "
            "masking; use attn_impl='auto' or 'xla'"
        )
    if window is not None and attn_impl == "jax_flash":
        raise ValueError(
            "attn_impl='jax_flash' does not support sliding-window "
            "attention; use attn_impl='auto' or 'xla'"
        )
    sp = mesh.shape.get(seq_axis, 1)
    heads = q.shape[1]
    if heads % sp:
        raise ValueError(
            "ulysses_attention needs num_heads (%d) divisible by the %s "
            "axis (%d); use ring attention for this config"
            % (heads, seq_axis, sp)
        )
    spec = P(batch_axes, None, seq_axis, None)
    seg_spec = P(batch_axes, seq_axis)
    local = functools.partial(
        ulysses_attention_local,
        axis_name=seq_axis,
        causal=causal,
        scale=scale,
        attn_impl=attn_impl,
        window=window,
    )
    if segments is None:
        fn = shard_map(
            local, mesh, (spec, spec, spec), spec,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda qq, kk, vv, ss: local(qq, kk, vv, segments=ss),
        mesh, (spec, spec, spec, seg_spec), spec,
    )
    return fn(q, k, v, jnp.asarray(segments, jnp.int32))
