"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

The reference has no long-context story (SURVEY.md §5: no ring attention,
no sequence parallelism anywhere in the tree); this module is the
TPU-native design the rebuild reserves the `sp` axis for: the sequence
axis of q/k/v is sharded over `sp`, each device computes its query
shard's attention against the key/value shard it currently holds, and
key/value shards rotate around the ring with `jax.lax.ppermute` (ICI
neighbor exchange) while partial softmax results merge online — the
all-gather of the full sequence never materializes.

Works inside `jit` via `shard_map`; differentiable (ppermute has a
transpose rule), so the same code path trains.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.ops.attention import (
    NEG_INF as _NEG_INF,
    softmax_finalize,
    softmax_merge,
)


def ring_attention_local(q, k, v, axis_name, causal=False, scale=None):
    """Per-device body: q/k/v are the local sequence shards
    [batch, heads, local_len, dim]. Call inside shard_map/pjit with a
    named `axis_name` axis; returns the local output shard."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lk = k.shape[2]
    q_scaled = q * scale
    q_pos = my * lq + jnp.arange(lq)
    perm = [((j + 1) % size, j) for j in range(size)]

    def merge_shard(o, l, m, k_cur, v_cur, i):
        # after i rotations device `my` holds the shard born on my+i
        src = (my + i) % size
        s = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, k_cur)
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        return softmax_merge(o, l, m, s, v_cur)

    def step(carry, i):
        o, l, m, k_cur, v_cur = carry
        o, l, m = merge_shard(o, l, m, k_cur, v_cur, i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, l, m, k_nxt, v_nxt), None

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((b, h, lq), q.dtype)
    m0 = jnp.full((b, h, lq), _NEG_INF, q.dtype)
    # the last shard's rotation would be discarded — merge it outside the
    # scan so each step pays exactly the ppermutes it uses
    (o, l, m, k_last, v_last), _ = jax.lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(size - 1)
    )
    o, l, m = merge_shard(o, l, m, k_last, v_last, size - 1)
    return softmax_finalize(o, l)


def ring_attention(q, k, v, mesh, causal=False, scale=None,
                   seq_axis=MeshAxis.SP, batch_axes=(MeshAxis.DP,
                                                     MeshAxis.FSDP)):
    """Global-view ring attention: q/k/v are [batch, heads, seq, dim]
    arrays (sharded or not); the sequence axis is laid out over
    `seq_axis` and batch over `batch_axes`, and XLA inserts only the
    ring ppermutes — no full-sequence gather.

    With an sp=1 mesh this degenerates to one shard_map program == plain
    attention.
    """
    spec = P(batch_axes, None, seq_axis, None)
    fn = jax.shard_map(
        functools.partial(
            ring_attention_local,
            axis_name=seq_axis,
            causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
