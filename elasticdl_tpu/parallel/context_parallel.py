"""Sequence/context parallelism over the `sp` mesh axis: ring attention
and Ulysses (all-to-all head/sequence transpose).

The reference has no long-context story (SURVEY.md §5: no ring attention,
no sequence parallelism anywhere in the tree); this module is the
TPU-native design the rebuild reserves the `sp` axis for. Two schemes,
both inside `jit` via `shard_map` and differentiable (ppermute and
all_to_all have transpose rules), so the same code paths train:

* **Ring** (`ring_attention`): the sequence axis of q/k/v is sharded
  over `sp`; key/value shards rotate around the ring with
  `jax.lax.ppermute` (ICI neighbor exchange) while partial softmax
  results merge online — the full sequence never materializes anywhere.
  Works for any head count; communication is 2(sp-1) neighbor hops of
  the local kv shard per attention.
* **Ulysses** (`ulysses_attention`): one `all_to_all` re-shards heads
  against sequence so each device holds heads/sp *full-sequence* heads,
  runs the local flash/blockwise kernel over the whole sequence, and
  transposes back. Requires heads % sp == 0; communication is 4
  all-to-alls of the activations per attention, and the inner kernel
  sees the full sequence (better MXU tiling than sp-chunked ring steps).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.ops.attention import (
    NEG_INF as _NEG_INF,
    blockwise_attention,
    flash_attention,
    softmax_finalize,
    softmax_merge,
)


def ring_attention_local(q, k, v, axis_name, causal=False, scale=None):
    """Per-device body: q/k/v are the local sequence shards
    [batch, heads, local_len, dim]. Call inside shard_map/pjit with a
    named `axis_name` axis; returns the local output shard."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lk = k.shape[2]
    q_scaled = q * scale
    q_pos = my * lq + jnp.arange(lq)
    perm = [((j + 1) % size, j) for j in range(size)]

    def merge_shard(o, l, m, k_cur, v_cur, i):
        # after i rotations device `my` holds the shard born on my+i
        src = (my + i) % size
        s = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, k_cur)
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        return softmax_merge(o, l, m, s, v_cur)

    def step(carry, i):
        o, l, m, k_cur, v_cur = carry
        o, l, m = merge_shard(o, l, m, k_cur, v_cur, i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, l, m, k_nxt, v_nxt), None

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((b, h, lq), q.dtype)
    m0 = jnp.full((b, h, lq), _NEG_INF, q.dtype)
    # the last shard's rotation would be discarded — merge it outside the
    # scan so each step pays exactly the ppermutes it uses
    (o, l, m, k_last, v_last), _ = jax.lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(size - 1)
    )
    o, l, m = merge_shard(o, l, m, k_last, v_last, size - 1)
    return softmax_finalize(o, l)


def ring_attention(q, k, v, mesh, causal=False, scale=None,
                   seq_axis=MeshAxis.SP, batch_axes=(MeshAxis.DP,
                                                     MeshAxis.FSDP)):
    """Global-view ring attention: q/k/v are [batch, heads, seq, dim]
    arrays (sharded or not); the sequence axis is laid out over
    `seq_axis` and batch over `batch_axes`, and XLA inserts only the
    ring ppermutes — no full-sequence gather.

    With an sp=1 mesh this degenerates to one shard_map program == plain
    attention.
    """
    spec = P(batch_axes, None, seq_axis, None)
    fn = jax.shard_map(
        functools.partial(
            ring_attention_local,
            axis_name=seq_axis,
            causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention_local(q, k, v, axis_name, causal=False, scale=None,
                            attn_impl="auto"):
    """Per-device body: q/k/v are local sequence shards
    [batch, heads, local_len, dim]. One tiled all_to_all turns them into
    [batch, heads/sp, full_len, dim] (device i holds head block i), the
    full-sequence attention kernel runs locally, and the inverse
    all_to_all restores the sequence-sharded layout."""

    def to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    local_attn = (
        blockwise_attention if attn_impl == "xla" else flash_attention
    )
    out = local_attn(
        to_heads(q), to_heads(k), to_heads(v), causal=causal, scale=scale
    )
    return jax.lax.all_to_all(
        out, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention(q, k, v, mesh, causal=False, scale=None,
                      attn_impl="auto",
                      seq_axis=MeshAxis.SP, batch_axes=(MeshAxis.DP,
                                                        MeshAxis.FSDP)):
    """Global-view Ulysses attention: q/k/v are [batch, heads, seq, dim];
    the sequence axis is laid out over `seq_axis`. Each device computes
    heads/sp full-sequence heads between two all-to-all transposes.

    With an sp=1 mesh this degenerates to one shard_map program == plain
    attention. Requires heads to divide evenly over the sp axis — use
    ring attention otherwise.
    """
    sp = mesh.shape.get(seq_axis, 1)
    heads = q.shape[1]
    if heads % sp:
        raise ValueError(
            "ulysses_attention needs num_heads (%d) divisible by the %s "
            "axis (%d); use ring attention for this config"
            % (heads, seq_axis, sp)
        )
    spec = P(batch_axes, None, seq_axis, None)
    fn = jax.shard_map(
        functools.partial(
            ulysses_attention_local,
            axis_name=seq_axis,
            causal=causal,
            scale=scale,
            attn_impl=attn_impl,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
