"""Worker process entrypoint (reference worker/main.py:33-88): parse
flags, connect to the master, run the task-driven loop. Launched by the
instance manager (k8s pod or local subprocess)."""

import sys

from elasticdl_tpu.common.args import parse_worker_args
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.model_utils import (
    get_dict_from_params_str,
    get_model_spec,
)
from elasticdl_tpu.worker.worker import JobType, Worker


def build_worker(args):
    spec = get_model_spec(args.model_zoo, args.model_def)
    mesh = None
    spmd = False
    if args.distribution_strategy == "AllreduceStrategy":
        from elasticdl_tpu.parallel import mesh as mesh_lib
        from elasticdl_tpu.parallel.spmd import initialize_distributed

        initialize_distributed(
            coordinator_addr=args.coordinator_addr or None,
            num_processes=args.num_processes or None,
            process_id=args.process_id,
        )
        mesh = mesh_lib.build_mesh(args.mesh_spec or None)
        spmd = True

    checkpoint_saver = None
    if args.checkpoint_dir and args.checkpoint_steps:
        from elasticdl_tpu.checkpoint import CheckpointSaver

        checkpoint_saver = CheckpointSaver(
            args.checkpoint_dir,
            checkpoint_steps=args.checkpoint_steps,
            keep_max_version=args.keep_checkpoint_max,
        )

    job_type = {
        "training_only": JobType.TRAINING_ONLY,
        "training_with_evaluation": JobType.TRAINING_WITH_EVALUATION,
        "evaluation_only": JobType.EVALUATION_ONLY,
        "prediction_only": JobType.PREDICTION_ONLY,
    }[args.job_type]

    return Worker(
        args.worker_id,
        spec,
        master_addr=args.master_addr,
        job_type=job_type,
        minibatch_size=args.minibatch_size,
        training_data=args.training_data or None,
        data_reader_params=get_dict_from_params_str(
            args.data_reader_params
        ),
        records_per_task=args.records_per_task,
        mesh=mesh,
        model_params=args.model_params,
        seed=args.seed,
        spmd=spmd,
        checkpoint_saver=checkpoint_saver,
        checkpoint_dir_for_init=args.checkpoint_dir_for_init or None,
        grad_accum_steps=args.grad_accum_steps,
    )


def main(argv=None):
    from elasticdl_tpu.common.platform_utils import (
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    # SIGUSR2 -> all-thread stack dump: a live wedged worker can
    # always be interrogated without killing its task
    from elasticdl_tpu.observability.runtime_health import (
        install_sigusr2_dump,
    )

    install_sigusr2_dump()
    args = parse_worker_args(argv)
    logger.info(
        "Worker %d starting, master=%s", args.worker_id, args.master_addr
    )
    # name this process's span recorder; task spans export to
    # $EDL_TRACE_DIR on exit (atexit) when tracing is armed
    from elasticdl_tpu.observability.tracing import configure

    configure(service="worker:%d" % args.worker_id)
    worker = build_worker(args)
    worker.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
