"""Task data service: bridges the master task queue to the worker's input
pipeline.

Behavioral parity with the reference's worker/task_data_service.py:26-237:
* a record generator that pulls tasks from the master forever, queues each
  pending task, and streams its records (batches may span task boundaries),
* ``report_record_done(count)`` pops pending tasks once enough records were
  consumed and reports them to the master (with failed-record counters),
* WAIT handling: when the master says WAIT the current dataset ends and
  ``get_dataset`` yields a fresh one after a backoff, so the worker loop can
  interleave evaluation tasks while training tasks are scarce,
* TRAIN_END_CALLBACK tasks are intercepted and parked for the worker.

TF-free: produces the framework's Dataset (data/dataset.py) over raw records.
"""

import threading
import time
from collections import deque

from elasticdl_tpu.common.constants import TaskExecCounterKey
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.dataset import Dataset
from elasticdl_tpu.data.reader.data_reader_factory import build_data_reader
from elasticdl_tpu.proto import elasticdl_pb2 as pb


class TaskDataService(object):
    def __init__(
        self,
        worker,
        data_origin=None,
        data_reader_params=None,
        custom_data_reader=None,
        records_per_task=None,
        wait_sleep_secs=2.0,
    ):
        self._worker = worker
        self._lock = threading.Lock()
        self._pending_dataset = True
        self._pending_train_end_callback_task = None
        self._wait_sleep_secs = wait_sleep_secs
        self.data_reader = build_data_reader(
            data_origin, records_per_task, data_reader_params,
            custom_data_reader=custom_data_reader,
        )
        self._failed_record_count = 0
        self._reported_record_count = 0
        self._current_task = None
        self._pending_tasks = deque()

    def _reset_locked(self):
        self._reported_record_count = 0
        self._failed_record_count = 0
        self._pending_tasks = deque()
        self._current_task = None

    def get_current_task(self):
        with self._lock:
            return self._current_task

    def _do_report_task(self, task, err_msg=""):
        exec_counters = None
        if self._failed_record_count:
            exec_counters = {
                TaskExecCounterKey.FAIL_COUNT: self._failed_record_count
            }
        self._worker.report_task_result(
            task.task_id, err_msg, exec_counters=exec_counters
        )

    def report_record_done(self, count, err_msg=""):
        """Account `count` consumed records against the pending task queue;
        report and pop every task fully covered (reference :94-129).

        The whole method runs under the lock: the counters and the
        pending deque are one consistent unit — the old unlocked
        read-modify-write of the counters raced `_gen`'s appends
        (edl-lint EDL001), and a torn `_reported_record_count` either
        double-reports a task or strands it pending forever."""
        with self._lock:
            self._reported_record_count += count
            if err_msg:
                self._failed_record_count += count
            if not self._pending_tasks:
                return False
            task = self._pending_tasks[0]
            if self._reported_record_count < task.end - task.start:
                return False
            while self._pending_tasks and (
                self._reported_record_count
                >= self._pending_tasks[0].end
                - self._pending_tasks[0].start
            ):
                task = self._pending_tasks[0]
                self._reported_record_count -= task.end - task.start
                self._pending_tasks.popleft()
                self._do_report_task(task, err_msg)
                self._failed_record_count = 0
            if self._pending_tasks:
                self._current_task = self._pending_tasks[0]
            return True

    def flush_record_accounting(self, err_msg=""):
        """Report every still-pending task as complete.

        Call ONLY when the task stream's dataset was consumed to normal
        exhaustion: `_gen` advances to the next task only after fully
        yielding the previous one, so at stream end every pending
        task's records went through the pipeline even when the
        per-batch counts undercounted. That happens with CARDINALITY-
        CHANGING dataset_fns — e.g. the sequence packer emits fewer
        rows than source records (model_zoo/transformer_lm_packed) —
        where row-based report_record_done can never cover the task.
        For 1:1 dataset_fns the counts already drained the queue and
        this is a no-op. A crash mid-stream skips the flush, so the
        master still recovers the in-flight tasks.

        Retry amplification caveat: when called with a non-empty
        err_msg, EVERY still-pending task is reported failed with that
        same message — packing blends records across task boundaries,
        so one failed minibatch late in a packed stream cannot be
        attributed to a single task, and all blended-in tasks get
        retried wholesale. Deliberately conservative: at-least-once
        processing over precise blame."""
        with self._lock:
            while self._pending_tasks:
                task = self._pending_tasks.popleft()
                self._do_report_task(task, err_msg)
                # failure counters attach to the FIRST reported task
                # only (mirrors report_record_done's per-report reset)
                self._failed_record_count = 0
            self._reported_record_count = 0
            self._current_task = None

    def get_train_end_callback_task(self):
        with self._lock:
            return self._pending_train_end_callback_task

    def clear_train_end_callback_task(self):
        with self._lock:
            self._pending_train_end_callback_task = None

    def get_dataset(self):
        """A fresh Dataset streaming records of dispatched tasks, or None
        when the job has no more training work (reference :163-203)."""
        with self._lock:
            if not self._pending_dataset:
                return None
            if self._pending_tasks:
                logger.error(
                    "Cannot get a new dataset with pending tasks"
                )
                return None
            self._reset_locked()
            self._pending_dataset = False
        return Dataset.from_generator(self._gen)

    def _gen(self):
        while True:
            task = self._worker.get_task()
            if not task.shard_name:
                if task.type == pb.WAIT:
                    with self._lock:
                        self._pending_dataset = True
                    logger.info("No tasks for now, maybe more later")
                    time.sleep(self._wait_sleep_secs)
                else:
                    logger.info("No more tasks, stopping")
                break
            with self._lock:
                if task.type == pb.TRAIN_END_CALLBACK:
                    self._pending_train_end_callback_task = task
                    continue
                self._pending_tasks.append(task)
                if len(self._pending_tasks) == 1:
                    self._current_task = task
            for record in self.data_reader.read_records(task):
                if record is not None:
                    yield record
