"""User-extensible sink for prediction outputs.

Reference: elasticdl/python/worker/prediction_outputs_processor.py:17-35 —
model-zoo modules export a ``PredictionOutputsProcessor`` subclass (by name)
whose ``process(predictions, worker_id)`` is invoked per prediction batch.
"""

from abc import ABC, abstractmethod


class BasePredictionOutputsProcessor(ABC):
    @abstractmethod
    def process(self, predictions, worker_id):
        """Process a batch of prediction outputs.

        Args:
            predictions: model outputs for one minibatch (ndarray or dict of
                ndarrays for multi-output models).
            worker_id: the integer id of the reporting worker.
        """


def resolve_processor(processor):
    """Normalize the spec's processor (class, instance, or bare callable)
    into a single ``fn(predictions, worker_id)``. Classes are instantiated
    exactly once so stateful processors (the reference's ODPS table writer
    pattern) keep cross-batch state."""
    if processor is None:
        return None
    if isinstance(processor, type) and issubclass(
        processor, BasePredictionOutputsProcessor
    ):
        processor = processor()
    if isinstance(processor, BasePredictionOutputsProcessor):
        return processor.process
    return lambda predictions, worker_id: processor(predictions)


def invoke_processor(processor, predictions, worker_id=0):
    """One-shot convenience over resolve_processor (prefer resolving once
    outside any per-batch loop)."""
    fn = resolve_processor(processor)
    if fn is not None:
        fn(predictions, worker_id)
