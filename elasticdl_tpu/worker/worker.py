"""Worker: the compute-plane process driven by master-dispatched tasks.

Replaces the reference's worker/worker.py:72-1147. What's gone, by design:
all PS plumbing (pull_dense_parameters / report_gradient / embedding RPC —
~700 of those 1147 lines). The TPU worker's gradient path is the jit-compiled
Trainer step; gradient aggregation across hosts is XLA collectives inside
that step (multi-host wiring in parallel/), not RPC.

What's preserved, behavior-for-behavior:
* task-driven training with batches spanning task boundaries,
* interleaved evaluation during training (TRAINING_WITH_EVALUATION pulls an
  eval task before each minibatch — reference :1041-1047, :1091-1110),
* minibatch retry up to MAX_MINIBATCH_RETRY_NUM (=64, reference :62),
* version reporting to the master for step-based eval triggers (in the
  reference the PS did this every eval_steps; the PS is gone, so the worker
  reports after each completed minibatch),
* TRAIN_END_CALLBACK processing (train-end callbacks e.g. model export),
* predict-only mode with a prediction outputs processor.
"""

import os
import traceback

import numpy as np

from elasticdl_tpu.common.constants import (
    MAX_MINIBATCH_RETRY_NUM,
    Mode,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.model_utils import resolve_dataset_fn
from elasticdl_tpu.common.retry import (
    RetryPolicy,
    is_transient_rpc_error,
    retry_call,
)
from elasticdl_tpu.common.tensor_utils import serialize_ndarray_dict
from elasticdl_tpu.common.timing_utils import Timing
from elasticdl_tpu.data.dataset import pad_batch
from elasticdl_tpu.master.task_dispatcher import Task
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto.service import MasterStub, build_channel
from elasticdl_tpu.training.trainer import Trainer
from elasticdl_tpu.worker.task_data_service import TaskDataService


def _default_retry_policy():
    """Worker RPC retry knobs, env-overridable so subprocess drills can
    shrink the reconnect window without new CLI flags."""
    return RetryPolicy(
        rpc_timeout_secs=float(
            os.environ.get("EDL_RPC_TIMEOUT_SECS", 30.0)
        ),
        reconnect_window_secs=float(
            os.environ.get("EDL_RPC_RECONNECT_WINDOW_SECS", 120.0)
        ),
    )


class JobType(object):
    TRAINING_ONLY = "training_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"


class Worker(object):
    def __init__(
        self,
        worker_id,
        model_spec,
        master_addr=None,
        master_servicer=None,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=32,
        training_data=None,
        data_reader_params=None,
        records_per_task=None,
        mesh=None,
        model_params="",
        seed=0,
        callbacks=None,
        wait_sleep_secs=0.5,
        spmd=False,
        checkpoint_saver=None,
        checkpoint_dir_for_init=None,
        grad_accum_steps=1,
        retry_policy=None,
    ):
        """Connect either over gRPC (master_addr) or in-process
        (master_servicer — the test harness path, mirroring the reference's
        InProcessMaster in tests/in_process_master.py)."""
        self.worker_id = worker_id
        self.spec = model_spec
        self.job_type = job_type
        self.minibatch_size = minibatch_size
        self._channel = None
        self._master_addr = master_addr
        if master_servicer is not None:
            self._master = master_servicer
        elif master_addr:
            self._channel = build_channel(master_addr)
            self._master = MasterStub(self._channel)
        else:
            raise ValueError("need master_addr or master_servicer")
        self.trainer = Trainer(
            model_spec, mesh=mesh, model_params=model_params, seed=seed,
            grad_accum_steps=grad_accum_steps,
        )
        from elasticdl_tpu.embedding.host_bridge import attach_from_spec

        self._host_manager = attach_from_spec(self.trainer, model_spec)
        self.state = None
        self._task_data_service = TaskDataService(
            self,
            data_origin=training_data,
            data_reader_params=data_reader_params,
            custom_data_reader=model_spec.custom_data_reader,
            records_per_task=records_per_task,
            wait_sleep_secs=wait_sleep_secs,
        )
        self._timing = Timing(enabled=True, logger=logger)
        self._callbacks = callbacks or []
        self._minibatch_retry_count = 0
        self._retry_policy = retry_policy or _default_retry_policy()
        # set ONLY by the master's explicit JOB_COMPLETE signal — never
        # inferred from a transport error (see _call_master)
        self.job_complete = False
        self.rpc_retry_count = 0
        self.reconnect_count = 0
        # training-plane tracing: task_id -> the worker's `worker_task`
        # span (fetch -> report), parented under the master's
        # task_dispatch span via the Task proto's trace fields. The
        # worker's task loop is single-threaded; no lock needed.
        self._task_spans = {}
        self.losses = []
        # The reference's PS owns checkpointing (ps/servicer.py:255-270);
        # with the PS gone the worker that owns the jit state does, on the
        # same every-checkpoint_steps cadence.
        self._checkpoint_saver = checkpoint_saver
        if checkpoint_saver is not None and self._host_manager:
            checkpoint_saver.extra_state_fn = self._host_manager.flat_state
        self._checkpoint_dir_for_init = checkpoint_dir_for_init
        self.spmd = spmd
        self._spmd_ctx = None
        self._template_batch = None
        self._train_iter = None
        self._eval_iter = None
        self._eval_task_pb = None
        if spmd:
            from elasticdl_tpu.parallel.spmd import SPMDContext

            self._spmd_ctx = SPMDContext(self.trainer.mesh)
            if self._host_manager:
                # Multi-host host-spill: partition the id space over
                # hosts (embedding/host_bridge.py enable_spmd) so table
                # capacity scales with the fleet, like the reference's
                # PS pods (docs/designs/parameter_server.md:42-78).
                self._host_manager.enable_spmd(self._spmd_ctx)

    # ----------------------------------------------------------- RPC layer
    #
    # Every worker->master RPC goes through _call_master: per-RPC
    # deadlines, exponential backoff with jitter, and a bounded reconnect
    # window (common/retry.py). The old heuristic — "UNAVAILABLE from an
    # ever-connected master means the job finished" — is GONE: a
    # transient master outage (pod reschedule, journal replay) looks
    # identical to shutdown on the wire, and the heuristic silently
    # terminated every worker mid-epoch. Workers now exit only on the
    # servicer's explicit JOB_COMPLETE reason; transport errors retry
    # within the window and then fail loudly.

    def _rebuild_channel(self):
        """Drop the broken channel and dial the master fresh. A stale
        channel's subchannel can sit in connect-backoff long after a
        restarted master is serving again; a new channel connects
        immediately."""
        if self._master_addr is None:
            return
        try:
            self._channel.close()
        except Exception:
            pass
        self._channel = build_channel(self._master_addr)
        self._master = MasterStub(self._channel)

    def _call_master(self, rpc_name, request, default_after_complete=None):
        if self._channel is not None:
            def attempt():
                # resolve through self._master each attempt: a retry may
                # have rebuilt the channel and stub underneath us
                return getattr(self._master, rpc_name)(
                    request, timeout=self._retry_policy.rpc_timeout_secs
                )
        else:
            def attempt():
                return getattr(self._master, rpc_name)(request)

        if self.job_complete and default_after_complete is not None:
            # after the explicit end-of-job signal the master is ALLOWED
            # to be gone — remaining reports/polls are best-effort
            try:
                return attempt()
            except Exception as e:
                if is_transient_rpc_error(e):
                    logger.info(
                        "Master gone after JOB_COMPLETE; dropping %s",
                        rpc_name,
                    )
                    return default_after_complete
                raise

        def on_retry(attempt_idx, exc):
            self.rpc_retry_count += 1
            if self._channel is not None:
                self._rebuild_channel()

        result, attempts = retry_call(
            attempt,
            policy=self._retry_policy,
            is_retryable=is_transient_rpc_error,
            on_retry=on_retry,
            what="%s(worker %s)" % (rpc_name, self.worker_id),
        )
        if attempts and rpc_name != "register_worker":
            # the call only succeeded after transport failures: the
            # master (re)started and lost in-memory membership, so
            # re-register before continuing the task loop
            self.reconnect_count += 1
            logger.info(
                "Reconnected to master after %d retries; re-registering",
                attempts,
            )
            self.register()
        return result

    def register(self):
        try:
            self._call_master(
                "register_worker",
                pb.RegisterWorkerRequest(
                    worker_id=self.worker_id, address="", num_devices=1
                ),
            )
        except Exception:
            logger.warning("register_worker failed", exc_info=True)

    def get_task(self, task_type=None):
        req = pb.GetTaskRequest(worker_id=self.worker_id)
        if task_type is not None:
            req.task_type = task_type
        task = self._call_master(
            "get_task",
            req,
            default_after_complete=pb.Task(
                type=pb.NONE, reason=pb.JOB_COMPLETE
            ),
        )
        if task.type == pb.NONE and task.reason == pb.JOB_COMPLETE:
            if not self.job_complete:
                logger.info("Master signaled JOB_COMPLETE")
            self.job_complete = True
        if task.task_id and task.trace_id:
            # open this task's span under the master's dispatch span;
            # report_task_result seals it, so the span's duration IS
            # the fetch->report task execution time
            from elasticdl_tpu.observability.tracing import recorder

            span = recorder().start_span(
                "worker_task", trace_id=task.trace_id,
                parent_span_id=task.span_id, task_id=task.task_id,
                worker_id=self.worker_id,
            )
            span.event("fetched", shard=task.shard_name,
                       start=task.start, end=task.end)
            self._task_spans[task.task_id] = span
        return task

    def report_task_result(self, task_id, err_msg="", exec_counters=None):
        req = pb.ReportTaskResultRequest(
            task_id=task_id, err_message=err_msg or ""
        )
        if exec_counters:
            for k, v in exec_counters.items():
                req.exec_counters[k] = int(v)
        # piggyback the trainer's tier-health gauges (cumulative host-
        # tier drop counters) on every task report — the master turns
        # tier/-prefixed counters into TensorBoard scalars
        tier = getattr(self.trainer, "tier_health", None)
        if tier and any(tier.values()):
            for k, v in tier.items():
                req.exec_counters["tier/" + k] = int(v)
        # ... and the RPC-resilience counters as fault/ gauges, so a
        # master outage leaves a visible trace in TensorBoard
        if self.rpc_retry_count:
            req.exec_counters["fault/rpc_retries"] = self.rpc_retry_count
        if self.reconnect_count:
            req.exec_counters["fault/reconnects"] = self.reconnect_count
        span = self._task_spans.pop(task_id, None)
        if span is not None:
            span.event("reported", ok=not err_msg)
        try:
            return self._call_master(
                "report_task_result", req,
                default_after_complete=pb.Empty(),
            )
        finally:
            if span is not None:
                span.finish("ok" if not err_msg else "error")

    def report_version(self, version):
        self._call_master(
            "report_version",
            pb.ReportVersionRequest(
                worker_id=self.worker_id, model_version=int(version)
            ),
            default_after_complete=pb.Empty(),
        )

    def report_evaluation_metrics(self, outputs, labels, version):
        if not isinstance(outputs, dict):
            outputs = {"output": outputs}
        self._call_master(
            "report_evaluation_metrics",
            pb.ReportEvaluationMetricsRequest(
                worker_id=self.worker_id,
                model_version=int(version),
                model_outputs=serialize_ndarray_dict(outputs),
                labels=serialize_ndarray_dict({"labels": labels}),
            ),
            default_after_complete=pb.Empty(),
        )

    # --------------------------------------------------------- train loop

    def _task_from_pb(self, task_pb):
        from elasticdl_tpu.proto.convert import task_type_from_pb

        return Task(
            task_pb.shard_name,
            task_pb.start,
            task_pb.end,
            task_type_from_pb(task_pb.type),
            model_version=task_pb.model_version,
        )

    def _ensure_state(self, batch):
        if self.state is None:
            self.state = self.trainer.init_state(batch)
            if self._checkpoint_dir_for_init:
                from elasticdl_tpu.embedding.host_bridge import (
                    restore_with_host_state,
                )

                self.state, version = restore_with_host_state(
                    self.state,
                    self._host_manager,
                    self._checkpoint_dir_for_init,
                )
                logger.info(
                    "Restored model version %d from %s",
                    version, self._checkpoint_dir_for_init,
                )

    def _maybe_checkpoint(self):
        """Save on the checkpoint_steps cadence. Never raises: a transient
        save failure must not fail (or retry) the already-applied step."""
        if self._checkpoint_saver is None or self.state is None:
            return
        try:
            self._checkpoint_saver.maybe_save(self.state)
        except Exception:
            logger.warning("checkpoint save failed", exc_info=True)

    def _process_minibatch(self, batch, true_count):
        """Train one minibatch with retry (reference :870-922: up to 64
        retries; there a retry refetched the PS model after a stale-version
        reject — here retries only guard transient runtime failures)."""
        err = ""
        for attempt in range(MAX_MINIBATCH_RETRY_NUM):
            try:
                self._ensure_state(batch)
                self.state, loss = self.trainer.train_step(
                    self.state, batch, true_count
                )
                self.losses.append(float(loss))
                break
            except (ValueError, TypeError):
                # deterministic failures don't heal with retries
                raise
            except Exception as e:
                err = "%s" % e
                logger.warning(
                    "minibatch failed (attempt %d): %s", attempt + 1, err
                )
                self._minibatch_retry_count += 1
        else:
            return err or "minibatch failed"
        # outside the retry region by design (see _maybe_checkpoint)
        self._maybe_checkpoint()
        return ""

    def _train_and_evaluate(self):
        evaluation_task_executed = False
        while True:
            dataset = self._task_data_service.get_dataset()
            if dataset is None:
                self._process_train_end_callback_task_if_needed()
                break
            dataset = resolve_dataset_fn(
                self.spec, self._task_data_service.data_reader
            )(
                dataset,
                Mode.TRAINING,
                self._task_data_service.data_reader.metadata,
            )
            dataset = dataset.batch(self.minibatch_size).prefetch(1)
            self._timing.start_record_time("task_process")
            stream_err = ""
            for batch in dataset:
                if self.job_type == JobType.TRAINING_WITH_EVALUATION:
                    evaluation_task_executed = (
                        self._evaluate_only() or evaluation_task_executed
                    )
                padded, n = pad_batch(batch, self.minibatch_size)
                with self._timing.record("batch_process"):
                    err_msg = self._process_minibatch(padded, n)
                if err_msg:
                    stream_err = err_msg
                else:
                    self.report_version(int(self.state.step))
                if self._task_data_service.report_record_done(n, err_msg):
                    self._timing.end_record_time("task_process")
                    self._timing.report_timing(reset=True)
                    self._timing.start_record_time("task_process")
            # stream exhausted normally: complete any tasks row-based
            # counting could not cover (cardinality-changing
            # dataset_fns, e.g. sequence packing); 1:1 families no-op.
            # Any failure in the stream propagates so those tasks are
            # retried, not silently marked successful.
            self._task_data_service.flush_record_accounting(stream_err)
            if self.job_type == JobType.TRAINING_WITH_EVALUATION:
                evaluation_task_executed = self._evaluate_only()
            self._process_train_end_callback_task_if_needed()

    def _evaluate_only(self):
        """Drain the master's eval queue (reference :1091-1110)."""
        executed = False
        while True:
            task_pb = self.get_task(pb.EVALUATION)
            if not task_pb.shard_name:
                break
            self._process_eval_task(task_pb)
            executed = True
        return executed

    def _process_eval_task(self, task_pb):
        ds = self._task_dataset(self._task_from_pb(task_pb), Mode.EVALUATION)
        err = ""
        try:
            for batch in ds:
                padded, n = pad_batch(batch, self.minibatch_size)
                self._ensure_state(padded)
                outputs, labels = self.trainer.evaluate_batch(
                    self.state, padded, n
                )
                self.report_evaluation_metrics(
                    outputs, labels, task_pb.model_version
                )
        except Exception as e:
            err = "%s" % e
            logger.error("eval task failed: %s", traceback.format_exc())
        self.report_task_result(task_pb.task_id, err)

    def _predict_only(self):
        from elasticdl_tpu.worker.prediction_outputs_processor import (
            resolve_processor,
        )

        process_outputs = resolve_processor(
            self.spec.prediction_outputs_processor
        )
        results = []
        while True:
            task_pb = self.get_task()
            if not task_pb.shard_name:
                if task_pb.type == pb.WAIT:
                    import time

                    time.sleep(self._task_data_service._wait_sleep_secs)
                    continue
                break
            ds = self._task_dataset(
                self._task_from_pb(task_pb), Mode.PREDICTION
            )
            err = ""
            try:
                for batch in ds:
                    padded, n = pad_batch(batch, self.minibatch_size)
                    self._ensure_state(padded)
                    preds, _ = self.trainer.evaluate_batch(
                        self.state, padded, n
                    )
                    results.append(preds)
                    if process_outputs is not None:
                        process_outputs(preds, self.worker_id)
            except Exception as e:
                err = "%s" % e
                logger.error(
                    "prediction task failed: %s", traceback.format_exc()
                )
            self.report_task_result(task_pb.task_id, err)
        return (
            np.concatenate(results, axis=0) if results else np.array([])
        )

    def _process_train_end_callback_task_if_needed(self):
        task_pb = self._task_data_service.get_train_end_callback_task()
        if task_pb is None:
            return
        err = ""
        try:
            for cb in self._callbacks:
                if hasattr(cb, "on_train_end"):
                    cb.on_train_end(self)
        except Exception as e:
            err = "%s" % e
            logger.error(
                "train-end callback failed: %s", traceback.format_exc()
            )
        self._task_data_service.clear_train_end_callback_task()
        self.report_task_result(task_pb.task_id, err)

    # ------------------------------------------------------ SPMD lockstep

    def _poll_train(self):
        """One tri-state train poll for the ElasticSPMDLoop:
        ("item", (padded, n)) | ("wait",) | ("done",)."""
        while True:
            if self._train_iter is None:
                dataset = self._task_data_service.get_dataset()
                if dataset is None:
                    return ("done",)
                dataset = resolve_dataset_fn(
                    self.spec, self._task_data_service.data_reader
                )(
                    dataset,
                    Mode.TRAINING,
                    self._task_data_service.data_reader.metadata,
                )
                self._train_iter = iter(
                    dataset.batch(self.minibatch_size).prefetch(1)
                )
            batch = next(self._train_iter, None)
            if batch is not None:
                return ("item", pad_batch(batch, self.minibatch_size))
            self._train_iter = None
            # per-stream flush: every emitted row was already processed
            # (the loop polls the next item only after the previous
            # round ran), so tasks row-counting could not cover are
            # complete — and MUST be reported before the WAIT resume,
            # or get_dataset()'s pending-tasks guard would wedge the
            # job. Step failures raise out of loop.run() instead, so
            # success reporting is correct here.
            self._task_data_service.flush_record_accounting()
            if self._task_data_service._pending_dataset:
                return ("wait",)
            # stream ended for good: loop once more; get_dataset -> None

    def _poll_eval(self):
        """Next eval batch, fetching new eval tasks as needed. Reports a
        task's result when refilled past its last batch (the loop only
        refills after the previous item's round executed)."""
        while True:
            if self._eval_iter is not None:
                batch = next(self._eval_iter, None)
                if batch is not None:
                    return (
                        pad_batch(batch, self.minibatch_size),
                        self._eval_task_pb,
                    )
                self.report_task_result(self._eval_task_pb.task_id, "")
                self._eval_iter = None
                self._eval_task_pb = None
            task_pb = self.get_task(pb.EVALUATION)
            if not task_pb.shard_name:
                return None
            self._eval_iter = iter(
                self._task_dataset(
                    self._task_from_pb(task_pb), Mode.EVALUATION
                )
            )
            self._eval_task_pb = task_pb

    def _zero_weight_item(self):
        """A template batch with weight 0 — keeps a starved host inside the
        collective without contributing to the global weighted loss."""
        if self._template_batch is None:
            raise RuntimeError(
                "host has no batch template: it never received any data, so "
                "it cannot synthesize a padding batch for the collective"
            )
        return self._template_batch, 0

    def _task_dataset(self, task, mode):
        """Batched dataset over one task's records (shared by the eval /
        predict paths)."""
        reader = self._task_data_service.data_reader
        from elasticdl_tpu.data.dataset import Dataset

        ds = Dataset.from_generator(lambda: reader.read_records(task))
        ds = resolve_dataset_fn(self.spec, reader)(
            ds, mode, reader.metadata
        )
        return ds.batch(self.minibatch_size)

    def _spmd_step(self, item):
        from elasticdl_tpu.training.trainer import _split_label

        if item is None:
            item = self._zero_weight_item()
        padded, n = item
        features, labels = _split_label(padded)
        weights = self.trainer.make_weights(self.minibatch_size, n)
        # Host-spill prepare runs on the LOCAL features before assembly
        # (the multi-host prepare is itself a host-level collective that
        # every host must enter this round — the lockstep loop ensures
        # every host is in this call).
        prepped = self.trainer._host_prepare(features)
        gf, gl, gw = self._spmd_ctx.assemble((prepped, labels, weights))
        self._ensure_state(padded)
        self.state, loss = self.trainer.train_step_assembled(
            self.state, gf, gl, gw
        )
        self._maybe_checkpoint()
        if n > 0:
            self._template_batch = (features, labels)
            self.losses.append(float(loss))
            if self._spmd_ctx.process_index == 0:
                self.report_version(int(self.state.step))
            self._task_data_service.report_record_done(n, "")

    def _run_spmd_job(self, with_train):
        """Unified lockstep job loop: eval-priority mode consensus every
        round (parallel/spmd.py ElasticSPMDLoop)."""
        from elasticdl_tpu.parallel.spmd import ElasticSPMDLoop

        with_eval = self.job_type in (
            JobType.TRAINING_WITH_EVALUATION,
            JobType.EVALUATION_ONLY,
        )
        loop = ElasticSPMDLoop(
            self._spmd_ctx,
            poll_train=self._poll_train if with_train else None,
            poll_eval=self._poll_eval if with_eval else None,
            train_step=self._spmd_step,
            eval_step=self._spmd_eval_step,
            idle_sleep_secs=min(0.2, self._task_data_service._wait_sleep_secs),
        )
        try:
            loop.run()
        except Exception as e:
            # Report in-flight tasks as failed so the master requeues them
            # promptly instead of waiting out the straggler watchdog, then
            # re-raise: a failed step desyncs the lockstep, so the job-level
            # answer is restart with a re-formed mesh (elastic recovery).
            err = "spmd step failed: %s" % e
            logger.error("%s\n%s", err, traceback.format_exc())
            if self._eval_task_pb is not None:
                self.report_task_result(self._eval_task_pb.task_id, err)
                self._eval_task_pb = None
            for task in list(
                self._task_data_service._pending_tasks
            ):
                self.report_task_result(task.task_id, err)
            raise
        self._process_train_end_callback_task_if_needed()

    def _spmd_eval_step(self, item):
        from elasticdl_tpu.training.trainer import _split_label

        if item is None:
            padded, n = self._zero_weight_item()
            task_pb = None
        else:
            (padded, n), task_pb = item
        features, labels = _split_label(padded)
        gf = self._spmd_ctx.assemble(self.trainer._host_prepare(features))
        self._ensure_state(padded)
        global_out = self.trainer.forward_assembled(self.state, gf)
        if task_pb is None:
            return
        self._template_batch = (features, labels)
        # slice the replicated global output back to this host's rows
        global_bsz = self.minibatch_size * self._spmd_ctx.num_processes
        rows = self._spmd_ctx.local_rows(global_bsz)

        def to_local(x):
            return np.asarray(x)[rows][:n]

        if isinstance(global_out, dict):
            outputs = {k: to_local(v) for k, v in global_out.items()}
        else:
            outputs = to_local(global_out)
        self.report_evaluation_metrics(
            outputs, np.asarray(labels)[:n], task_pb.model_version
        )


    def run(self):
        self.register()
        if self.job_type in (
            JobType.TRAINING_ONLY,
            JobType.TRAINING_WITH_EVALUATION,
        ):
            if self.spmd:
                self._run_spmd_job(with_train=True)
            else:
                self._train_and_evaluate()
            return self.state
        if self.job_type == JobType.EVALUATION_ONLY:
            if self.spmd:
                self._run_spmd_job(with_train=False)
            else:
                self._evaluate_only()
            return self.state
        if self.job_type == JobType.PREDICTION_ONLY:
            return self._predict_only()
        raise ValueError("Unknown job type %s" % self.job_type)

    def close(self):
        if self._channel is not None:
            self._channel.close()
