"""Key-value table flattening for MaxCompute/ODPS tables — the rebuild of
reference tools/odps_table_tools/ (transform_kv_table.py +
normalize_kv_udf.py, ~380 LoC): SQLFlow-style tables often store features
as one "k1:v1,k2:v2" string column; training wants one column per key.

Pieces (pure functions first, so the flattening logic is testable and
reusable host-side without an ODPS cluster; the cluster path is gated on
pyodps like the reader/writer):

* parse_kv_string          "k1:v1,k2:v2" -> {"k1": "v1", ...}
* analyze_feature_names    key-name discovery over the first N records
                           (reference get_feature_names, head(100))
* flatten_kv_record        one record -> per-feature values, missing -> ""
* KVFlatter                the UDTF class (reference normalize_kv_udf
                           KVFlatter.process arg protocol: kv value,
                           *append columns, names csv, pair sep, kv sep)
* generate_transform_sql   CREATE TABLE ... AS SELECT <udtf>(...) FROM ...
* transform_kv_table       end-to-end driver against a live ODPS entry

Separator naming: `pair_sep` splits the string into pairs (reference
call sites pass ","), `kv_sep` splits key from value (":").
"""

import time

# Defaults matching the reference tables' "k1:v1,k2:v2" layout.
PAIR_SEPARATOR = ","
KV_SEPARATOR = ":"

UDF_CLASS_NAME = "KVFlatter"
ANALYZE_FEATURE_RECORDS_COUNT = 100

_TRANSFORM_SQL_TEMPLATE = (
    "CREATE TABLE IF NOT EXISTS {output_table} LIFECYCLE 7 AS \n"
    "    SELECT \n"
    "        {udf} \n"
    "    FROM {input_table}"
)


def parse_kv_string(kvs_string, pair_sep=PAIR_SEPARATOR,
                    kv_sep=KV_SEPARATOR):
    """"k1:v1,k2:v2" -> {"k1": "v1", "k2": "v2"}; malformed pairs (no
    kv_sep, or extra separators) are skipped, as in the reference."""
    out = {}
    for pair in kvs_string.split(pair_sep):
        key_and_value = pair.split(kv_sep)
        if len(key_and_value) == 2:
            out[key_and_value[0]] = key_and_value[1]
    return out


def analyze_feature_names(records, kv_value_fn=None,
                          pair_sep=PAIR_SEPARATOR, kv_sep=KV_SEPARATOR,
                          max_records=ANALYZE_FEATURE_RECORDS_COUNT):
    """Discover the union of key names over the first `max_records`
    records, sorted (reference get_feature_names over table.head(100)).
    `kv_value_fn` extracts the kv string from a record (default: the
    record itself is the string)."""
    names = set()
    for i, record in enumerate(records):
        if i >= max_records:
            break
        value = kv_value_fn(record) if kv_value_fn is not None else record
        names.update(parse_kv_string(value, pair_sep, kv_sep).keys())
    return sorted(names)


def flatten_kv_record(kvs_string, feature_names,
                      pair_sep=PAIR_SEPARATOR, kv_sep=KV_SEPARATOR):
    """One kv string -> [value for each feature name], missing keys
    becoming "" (reference normalize_kv_udf parse_kv_string_to_dict)."""
    kv = parse_kv_string(kvs_string, pair_sep, kv_sep)
    return [kv.get(name, "") for name in feature_names]


class KVFlatter(object):
    """Local twin of the UDTF that runs the flattening inside ODPS SQL
    (host-side normalization + tests; the cluster-side resource is the
    self-contained BaseUDTF source UDF_RESOURCE_SOURCE below — a plain
    object here because odps.udf only exists inside the ODPS runtime).

    Argument protocol (must match generate_transform_sql's projection,
    which is the reference's — normalize_kv_udf.py KVFlatter.process):
    args[0] = kv column value; args[1:-3] = append-column values (copied
    through, stringified); args[-3] = comma-joined feature names;
    args[-2] = pair separator; args[-1] = key-value separator.
    """

    def __init__(self):
        self.collected = []

    def forward(self, *values):
        self.collected.append(list(values))

    def process(self, *args):
        if len(args) < 4:
            raise ValueError(
                "The input values number can not be less than 4"
            )
        feature_names = args[-3].split(",")
        pair_sep, kv_sep = args[-2], args[-1]
        values = flatten_kv_record(args[0], feature_names, pair_sep, kv_sep)
        for append_value in args[1:-3]:
            values.append(str(append_value))
        self.forward(*values)


# The source uploaded as the ODPS python resource: a real BaseUDTF whose
# forward() emits into the SQL engine. Self-contained (no imports from
# this package — the cluster only has the resource file) with the same
# process() protocol as the local KVFlatter above.
UDF_RESOURCE_SOURCE = '''\
from odps.udf import BaseUDTF


class KVFlatter(BaseUDTF):
    """Flatten "k1:v1,k2:v2" kv strings into per-feature columns."""

    def process(self, *args):
        if len(args) < 4:
            raise ValueError(
                "The input values number can not be less than 4"
            )
        feature_names = args[-3].split(",")
        pair_sep, kv_sep = args[-2], args[-1]
        kv = {}
        for pair in args[0].split(pair_sep):
            key_and_value = pair.split(kv_sep)
            if len(key_and_value) == 2:
                kv[key_and_value[0]] = key_and_value[1]
        values = [kv.get(name, "") for name in feature_names]
        for append_value in args[1:-3]:
            values.append(str(append_value))
        self.forward(*values)
'''


def generate_transform_sql(
    input_table,
    output_table,
    feature_names,
    kv_column,
    udf_function,
    append_columns=None,
    input_table_partition=None,
    pair_sep=PAIR_SEPARATOR,
    kv_sep=KV_SEPARATOR,
):
    """The CREATE-TABLE-AS-SELECT statement flattening `kv_column` into
    one column per feature name, carrying `append_columns` (e.g. the
    label) through (reference generate_sql)."""
    append_columns = list(append_columns or [])
    output_columns = list(feature_names) + append_columns
    input_columns = [kv_column] + append_columns
    udf = (
        '{udf}({input_cols},\n'
        '    "{features}", "{pair_sep}", "{kv_sep}")\n'
        '    as ({output_cols})'.format(
            udf=udf_function,
            input_cols=",".join(input_columns),
            features=",".join(feature_names),
            output_cols=",".join(output_columns),
            pair_sep=pair_sep,
            kv_sep=kv_sep,
        )
    )
    sql = _TRANSFORM_SQL_TEMPLATE.format(
        output_table=output_table, udf=udf, input_table=input_table
    )
    if input_table_partition:
        sql += " where {}".format(input_table_partition)
    return sql


def transform_kv_table(
    odps_entry,
    input_table,
    output_table,
    kv_column,
    append_columns=None,
    input_table_partition=None,
    pair_sep=PAIR_SEPARATOR,
    kv_sep=KV_SEPARATOR,
    udf_file_path=None,
):
    """End-to-end driver against a live ODPS entry (reference
    transform_kv_table.py main): analyze key names from the table head,
    register the UDTF resource+function, run the transform SQL, drop the
    temporaries. Requires pyodps (the entry object)."""
    source = odps_entry.get_table(input_table)
    names = analyze_feature_names(
        source.head(
            ANALYZE_FEATURE_RECORDS_COUNT, partition=input_table_partition
        ),
        kv_value_fn=lambda rec: rec[kv_column],
        pair_sep=pair_sep,
        kv_sep=kv_sep,
    )
    stamp = int(time.time())
    resource_name = "edl_tpu_kv_flat_%d.py" % stamp
    function_name = "edl_tpu_kv_flat_func_%d" % stamp
    if udf_file_path is not None:
        with open(udf_file_path) as f:
            resource = odps_entry.create_resource(
                resource_name, type="py", file_obj=f
            )
    else:
        import io

        resource = odps_entry.create_resource(
            resource_name, type="py",
            file_obj=io.StringIO(UDF_RESOURCE_SOURCE),
        )
    try:
        function = odps_entry.create_function(
            function_name,
            class_type="%s.%s" % (resource_name[:-3], UDF_CLASS_NAME),
            resources=[resource],
        )
        try:
            sql = generate_transform_sql(
                input_table,
                output_table,
                names,
                kv_column,
                function_name,
                append_columns=append_columns,
                input_table_partition=input_table_partition,
                pair_sep=pair_sep,
                kv_sep=kv_sep,
            )
            instance = odps_entry.run_sql(sql)
            instance.wait_for_success()
        finally:
            odps_entry.delete_function(function_name)
            function = None  # noqa: F841
    finally:
        odps_entry.delete_resource(resource_name)
    return names
