"""C6/C7 — lock-graph rules: EDL003 lock-order deadlock detection and
EDL004 wrong-lock-held.

**EDL003** builds the global lock-acquisition graph: a node per lock
attribute ``(Class, _lock)``, an edge A→B whenever B is acquired while
A is held — directly (nested ``with`` blocks) or through method calls
(``self.m()``, ``self.attr.m()`` with the attribute's class resolved
by the project index, local aliases of either, and ``ClassName(...)``
construction), with each callee's TRANSITIVELY acquired locks computed
by a fixpoint over the call graph. Any cycle is a potential deadlock:

* a self-edge on a non-reentrant ``Lock`` is the re-entry deadlock —
  the PR 5 shape where ``report`` held the dispatcher lock while
  ``complete_task`` → ``try_to_create_new_job`` → ``create_tasks``
  re-acquired it;
* a multi-node cycle is the classic AB/BA ordering deadlock across
  objects (dispatcher→evaluation-service edges meeting
  evaluation-service→dispatcher edges).

``RLock``/``Condition`` (reentrant by default) self-edges are fine and
never reported. The rule runs per-module (everything resolvable inside
one file, which is what the fixtures exercise) AND repo-wide
(`check_repo`, where cross-module bindings let dispatcher↔eval-service
chains resolve); repo-level reporting skips cycles wholly inside one
module to avoid duplicating the per-module findings.

**EDL004** — for a class holding TWO OR MORE locks, infer each
guarded attribute's lock BINDING: the lock(s) held by every locked
write, or — when the writes disagree, which is precisely the buggy
case — the strict-majority lock (a single wrong-lock write must not
dissolve the binding that convicts it; with no majority the binding
is ambiguous and the rule stays quiet). An access (read or write)
holding a non-empty lock set DISJOINT from the binding is guarded by
the wrong lock — invisible to EDL001/002, which treat any held lock
as safe.
Unlocked accesses stay EDL001/002's business; ``*_locked`` methods are
skipped (the convention does not say WHICH lock the caller holds) and
``__init__`` is single-threaded by construction.

Deliberately not modeled: lock acquisitions inside nested ``def``s
(they run later, usually on another thread — their nesting context is
not this function's), ``acquire()``-method locking (the codebase idiom
is ``with``), and receivers that do not resolve through the project
index (unresolvable = silent, never a guess).
"""

import ast

from elasticdl_tpu.analysis.cfg import walk_shallow
from elasticdl_tpu.analysis.core import (
    Finding,
    Rule,
    iter_python_files,
    register,
)
from elasticdl_tpu.analysis.dataflow import (
    ModuleIndex,
    ProjectIndex,
    _self_attr,
)
from elasticdl_tpu.analysis.lock_rules import _MUTATORS


def _lock_in_item(expr, info, classes):
    """Lock key (class_name, attr) for a with-item context expression:
    ``self._x`` or ``ClassName._x``."""
    attr = _self_attr(expr)
    if attr is not None and attr in info.lock_attrs:
        return (info.name, attr)
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)):
        cls = classes.get(expr.value.id)
        if cls is not None and expr.attr in cls.lock_attrs:
            return (cls.name, expr.attr)
    return None


class _MethodLockScan(object):
    """One pass over a method: every lock acquisition and every call
    site, each with the set of locks HELD at that point."""

    def __init__(self, index, info, fn):
        self.index = index
        self.info = info
        self.fn = fn
        self.aliases = {}     # local name -> ("selfattr", attr)
        self.acquires = []    # (lockkey, heldset frozenset, line)
        self.calls = []       # ((class, method), heldset, line)
        self.accesses = []    # (attr, line, is_write, heldset)
        entry = frozenset()
        if fn.name.endswith("_locked"):
            single = info.single_lock()
            if single:
                entry = frozenset([(info.name, single)])
        self._scan_alias_prepass()
        self._body(fn.body, entry)

    def _scan_alias_prepass(self):
        for stmt in self.fn.body:
            self._alias_stmt(stmt)

    def _alias_stmt(self, stmt):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                attr = _self_attr(node.value)
                if isinstance(tgt, ast.Name) and attr is not None:
                    self.aliases[tgt.id] = ("selfattr", attr)

    # ------------------------------------------------------------ walk

    def _body(self, stmts, held):
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held):
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                self._expr(item.context_expr, held)
                key = _lock_in_item(item.context_expr, self.info,
                                    self.index.classes)
                if key is not None:
                    self.acquires.append((key, held, stmt.lineno))
                    acquired.append(key)
            self._body(stmt.body, held | frozenset(acquired))
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scope: runs later, context unknown
        for child_stmts, child_exprs in _stmt_parts(stmt):
            for e in child_exprs:
                self._expr(e, held)
            self._body(child_stmts, held)

    def _expr(self, expr, held):
        if expr is None:
            return
        for node in walk_shallow(expr):
            if isinstance(node, ast.Call):
                self._call(node, held)
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
                    recv = fn.value
                    if isinstance(recv, ast.Subscript):
                        recv = recv.value  # self.x[k].append(...)
                    attr = _self_attr(recv)
                    if (attr is not None
                            and attr not in self.info.lock_attrs):
                        self.accesses.append(
                            (attr, node.lineno, True, held)
                        )
            elif isinstance(node, ast.Subscript):
                if not isinstance(node.ctx, ast.Load):
                    attr = _self_attr(node.value)  # self.x[k] = v
                    if (attr is not None
                            and attr not in self.info.lock_attrs):
                        self.accesses.append(
                            (attr, node.lineno, True, held)
                        )
            elif isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None and attr not in self.info.lock_attrs:
                    self.accesses.append((
                        attr, node.lineno,
                        not isinstance(node.ctx, ast.Load), held,
                    ))

    def _call(self, call, held):
        fn = call.func
        callee = None
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            target = self.index.resolve_receiver(
                self.info, recv, local_aliases=self.aliases
            )
            if target is not None and fn.attr in target.methods:
                callee = (target.name, fn.attr)
        else:
            cname = None
            if isinstance(fn, ast.Name) and fn.id in self.index.classes:
                cname = fn.id
            if cname:
                callee = (cname, "__init__")
        if callee is not None:
            self.calls.append((callee, held, call.lineno))


def _stmt_parts(stmt):
    """((nested statement lists), (evaluated expressions)) of one
    statement — enough structure to keep held-sets correct without a
    full CFG (lock nesting is lexical in this codebase)."""
    if isinstance(stmt, ast.If):
        return [(stmt.body, (stmt.test,)), (stmt.orelse, ())]
    if isinstance(stmt, ast.While):
        return [(stmt.body, (stmt.test,)), (stmt.orelse, ())]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [(stmt.body, (stmt.iter,)), (stmt.orelse, ())]
    if isinstance(stmt, ast.Try) or type(stmt).__name__ == "TryStar":
        parts = [(stmt.body, ()), (stmt.orelse, ()),
                 (stmt.finalbody, ())]
        for h in stmt.handlers:
            parts.append((h.body, ()))
        return parts
    exprs = []
    for node in ast.iter_child_nodes(stmt):
        if isinstance(node, ast.expr):
            exprs.append(node)
    return [((), tuple(exprs))]


# ------------------------------------------------------------ lock graph


class LockGraph(object):
    def __init__(self, index):
        self.index = index
        self.kind = {}        # lockkey -> 'lock' | 'rlock' | 'cond'
        self.edges = {}       # lockkey -> {lockkey}
        self.evidence = {}    # (a, b) -> (path, line, text)
        self.scans = {}       # (class, method) -> scan
        self._build()

    def _build(self):
        for info in self.index.classes.values():
            for attr, kind in info.lock_attrs.items():
                self.kind[(info.name, attr)] = kind
            for name, fn in info.methods.items():
                self.scans[(info.name, name)] = _MethodLockScan(
                    self.index, info, fn
                )
        # transitive acquisitions per method
        acquired = {
            key: {lk for lk, _h, _l in scan.acquires}
            for key, scan in self.scans.items()
        }
        changed = True
        while changed:
            changed = False
            for key, scan in self.scans.items():
                for callee, _held, _line in scan.calls:
                    extra = acquired.get(callee, ())
                    before = len(acquired[key])
                    acquired[key] |= set(extra)
                    changed = changed or len(acquired[key]) != before

        for key, scan in self.scans.items():
            info = self.index.classes[key[0]]
            path = info.path
            for lk, held, line in scan.acquires:
                for h in held:
                    self._edge(h, lk, path, line,
                               "%s.%s acquires %s under %s"
                               % (key[0], key[1], _fmt(lk), _fmt(h)))
            for callee, held, line in scan.calls:
                if not held:
                    continue
                for lk in acquired.get(callee, ()):
                    for h in held:
                        self._edge(
                            h, lk, path, line,
                            "%s.%s calls %s.%s (which acquires %s) "
                            "under %s" % (key[0], key[1], callee[0],
                                          callee[1], _fmt(lk), _fmt(h)),
                        )

    def _edge(self, a, b, path, line, text):
        if a == b and self.kind.get(a) in ("rlock", "cond"):
            return  # reentrant self-acquisition is legal
        self.edges.setdefault(a, set()).add(b)
        self.evidence.setdefault((a, b), (path, line, text))

    def cycles(self):
        """Minimal reportable cycles: self-edges plus one shortest
        cycle through each edge that closes back (deduplicated by the
        canonical rotation of the lock sequence)."""
        out = {}
        for a, succs in sorted(self.edges.items()):
            if a in succs:
                out.setdefault((a,), [a, a])
        for a in sorted(self.edges):
            path = self._find_cycle(a)
            if path:
                nodes = tuple(path[:-1])
                start = nodes.index(min(nodes))
                canon = nodes[start:] + nodes[:start]
                if len(nodes) > 1:
                    out.setdefault(canon, path)
        return out

    def _find_cycle(self, start):
        # BFS back to start
        frontier = [(start, [start])]
        seen = set()
        while frontier:
            node, path = frontier.pop(0)
            for succ in sorted(self.edges.get(node, ())):
                if succ == start and len(path) > 1:
                    return path + [start]
                if succ not in seen and succ != start:
                    seen.add(succ)
                    frontier.append((succ, path + [succ]))
        return None


def _fmt(lockkey):
    return "%s.%s" % lockkey


def _cycle_findings(graph, index, skip_single_module=False):
    findings = []
    for canon, path in sorted(graph.cycles().items()):
        classes = {index.classes[c] for c, _a in canon
                   if c in index.classes}
        paths = {c.path for c in classes}
        if skip_single_module and len(paths) <= 1:
            continue  # check_module already reported it
        detail = "->".join(_fmt(k) for k in list(canon) + [canon[0]])
        hops = []
        line = 0
        first_path = sorted(paths)[0] if paths else "<unknown>"
        for i in range(len(path) - 1):
            ev = graph.evidence.get((path[i], path[i + 1]))
            if ev:
                hops.append("%s (%s:%d)" % (ev[2], ev[0], ev[1]))
                if not line:
                    line = ev[1]
                    first_path = ev[0]
        if len(canon) == 1:
            msg = ("re-entry deadlock: non-reentrant %s is acquired "
                   "while already held — %s"
                   % (_fmt(canon[0]), "; ".join(hops)))
        else:
            msg = ("lock-order cycle (potential AB/BA deadlock): %s — %s"
                   % (detail, "; ".join(hops)))
        findings.append(Finding(
            "EDL003", first_path, line, "lock-graph", detail, msg,
        ))
    return findings


@register
class LockOrderRule(Rule):
    """EDL003 — see module docstring."""

    id = "EDL003"
    name = "lock-order-deadlock"

    def check_module(self, tree, lines, path):
        index = ProjectIndex([ModuleIndex(tree, path)])
        if not any(c.lock_attrs for c in index.classes.values()):
            return []
        return _cycle_findings(LockGraph(index), index)

    def check_repo(self, root, paths=None):
        import os

        modules = []
        for fp in iter_python_files(paths or [root]):
            try:
                with open(fp) as f:
                    tree = ast.parse(f.read(), filename=fp)
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue
            rel = os.path.relpath(fp, root).replace(os.sep, "/")
            modules.append(ModuleIndex(tree, rel))
        index = ProjectIndex(modules)
        if not any(c.lock_attrs for c in index.classes.values()):
            return []
        return _cycle_findings(LockGraph(index), index,
                               skip_single_module=True)


@register
class WrongLockRule(Rule):
    """EDL004 — see module docstring."""

    id = "EDL004"
    name = "wrong-lock-held"

    def check_module(self, tree, lines, path):
        index = ProjectIndex([ModuleIndex(tree, path)])
        findings = []
        for info in index.classes.values():
            if len(info.lock_attrs) < 2:
                continue
            findings.extend(self._check_class(index, info, path))
        return findings

    def _check_class(self, index, info, path):
        scans = {}
        for name, fn in info.methods.items():
            scans[name] = _MethodLockScan(index, info, fn)

        # binding: the lock(s) every locked write holds — or, when the
        # writes DISAGREE (which is precisely the buggy case: one
        # writer under the wrong lock), the strict-majority lock, so a
        # single offending write cannot dissolve the binding that
        # convicts it. No majority = ambiguous = no binding.
        write_sets = {}
        for name, scan in scans.items():
            if name == "__init__":
                continue
            for attr, _line, is_write, held in scan.accesses:
                if is_write and held:
                    write_sets.setdefault(attr, []).append(held)
        binding = {}
        for attr, sets in write_sets.items():
            inter = frozenset.intersection(*sets)
            if inter:
                binding[attr] = set(inter)
                continue
            counts = {}
            for held in sets:
                for key in held:
                    counts[key] = counts.get(key, 0) + 1
            top = max(sorted(counts), key=lambda k: counts[k])
            if counts[top] * 2 > len(sets):
                binding[attr] = {top}
        for name, scan in scans.items():
            if name == "__init__" or name.endswith("_locked"):
                continue
            scope = "%s.%s" % (info.name, name)
            for attr, line, is_write, held in scan.accesses:
                bound = binding.get(attr)
                if not bound or not held:
                    continue  # unbound, or EDL001/002's territory
                if held & bound:
                    continue
                yield Finding(
                    "EDL004", path, line, scope, attr,
                    "%s of %r under %s, but every locked write binds "
                    "it to %s — wrong lock held (torn state both "
                    "sides)" % (
                        "write" if is_write else "read", attr,
                        "/".join(sorted(_fmt(k) for k in held)),
                        "/".join(sorted(_fmt(k) for k in bound)),
                    ),
                )
