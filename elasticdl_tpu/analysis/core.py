"""Rule registry, pragma suppression, baseline file, and the runner.

A `Rule` inspects one module (AST + source) and yields `Finding`s. A
finding is identified by a LINE-INDEPENDENT fingerprint
``(rule, path, scope, detail)`` so unrelated edits never churn the
baseline; `scope` is ``Class.method`` (or ``<module>``) and `detail`
names the offending thing (an attribute, a call).

Suppression, two tiers with different intent:

* pragma — ``# edl-lint: disable=EDL002`` on the offending line (or
  ``disable=all``): for code whose SAFETY ARGUMENT lives right there in
  a comment. Prefer this when the justification is local.
* baseline — a checked-in JSON file of vetted exceptions, each with a
  mandatory one-line ``reason``: for findings whose justification is
  architectural (e.g. "worker-side state is single-threaded by
  construction"). STALE entries fail the run: every baseline line must
  match a live finding, so the file can only shrink or be consciously
  re-vetted — it cannot silently rot into a blanket waiver.
"""

import ast
import json
import os

_PRAGMA = "# edl-lint:"


class Finding(object):
    def __init__(self, rule, path, line, scope, detail, message):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.scope = scope
        self.detail = detail
        self.message = message

    @property
    def fingerprint(self):
        return (self.rule, self.path, self.scope, self.detail)

    def format(self):
        return "%s:%d: %s [%s] %s: %s" % (
            self.path, self.line, self.rule, self.scope, self.detail,
            self.message,
        )


class Rule(object):
    """Base checker. Subclasses set `id` (EDLnnn), `name`, and a
    docstring that doubles as the rule catalogue entry; implement
    `check_module(tree, lines, path)` yielding Findings. Rules that
    inspect something other than Python modules (the proto-drift gate)
    override `check_repo(root)` instead and leave check_module empty."""

    id = None
    name = None

    def check_module(self, tree, lines, path):
        return ()

    def check_repo(self, root):
        return ()


_REGISTRY = {}


def register(rule_cls):
    rule = rule_cls()
    if rule.id in _REGISTRY:
        raise ValueError("duplicate rule id %s" % rule.id)
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules():
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# ------------------------------------------------------------------ pragma


def _pragma_names(text):
    """The rule-name set of a ``# edl-lint: disable=...`` pragma in
    `text`, else None."""
    idx = text.find(_PRAGMA)
    if idx < 0:
        return None
    spec = text[idx + len(_PRAGMA):].strip()
    spec = spec.split()[0] if spec else ""
    if not spec.startswith("disable="):
        return None
    return {n.strip() for n in spec[len("disable="):].split(",")}


def pragma_line_for(finding, lines):
    """The 1-based line number of the pragma suppressing this finding
    (same line or the line directly above), else None. EDL000 findings
    are never pragma-suppressed — a dead ``disable=all`` would
    otherwise silence its own unused-suppression report."""
    if finding.rule == "EDL000":
        return None
    for lineno in (finding.line, finding.line - 1):
        if not 1 <= lineno <= len(lines):
            continue
        names = _pragma_names(lines[lineno - 1])
        if names is None:
            continue
        if "all" in names or finding.rule in names:
            return lineno
    return None


def suppressed_by_pragma(finding, lines):
    """True when the finding's source line (or the line directly above
    it) carries ``# edl-lint: disable=<rule>`` naming this rule or
    ``all``."""
    return pragma_line_for(finding, lines) is not None


def collect_pragmas(lines):
    """[(lineno, frozenset(rule names))] for every pragma line."""
    out = []
    for i, text in enumerate(lines, 1):
        names = _pragma_names(text)
        if names is not None:
            out.append((i, frozenset(names)))
    return out


def unused_pragma_findings(path, lines, used_lines, emitted_ids,
                           full_run):
    """EDL000 findings for pragmas that suppressed NOTHING in this
    run — the pragma mirror of the stale-baseline failure: a dead
    suppression is a standing invitation to hide the next real
    finding on that line.

    A pragma is only judged when this run could have vindicated it:
    every rule it names was among the emitted ids of the selected
    checkers (``disable=all`` needs the full registry)."""
    out = []
    for lineno, names in collect_pragmas(lines):
        if lineno in used_lines:
            continue
        if "all" in names:
            if not full_run:
                continue
        elif not (names - {"all"} <= emitted_ids):
            continue
        detail = "disable=%s" % ",".join(sorted(names))
        out.append(Finding(
            "EDL000", path, lineno, "<pragma>", detail,
            "unused suppression: this pragma suppresses zero "
            "findings — the code it vetted is gone or fixed; delete "
            "the pragma (or run --fix-pragmas)",
        ))
    return out


def strip_pragma(text):
    """`text` with its ``# edl-lint: ...`` pragma removed; None when
    the whole line was only the pragma (delete the line)."""
    idx = text.find(_PRAGMA)
    if idx < 0:
        return text
    head = text[:idx].rstrip()
    return head if head else None


class UnusedPragmaRule(Rule):
    """EDL000 — unused-suppression detection. The detection itself
    runs inside the per-file pass (it needs the pragma-application
    bookkeeping), so this class only anchors the id in the registry
    for --select / --list-rules."""

    id = "EDL000"
    name = "unused-suppression"


register(UnusedPragmaRule)


# ---------------------------------------------------------------- baseline


class BaselineError(Exception):
    pass


class Baseline(object):
    """The checked-in vetted-exception list (.edl-lint-baseline.json).

    Every entry carries a mandatory one-line justification; an entry
    that no longer matches a live finding is itself an error."""

    def __init__(self, entries=None, path=None):
        self.path = path
        self.entries = list(entries or [])
        for e in self.entries:
            for key in ("rule", "path", "scope", "detail", "reason"):
                if not e.get(key):
                    raise BaselineError(
                        "baseline entry %r is missing %r (every vetted "
                        "exception needs a one-line justification)"
                        % (e, key)
                    )

    @classmethod
    def load(cls, path):
        if not os.path.exists(path):
            return cls(path=path)
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("entries", []), path=path)

    @classmethod
    def from_findings(cls, findings, reason, path=None):
        entries = [
            {
                "rule": f.rule, "path": f.path, "scope": f.scope,
                "detail": f.detail, "reason": reason,
            }
            for f in findings
        ]
        return cls(entries, path=path)

    def save(self, path=None):
        path = path or self.path
        with open(path, "w") as f:
            json.dump(
                {"version": 1, "entries": self.entries}, f, indent=2,
                sort_keys=True,
            )
            f.write("\n")

    def _fingerprints(self):
        return {
            (e["rule"], e["path"], e["scope"], e["detail"]): e
            for e in self.entries
        }

    def apply(self, findings):
        """Split into (unsuppressed findings, stale entries)."""
        fps = self._fingerprints()
        live = set()
        out = []
        for f in findings:
            if f.fingerprint in fps:
                live.add(f.fingerprint)
            else:
                out.append(f)
        stale = [e for fp, e in sorted(fps.items()) if fp not in live]
        return out, stale


# ------------------------------------------------------------------ runner

#: path fragments never analyzed: generated code, the fixture battery
#: (which exists to TRIGGER rules), and vendored/native sources
DEFAULT_EXCLUDES = (
    "proto/elasticdl_pb2.py",
    "tests/lint_fixtures/",
)


def iter_python_files(paths, excludes=DEFAULT_EXCLUDES):
    for path in paths:
        if os.path.isfile(path):
            norm = path.replace(os.sep, "/")
            if path.endswith(".py") and not any(
                ex in norm for ex in excludes
            ):
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git")
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                norm = full.replace(os.sep, "/")
                if any(ex in norm for ex in excludes):
                    continue
                yield full


def _check_one_file(args):
    """Module-rule pass over ONE file — the process-pool work unit
    (top-level so it pickles; rules are reconstructed from ids in the
    child, where the registry import already ran)."""
    path, rel, rule_ids, full_run = args
    import elasticdl_tpu.analysis  # noqa: F401 - loads the registry

    rules = [r for r in all_rules() if r.id in rule_ids]
    findings, errors = [], []
    try:
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
    except (SyntaxError, UnicodeDecodeError) as e:
        return findings, ["%s: unparseable: %s" % (path, e)]
    lines = src.splitlines()
    used_pragma_lines = set()
    for rule in rules:
        for finding in rule.check_module(tree, lines, rel):
            pragma_line = pragma_line_for(finding, lines)
            if pragma_line is None:
                findings.append(finding)
            else:
                used_pragma_lines.add(pragma_line)
    if "EDL000" in rule_ids:
        from elasticdl_tpu.analysis.lint import RULE_FAMILIES

        emitted = frozenset(
            fid for rid in rule_ids
            for fid in RULE_FAMILIES.get(rid, (rid,))
        )
        findings.extend(unused_pragma_findings(
            rel, lines, used_pragma_lines, emitted, full_run,
        ))
    return findings, errors


def run_rules(paths, rules=None, root=None, excludes=DEFAULT_EXCLUDES,
              jobs=1, cache=None):
    """Run `rules` over every Python file under `paths` plus each
    rule's repo-level check. Returns (findings, errors): findings are
    pragma-filtered but NOT baseline-filtered (the caller owns the
    baseline so --write-baseline can see everything).

    `jobs` > 1 fans the per-file module passes out over a process
    pool (findings and errors merge deterministically: results are
    re-sorted, so parallel output is byte-identical to serial);
    repo-level checks always run in this process.

    `cache` (a `cache.ResultCache`) memoizes per-file results by
    content hash: hits skip the file entirely, misses are stored, and
    the cache is saved before returning. Repo-level checks are never
    cached."""
    rules = rules if rules is not None else all_rules()
    rule_ids = frozenset(r.id for r in rules)
    full_run = rule_ids == frozenset(r.id for r in all_rules())
    findings, errors = [], []
    work, shas = [], []
    for path in iter_python_files(paths, excludes=excludes):
        rel = os.path.relpath(path, root) if root else path
        rel = rel.replace(os.sep, "/")
        sha = None
        if cache is not None:
            from elasticdl_tpu.analysis.cache import file_sha

            try:
                sha = file_sha(path)
            except OSError:
                sha = None
            if sha is not None:
                hit = cache.get(rel, sha)
                if hit is not None:
                    findings.extend(hit[0])
                    errors.extend(hit[1])
                    continue
        work.append((path, rel, rule_ids, full_run))
        shas.append(sha)

    if jobs > 1 and len(work) > 1:
        import multiprocessing

        with multiprocessing.Pool(min(jobs, len(work))) as pool:
            results = pool.map(_check_one_file, work,
                               chunksize=max(1, len(work) // (4 * jobs)))
    else:
        results = [_check_one_file(item) for item in work]
    for item, sha, (fs, es) in zip(work, shas, results):
        findings.extend(fs)
        errors.extend(es)
        if cache is not None and sha is not None:
            cache.put(item[1], sha, fs, es)
    if cache is not None:
        cache.save()

    if root:
        for rule in rules:
            findings.extend(rule.check_repo(root))
    # CFG finally-copies and the module+repo lock-graph overlap can
    # produce byte-identical findings; report each once
    seen, unique = set(), []
    for f in sorted(findings,
                    key=lambda f: (f.path, f.line, f.rule, f.detail)):
        key = (f.fingerprint, f.line)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    errors.sort()
    return unique, errors
