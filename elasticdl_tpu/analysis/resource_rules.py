"""C8 — must-release resource tracking (EDL501).

Registered acquire/release pairs, checked PATH-SENSITIVELY on the
function's CFG: from the acquisition point, every path to the
function's exit — normal return, fall-off-the-end, or an exception
propagating out — must pass a release (or transfer ownership). The
PR 4 circuit-breaker probe leak as a lint rule: a HALF_OPEN probe slot
acquired and then lost on the non-transient-failure branch silently
evicted a replica from rotation forever.

Two resource shapes:

* **value-bound** — ``x = <something>.start_span(...)``: the HANDLE
  carries the obligation. Tracked only when assigned to a plain local
  name (an attribute/subscript target is an immediate ownership
  transfer). Settled by ``x.<release>()``, by reassigning ``x``, or by
  ESCAPE: returning/yielding x, passing x as a call argument, storing
  x anywhere (``self.y = x``, ``d[k] = x``, ``lst = x``), or raising
  with it — whoever receives the handle owns the release. A
  return/raise escape settles only the path on which the statement
  COMPLETES: if its evaluation raises inside a try, the handler paths
  still carry the obligation (``return f.read()`` does not excuse an
  ``except`` branch that drops ``f``).
  Registered: ``start_span``→``finish``, ``open``→``close`` (when not
  in a ``with``), ``build_channel``→``close``, and the supervisor
  launcher's ``Popen``→``wait``/``communicate`` (a killed-but-never-
  waited child is a zombie until its parent exits).

* **receiver-bound** — ``rep.begin_dispatch()``: the RECEIVER owns a
  slot until a paired method releases it. Settled by
  ``<same receiver>.<release>()`` or by the receiver's BASE name
  escaping (returned/passed/stored — e.g. ``_acquire_replica`` returns
  the replica whose breaker probe it holds; the caller inherits the
  obligation, which is a cross-function contract this rule does not
  police). ``self.<attr>`` receivers are skipped entirely: their
  lifecycle is cross-method by design (an allocator owned by the
  engine seats in ``insert`` and frees on completion).
  Registered: ``breaker.acquire``→``record_success``/
  ``record_failure``/``release_probe`` (the three-way settle from
  PR 4's fix), ``begin_dispatch``→``end_dispatch``,
  ``begin_poll``→``end_poll``, ``<alloc>.alloc``→``free``, the
  prefix-shared pool's refcount pairs ``<alloc>.incref``/``share``/
  ``cow``→``decref``/``free`` (a leaked block reference pins arena
  rows forever; the CoW draw owns its copy like any table block), and
  the replica supervisor's seat lifecycle
  ``<supervis*>.spawn``→``adopt``/``reap`` + ``begin_drain``→
  ``retire``/``reap`` (serving/autoscaler.py: a seat lost between
  spawn and adoption is an orphan process no journal remembers).

Guarded acquisition idioms are recognized so the common "probe or
bail" shape does not false-positive:

    if not rep.breaker.acquire(now):   # acquired ONLY on fall-through
        return None
    if rep.breaker.acquire(now):       # acquired ONLY in the body
        ...

The exception model is cfg.py's selective one: leak paths come from
explicit control flow (branches, early returns, handlers, re-raises),
not from "any statement may raise" — that keeps
``f = open(p); f.read(); f.close()`` quiet while still catching every
handler branch that forgets to settle.
"""

import ast

from elasticdl_tpu.analysis.cfg import (
    EXIT,
    RAISE_EXIT,
    TEST,
    build_cfg,
    walk_shallow,
)
from elasticdl_tpu.analysis.core import Finding, Rule, register
from elasticdl_tpu.analysis.dataflow import leak_paths

#: receiver-bound pairs: acquire attr -> (releases, receiver hint —
#: a substring the receiver spelling must contain, or None for any)
RECEIVER_PAIRS = {
    "acquire": (
        frozenset(["record_success", "record_failure",
                   "release_probe"]),
        "breaker",
    ),
    "begin_dispatch": (frozenset(["end_dispatch"]), None),
    "begin_poll": (frozenset(["end_poll"]), None),
    "alloc": (frozenset(["free"]), "alloc"),
    # the prefix-shared paged KV pool's refcount discipline
    # (serving/kv_pool.py): a block reference taken by incref (or a
    # whole shared chain seated by share/seat) must drop via decref or
    # the slot-level free on EVERY path — a leaked refcount pins the
    # block (and its arena rows) forever
    "incref": (frozenset(["decref", "free"]), "alloc"),
    "share": (frozenset(["decref", "free"]), "alloc"),
    # a CoW fault draws a block from the slot's reservation; the copy
    # is owned like any other table block and must settle through the
    # same decref/free discipline
    "cow": (frozenset(["decref", "free"]), "alloc"),
    # the replica supervisor's seat lifecycle (serving/autoscaler.py):
    # a spawned seat must be adopted into the roster or reaped on
    # EVERY path — a seat lost between Popen and adoption is an orphan
    # process no journal remembers; a drain begun must end in retire
    # (or reap, the escalation) or the seat leaks mid-drain forever
    "spawn": (frozenset(["adopt", "reap"]), "supervis"),
    "begin_drain": (frozenset(["retire", "reap"]), None),
    # the cell supervisor's router-cell lifecycle
    # (serving/router_main.py CellRoster): a spawned cell must be
    # adopted into the roster or retired (terminate + wait) on EVERY
    # path — an unadopted cell is an orphan router process serving
    # traffic no supervisor restarts, no drill kills, no shutdown
    # reaps
    "spawn_cell": (frozenset(["adopt", "retire"]), None),
    # the tiered KV cache's spill lifecycle (serving/kv_pool.py): a
    # chain block demoted to the host tier must either REVIVE (upload
    # back into a device block) or DROP (host-budget LRU / reload
    # flush) on every path — a spilled chain that is neither is host
    # memory pinned forever with no index entry left to find it
    "spill": (frozenset(["revive", "drop"]), "tier"),
    # the disaggregated handoff's transfer obligation
    # (serving/disagg.py HandoffCoordinator): every chain exported off
    # a prefill replica must land on the decode side (import_chain,
    # the success settle) or be closed as a failure record
    # (abort_transfer) on EVERY path — an unsettled export is a
    # handoff the two-pool ledger cannot reconcile. Hinted to the
    # coordinator spelling ("disagg"): pool-level export_chain calls
    # in tests/benches return plain data and owe nothing.
    "export_chain": (
        frozenset(["import_chain", "abort_transfer"]),
        "disagg",
    ),
    # the rollout controller's wave lifecycle (serving/rollout.py): a
    # wave opened over a set of replicas must settle in commit_wave
    # (the soak passed) or rollback_wave (judgment turned the fleet
    # around) on EVERY path — an unsettled wave is a fleet stuck on a
    # mixed version with the journal claiming the wave is still in
    # flight
    "begin_wave": (frozenset(["commit_wave", "rollback_wave"]), None),
    # and its checkpoint staging: a staged target version must be
    # activated (manifest accepted, swaps may start) or discarded
    # (verification error surfaced) — a staged-and-forgotten
    # checkpoint is a verification verdict nobody read
    "stage_checkpoint": (frozenset(["activate", "discard"]), None),
}

#: value-bound acquires: callable tail -> release method names
VALUE_ACQUIRES = {
    "start_span": frozenset(["finish"]),
    "open": frozenset(["close"]),
    "build_channel": frozenset(["close"]),
    # a launcher Popen handle must be waited on (or escape to an
    # owner that will): a killed-but-never-waited child is a zombie
    # pinned until the supervisor exits
    "Popen": frozenset(["wait", "communicate"]),
}

def _recv_text(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        return None  # recorder().x — no stable receiver identity
    else:
        return None
    return ".".join(reversed(parts))


def _call_tail(call):
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


class _Obligation(object):
    __slots__ = ("kind", "name", "recv", "releases", "line", "detail")

    def __init__(self, kind, name, recv, releases, line, detail):
        self.kind = kind          # "value" | "recv"
        self.name = name          # local name (value) / base name (recv)
        self.recv = recv          # receiver spelling (recv kind)
        self.releases = releases
        self.line = line
        self.detail = detail


def _value_acquire(stmt):
    """_Obligation for ``x = <acq>(...)`` statements, else None."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return None
    tgt = stmt.targets[0]
    if not isinstance(tgt, ast.Name):
        return None  # attribute/subscript target = ownership transfer
    value = stmt.value
    if not isinstance(value, ast.Call):
        return None
    tail = _call_tail(value)
    if tail not in VALUE_ACQUIRES:
        return None
    if tail == "open" and not isinstance(value.func, ast.Name):
        return None  # only builtin open(), not x.open()
    return _Obligation(
        "value", tgt.id, None, VALUE_ACQUIRES[tail], stmt.lineno,
        "%s=%s" % (tgt.id, tail),
    )


def _recv_acquires(root):
    """(call node, _Obligation) for receiver-pair acquires inside an
    AST subtree (self-receivers and unresolvable receivers skipped)."""
    out = []
    for node in walk_shallow(root):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        pair = RECEIVER_PAIRS.get(fn.attr)
        if pair is None:
            continue
        releases, hint = pair
        recv = _recv_text(fn.value)
        if not recv or recv == "self" or recv.startswith("self."):
            continue
        if hint is not None and hint not in recv:
            continue
        base = recv.split(".", 1)[0]
        out.append((node, _Obligation(
            "recv", base, recv, releases, node.lineno,
            "%s.%s" % (recv, fn.attr),
        )))
    return out


def _settles(node, ob):
    """How entering `node` settles the obligation: "full" (release
    call, reassign, store/pass escape — the path ends here), "exit"
    (``return``/``raise``/``yield`` of the handle — settled only if
    the statement completes, so exceptional successors stay live), or
    None."""
    exit_escape = False
    for root in node.scan_roots():
        for n in walk_shallow(root):
            if isinstance(n, ast.Call):
                fn = n.func
                if isinstance(fn, ast.Attribute):
                    if fn.attr in ob.releases:
                        if ob.kind == "value":
                            # also matches a method chain rooted at
                            # the handle: span.event(...).finish()
                            if _mentions_name(fn.value, ob.name):
                                return "full"
                        else:
                            if _recv_text(fn.value) == ob.recv:
                                return "full"
                # escape: the tracked name reaches a callee through
                # ANY argument shape (bare, tuple — the
                # Thread(args=(rep,)) handoff — starred, keyword);
                # whoever received it owns the release now
                for arg in list(n.args) + [
                    kw.value for kw in n.keywords
                ]:
                    if _mentions_name(arg, ob.name):
                        return "full"
            elif isinstance(n, (ast.Return, ast.Raise)):
                v = n.value if isinstance(n, ast.Return) else n.exc
                if v is not None and _mentions_name(v, ob.name):
                    exit_escape = True
            elif isinstance(n, (ast.Yield, ast.YieldFrom)):
                if n.value is not None and _mentions_name(
                    n.value, ob.name
                ):
                    exit_escape = True
            elif isinstance(n, ast.Assign):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Name):
                        if tgt.id == ob.name:
                            return "full"  # reassigned: obligation gone
                    elif _mentions_name(n.value, ob.name):
                        return "full"  # stored somewhere: escaped
                if ob.kind == "value" and _mentions_name(
                    n.value, ob.name
                ) and not (
                    len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and n.targets[0].id == ob.name
                ):
                    return "full"  # aliased into another local
    return "exit" if exit_escape else None


def _mentions_name(expr, name):
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id == name:
            return True
    return False


def _is_exit(node):
    return node.kind in (EXIT, RAISE_EXIT)


def _iter_functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scope_of(tree, fndef):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and fndef in node.body:
            return "%s.%s" % (node.name, fndef.name)
    return fndef.name


@register
class MustReleaseRule(Rule):
    """EDL501 — see module docstring."""

    id = "EDL501"
    name = "must-release"

    def check_module(self, tree, lines, path):
        findings = []
        for fndef in _iter_functions(tree):
            findings.extend(self._check_function(tree, fndef, path))
        # findings from duplicated finally copies collapse by line
        seen = set()
        out = []
        for f in findings:
            key = (f.fingerprint, f.line)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out

    def _check_function(self, tree, fndef, path):
        cfg = build_cfg(fndef)
        scope = _scope_of(tree, fndef)
        obligations = []  # (start nodes, obligation)
        for node in cfg.nodes:
            roots = node.scan_roots()
            if not roots:
                continue
            if node.kind == "stmt":
                ob = _value_acquire(node.payload)
                if ob is not None:
                    obligations.append((list(node.succ), ob))
            for root in roots:
                for call, ob in _recv_acquires(root):
                    starts = self._guarded_starts(node, call)
                    obligations.append(
                        (starts if starts is not None
                         else list(node.succ), ob)
                    )
        for starts, ob in obligations:
            leak = leak_paths(
                starts, lambda n, ob=ob: _settles(n, ob), _is_exit
            )
            if leak is not None:
                how = ("an exception propagates out"
                       if leak.kind == RAISE_EXIT else
                       "the function returns")
                yield Finding(
                    "EDL501", path, ob.line, scope, ob.detail,
                    "resource acquired here can reach a path where %s "
                    "without %s — every acquisition must settle on "
                    "ALL paths (the PR 4 probe-leak shape); release "
                    "in a finally or transfer ownership explicitly"
                    % (how, "/".join(sorted(ob.releases))),
                )

    @staticmethod
    def _guarded_starts(node, call):
        """For ``if [not] <acquire>(...):`` tests, the successors on
        which the acquisition actually holds; None when the acquire is
        not a guard (effective on every successor)."""
        if node.kind != TEST:
            return None
        stmt = node.payload
        test = stmt.test
        negated = False
        if isinstance(test, ast.UnaryOp) and isinstance(
            test.op, ast.Not
        ):
            test = test.operand
            negated = True
        if test is not call:
            return None
        body_first = stmt.body[0] if stmt.body else None
        true_succs = [s for s in node.succ
                      if s.payload is body_first]
        false_succs = [s for s in node.succ if s not in true_succs]
        return false_succs if negated else true_succs
