"""SARIF 2.1.0 rendering for the lint driver (``--format sarif``).

GitHub code scanning ingests SARIF and annotates PRs from it — the
code-scanning twin of the ``--format github`` ::error annotations,
with two properties those lack: findings persist as dismissable
alerts, and the fingerprint travels with the alert so a line shift
does not re-open it.

The document is BYTE-DETERMINISTIC by construction (the same contract
as `--jobs` output parity and the proto generator): findings arrive
already sorted from the runner, rule metadata is sorted by id, and
serialization is ``sort_keys`` with fixed indentation — no
timestamps, no absolute paths, no environment. `tests/test_lint.py`
pins serial == fanned-out bytes.

Only FINDINGS are rendered; stale-baseline entries and runner errors
stay on stderr (they are run-hygiene failures, not code locations).
"""

import json


def _rule_meta(rules, families):
    """One reportingDescriptor per EMITTED id of the selected
    checkers (a checker like EDL101 emits EDL101/102/103 — each needs
    a descriptor or the uploader drops the result's rule link)."""
    import sys

    metas = {}
    for rule in rules:
        doc = (sys.modules[rule.__module__].__doc__ or "")
        title = doc.strip().splitlines()[0] if doc else (rule.name or "")
        for fid in families.get(rule.id, (rule.id,)):
            metas[fid] = {
                "id": fid,
                "name": rule.name or fid,
                "shortDescription": {"text": title},
                # the catalogue row in the design doc; code-scanning
                # renders it as the alert's "learn more" link
                "helpUri": (
                    "docs/designs/static_analysis.md#%s" % fid.lower()
                ),
            }
    return [metas[k] for k in sorted(metas)]


def sarif_document(findings, rules):
    """The SARIF run for one lint invocation, as a dict."""
    from elasticdl_tpu.analysis.lint import RULE_FAMILIES

    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {
                "text": "[%s] %s: %s" % (f.scope, f.detail, f.message),
            },
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "edlLintFingerprint/v1": "%s:%s:%s:%s" % f.fingerprint,
            },
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "edl-lint",
                    "informationUri": (
                        "docs/designs/static_analysis.md"
                    ),
                    "rules": _rule_meta(rules, RULE_FAMILIES),
                },
            },
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {
                "SRCROOT": {"uri": "file:///"},
            },
            "results": results,
        }],
    }


def render_sarif(findings, rules):
    """Byte-deterministic SARIF text (trailing newline included)."""
    return json.dumps(
        sarif_document(findings, rules), indent=2, sort_keys=True,
    ) + "\n"
