"""edl-lint: domain-aware static analysis for this codebase.

The repo mixes two failure-prone idioms — lock-guarded concurrent
control planes (master dispatcher, instance manager, serving router/
admission/telemetry) and jit-compiled JAX hot paths — and both fail
SILENTLY: a race corrupts bookkeeping under load, a stray host sync
serializes the decode loop. These checkers encode the project's
conventions as AST rules so correctness scales with the code instead
of with reviewer attention.

Entry point: ``python -m elasticdl_tpu.analysis.lint`` (see `make
lint` and the CI ``lint`` job). Rules live in small visitor classes
behind the registry in core.py; adding one is ~50 LoC plus two
fixtures (docs/designs/static_analysis.md has the recipe).
"""

from elasticdl_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Rule,
    all_rules,
    register,
    run_rules,
)

# importing the rule modules registers their rules
from elasticdl_tpu.analysis import (  # noqa: F401,E402
    blocking_rules,
    jit_rules,
    lock_rules,
    proto_rules,
    telemetry_rules,
)
