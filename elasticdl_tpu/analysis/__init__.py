"""edl-lint: domain-aware static analysis for this codebase.

The repo mixes two failure-prone idioms — lock-guarded concurrent
control planes (master dispatcher, instance manager, serving router/
admission/telemetry) and jit-compiled JAX hot paths — and both fail
SILENTLY: a race corrupts bookkeeping under load, a stray host sync
serializes the decode loop. These checkers encode the project's
conventions as AST rules so correctness scales with the code instead
of with reviewer attention.

Two tiers of machinery:

* syntactic visitor rules (lock_rules, jit_rules, blocking_rules,
  telemetry_rules) — one AST pass, local judgments;
* CFG/dataflow rules (lockgraph_rules, resource_rules,
  deadline_rules, donate_rules) on the engine in cfg.py (per-function
  control-flow graphs) and dataflow.py (worklist may/must analyses +
  the project-wide class/lock/binding index) — path-sensitive and
  interprocedural judgments: lock-order deadlock cycles, wrong-lock
  bindings, must-release obligations, deadline propagation, donated-
  buffer liveness.

Entry point: ``python -m elasticdl_tpu.analysis.lint`` (see `make
lint` and the CI ``lint`` job). Rules live behind the registry in
core.py; adding one is ~50-150 LoC plus two fixtures
(docs/designs/static_analysis.md has the recipe for both tiers).
"""

from elasticdl_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Rule,
    all_rules,
    register,
    run_rules,
)

# importing the rule modules registers their rules (EDL000 registers
# with core itself — the unused-pragma check lives in the runner)
from elasticdl_tpu.analysis import (  # noqa: F401,E402
    blocking_rules,
    compile_rules,
    deadline_rules,
    donate_rules,
    jit_rules,
    journal_rules,
    lock_rules,
    lockgraph_rules,
    proto_rules,
    resource_rules,
    sharding_rules,
    telemetry_rules,
)
