"""Spec-derived crash-point replay batteries for journal protocols.

The static half of the protocol contract lives in `journal_rules`
(EDL701-EDL704); this module is the dynamic half, consumed by
`tests/test_protocol_batteries.py`. Given a controller's declared
`JournalProtocol` and a journal (recorded from a live run or
synthesized and strict-validated against the machine), the battery
walks EVERY crash point:

* `replay_battery` — truncate the journal after each event (the crash
  window a SIGKILL opens between two appends), rebuild from the
  prefix through the controller's REAL replay surface, and require
  recovery to be deterministic; an optional `check` callback compares
  the recovered state against the machine's own simulation of the
  prefix.
* `double_replay_idempotent` — the compaction crash-window contract:
  `write_snapshot` persists the snapshot BEFORE truncating the
  journal, so a crash between the two replays the full journal
  against a snapshot that already incorporates it. Replaying
  (snapshot + events) on top of (events) must land in the same state.
* `validate_journal` / `kind_coverage` — the declaration-level gates:
  every event legal from its machine state, every prefix recoverable,
  and (coverage) which declared kinds a battery's journal never
  exercises — a battery over half the alphabet proves little.

Pure stdlib, no jax: runs in tier-1 and in the minimal lint CI env.
"""

from elasticdl_tpu.analysis.typestate import ProtocolError  # noqa: F401


def validate_journal(spec, events):
    """Declaration-level checks on a journal: every event declared and
    legal from its (global or entity) machine state — the dynamic twin
    of EDL703 — and every prefix recoverable — the dynamic twin of
    EDL704. Returns the final ``(global_state, entity_states)``."""
    result = spec.simulate(events, strict=True)
    spec.assert_recoverable_prefixes(events)
    return result


def kind_coverage(spec, events):
    """Declared non-informational kinds `events` never exercises."""
    seen = {ev.get(spec.kind_key) for ev in events}
    return sorted(spec.replayed_kinds() - seen)


def replay_battery(spec, events, recover, check=None):
    """Exhaustive crash-point battery over a recorded journal.

    For every prefix of `events` — the journal a SIGKILL after the
    k-th append leaves on disk — call ``recover(None, prefix)`` to
    rebuild a controller and return a comparable state fingerprint.
    Recovery must be deterministic (recovering the same prefix twice
    lands in the same place), and ``check(k, sim, fingerprint)`` —
    `sim` being ``spec.simulate(prefix)`` — lets the harness assert
    that the recovered controller matches the declared machine.

    Events are deep-ish copied per call so a replay surface that
    mutates its input cannot leak state between crash points. Returns
    the number of crash points exercised."""
    validate_journal(spec, events)
    for k in range(len(events) + 1):
        first = recover(None, [dict(ev) for ev in events[:k]])
        second = recover(None, [dict(ev) for ev in events[:k]])
        if first != second:
            raise AssertionError(
                "crash point %d of %r: recovery is not deterministic"
                "\n first:  %r\n second: %r"
                % (k, spec.name, first, second)
            )
        if check is not None:
            sim = spec.simulate(events[:k], strict=False)
            check(k, sim, first)
    return len(events) + 1


def double_replay_idempotent(spec, events, recover, snapshot_of,
                             fingerprint=None):
    """The snapshot/journal-overlap contract: recovering from
    ``(snapshot-incorporating-events, events)`` — what a crash between
    `write_snapshot` and the journal truncate leaves behind — must
    reach the same state as recovering from ``(None, events)``.

    ``recover(snapshot, events)`` rebuilds a controller;
    ``snapshot_of(state)`` renders its compacted snapshot dict;
    ``fingerprint`` (default: identity) projects the compared state —
    harnesses exclude journal-history counters here, which by design
    fold the FULL event history and may legally inflate by one crash's
    worth in the overlap window. Returns the once-recovered state."""
    fp = fingerprint or (lambda s: s)
    once = recover(None, [dict(ev) for ev in events])
    snap = snapshot_of(once)
    twice = recover(snap, [dict(ev) for ev in events])
    a, b = fp(once), fp(twice)
    if a != b:
        raise AssertionError(
            "protocol %r: snapshot+journal overlap replay diverges"
            "\n journal only:     %r\n snapshot+journal: %r"
            % (spec.name, a, b)
        )
    return once
