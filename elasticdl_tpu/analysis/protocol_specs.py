"""Registry of WAL-backed controllers and their protocol loaders.

Each listed module is REQUIRED to carry a module-level
``PROTOCOL = JournalProtocol(...)`` declaration (see `typestate.py`);
a missing declaration is an EDL701 conviction in its own right — the
write/replay closure, payload-drift, typestate, and crash-point rules
can only gate journals that declare their machine, so the gate on the
declaration itself is what makes new journal consumers born-checked.

`load_protocol` re-reads a declaration from source without importing
the module: the lint rules and spec-derived test generators run in
environments (the CI lint job, fixture files) where importing a
serving controller — and its jax dependency chain — is not an option.
"""

import ast

from elasticdl_tpu.analysis.typestate import (
    ProtocolError,
    find_protocol_decl,
    machine_from_ast,
    module_constant_env,
)

#: repo-relative paths of every shipped WAL-backed controller; a new
#: journal consumer is added here in the SAME PR that introduces it
WAL_CONTROLLERS = (
    "elasticdl_tpu/master/task_dispatcher.py",
    "elasticdl_tpu/serving/autoscaler.py",
    "elasticdl_tpu/serving/rollout.py",
    "elasticdl_tpu/serving/router_cell.py",
)


def load_protocol(path):
    """The declared JournalProtocol of the module at `path`, parsed
    from source (never imported). Raises ProtocolError when the file
    has no declaration or the declaration is malformed."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    decl = find_protocol_decl(tree)
    if decl is None:
        raise ProtocolError("%s declares no PROTOCOL" % path)
    return machine_from_ast(decl.value, module_constant_env(tree))
