"""C5 — telemetry counter/gauge/slow-cause-name checker (EDL401).

The telemetry counter, gauge AND slow-cause sets are CLOSED
(ServingTelemetry.COUNTERS/GAUGES/SLOW_CAUSES and
RouterTelemetry.COUNTERS/GAUGES in serving/telemetry.py):
`count()`/`gauge()`/`count_slow_cause()` raise at runtime on an
undeclared name, because a typo like ``count("admittd")`` used to
silently fork a brand-new counter and under-report the real one
forever — an observability bug that corrupts dashboards without ever
failing a test that doesn't read the exact counter back. A typo'd
gauge is the same bug on the scrape plane: a dead TensorBoard tag and
a dead Prometheus series, silently. A typo'd slow cause is the same
bug on the forensics plane: a labeled `slow_cause{cause=...}` series
nobody's dashboards or the fleet collector's cause taxonomy will ever
aggregate.

This rule is the STATIC twin of those runtime raises: it flags every
``<telemetry-ish receiver>.count("<literal>")`` call site whose string
literal is not in the declared counter union, every
``<telemetry-ish receiver>.gauge("<literal>")`` not in the declared
gauge union, and every
``<telemetry-ish receiver>.count_slow_cause("<literal>")`` not in the
declared cause union (observability/forensics.py CAUSES, re-exported
by ServingTelemetry.SLOW_CAUSES), so the typo fails `make lint`
before any drill has to hit the code path.

FLAGGED: attribute calls ``X.count("name")`` / ``X.gauge("name")`` /
``X.count_slow_cause("name")`` where the receiver's dotted spelling
mentions ``telemetry`` (``self.telemetry.count``,
``self._telemetry.gauge``, ``router.telemetry.count`` ...) and the
first argument is a string literal not in the matching declared set.

NOT flagged: non-literal names (the runtime raise owns those),
receivers that don't spell ``telemetry`` (list.count etc.), and call
sites with no arguments.

The declared sets are read from elasticdl_tpu.serving.telemetry at
rule run time (stdlib-only import), so declaring a new counter/gauge/
cause there is the single source of truth — no second list to update
here.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, Rule, register


def _receiver_text(node):
    """Dotted spelling of an attribute chain, lowercased."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)).lower()


def declared_counters():
    """The closed counter-name union (single source of truth:
    serving/telemetry.py class attributes)."""
    from elasticdl_tpu.serving.telemetry import (
        RouterTelemetry,
        ServingTelemetry,
    )

    return frozenset(ServingTelemetry.COUNTERS) | frozenset(
        RouterTelemetry.COUNTERS
    )


def declared_gauges():
    """The closed gauge-name union — same import, same contract."""
    from elasticdl_tpu.serving.telemetry import (
        RouterTelemetry,
        ServingTelemetry,
    )

    return frozenset(ServingTelemetry.GAUGES) | frozenset(
        RouterTelemetry.GAUGES
    )


def declared_slow_causes():
    """The closed slow-cause union (forensics.CAUSES, re-exported as
    ServingTelemetry.SLOW_CAUSES) — same import, same contract."""
    from elasticdl_tpu.serving.telemetry import ServingTelemetry

    return frozenset(ServingTelemetry.SLOW_CAUSES)


class _CounterVisitor(ast.NodeVisitor):
    #: method name -> (allowed-set key, series noun in the message)
    _CHECKED = {"count": "counter", "gauge": "gauge",
                "count_slow_cause": "slow cause"}

    def __init__(self, path, allowed):
        self.path = path
        self.allowed = allowed  # {"counter": frozenset, "gauge": ...}
        self.scope_stack = []
        self.findings = []

    @property
    def scope(self):
        return ".".join(self.scope_stack) or "<module>"

    def visit_ClassDef(self, node):
        self.scope_stack.append(node.name)
        self.generic_visit(node)
        self.scope_stack.pop()

    def visit_FunctionDef(self, node):
        self.scope_stack.append(node.name)
        self.generic_visit(node)
        self.scope_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        fn = node.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr in self._CHECKED
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and "telemetry" in _receiver_text(fn.value)):
            kind = self._CHECKED[fn.attr]
            name = node.args[0].value
            if name not in self.allowed[kind]:
                self.findings.append(Finding(
                    "EDL401", self.path, node.lineno, self.scope,
                    name,
                    "unknown telemetry %s %r — not in the declared "
                    "ServingTelemetry/RouterTelemetry %sS (a typo "
                    "here silently forks a new series; fix the name "
                    "or declare it)"
                    % (kind, name, kind.upper()),
                ))
        self.generic_visit(node)


@register
class TelemetryCounterRule(Rule):
    """EDL401 — see module docstring."""

    id = "EDL401"
    name = "telemetry-counter-name"

    def check_module(self, tree, lines, path):
        visitor = _CounterVisitor(path, {
            "counter": declared_counters(),
            "gauge": declared_gauges(),
            "slow cause": declared_slow_causes(),
        })
        visitor.visit(tree)
        return visitor.findings
