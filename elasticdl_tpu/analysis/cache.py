"""On-disk per-file result cache for the lint runner.

The full-tree sweep re-parses and re-analyzes every module on every
run, but between two local runs almost nothing changed — so the
runner memoizes each file's (pragma-filtered) findings keyed by the
file's CONTENT hash, under a context key that folds in the rule-set
version (a hash of every ``analysis/*.py`` source) and the selected
rule ids. Any engine or rule edit, or a different ``--select``,
silently invalidates the whole cache; a file edit invalidates that
file. Repo-level checks (proto drift, the lock graph, the WAL
controller registry) are never cached — they are cross-file by
nature and cheap.

Soundness: ``check_module(tree, lines, path)`` is a pure function of
(file content, relative path, rule set, full-run flag) — content and
path are the entry key, rule set and full-run are in the context —
so a hit replays byte-identical findings (test_lint.py pins SARIF
parity between a cold and a warm run). The cache file lives at the
repo root (``.edl-lint-cache.json``, git-ignored) and is written
atomically; a corrupt or stale-context file is discarded wholesale,
never trusted partially.
"""

import hashlib
import json
import os
import tempfile

from elasticdl_tpu.analysis.core import Finding

CACHE_BASENAME = ".edl-lint-cache.json"

_FORMAT = 1


def ruleset_version():
    """Hash of every analysis-package source file: any edit to a
    rule, the engine, or this module invalidates every cached
    result."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for fn in sorted(os.listdir(here)):
        if not fn.endswith(".py"):
            continue
        h.update(fn.encode("utf-8"))
        with open(os.path.join(here, fn), "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def cache_context(rule_ids):
    """The context key: rule-set version x selected checkers. The
    full-run flag (which gates EDL000 pragma judgment) is a pure
    function of the id set, so folding the ids in covers it."""
    h = hashlib.sha256()
    h.update(ruleset_version().encode("utf-8"))
    h.update(",".join(sorted(rule_ids)).encode("utf-8"))
    return h.hexdigest()


def file_sha(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


class ResultCache(object):
    def __init__(self, path, context):
        self.path = path
        self.context = context
        self.files = {}
        self._dirty = False
        self._load()

    def _load(self):
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        if (not isinstance(data, dict)
                or data.get("format") != _FORMAT
                or data.get("context") != self.context):
            # engine/rule-set changed: the whole cache is void, and
            # keeping old-context entries around would only let a
            # future bug resurrect them
            self._dirty = True
            return
        files = data.get("files")
        if isinstance(files, dict):
            self.files = files

    def get(self, rel, sha):
        """(findings, errors) memoized for this content, else None."""
        entry = self.files.get(rel)
        if not isinstance(entry, dict) or entry.get("sha") != sha:
            return None
        try:
            findings = [
                Finding(rule, path, line, scope, detail, message)
                for rule, path, line, scope, detail, message
                in entry["findings"]
            ]
            errors = [str(e) for e in entry["errors"]]
        except (KeyError, TypeError, ValueError):
            return None
        return findings, errors

    def put(self, rel, sha, findings, errors):
        self.files[rel] = {
            "sha": sha,
            "findings": [
                [f.rule, f.path, f.line, f.scope, f.detail, f.message]
                for f in findings
            ],
            "errors": list(errors),
        }
        self._dirty = True

    def save(self):
        """Atomic write (tmp + rename): a parallel run or a crash
        mid-write can never leave a torn cache — the same discipline
        the journals this linter now checks live by."""
        if not self._dirty:
            return
        payload = {
            "format": _FORMAT,
            "context": self.context,
            "files": self.files,
        }
        d = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".edl-lint-cache.")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, sort_keys=True)
                f.write("\n")
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
