"""Per-function control-flow graph over the AST.

The engine the CFG-based rules (EDL003/004/104/202/203/501) run on.
One `Node` per *simple* statement plus synthetic junctions; control
statements (``if``/``while``/``for``/``try``/``with``) contribute a
node for the expression they evaluate (test, iterable, with-items)
and structure for their bodies. Every function gets three
distinguished nodes: ``entry``, ``exit`` (normal return /
fall-off-the-end) and ``raise_exit`` (an exception propagates out).

Exception edges are deliberately SELECTIVE, not sound: a statement
gets an exceptional successor only when it is lexically inside a
``try`` (to that try's dispatch junction) or when it is an explicit
``raise``. Modeling "any statement may raise" would make every
``acquire(); use(); release()`` sequence a leak path and drown the
resource rules in noise; the bug shapes that matter here — a handler
branch that forgets the release, an early return, a re-raise — all
flow through explicit try/raise structure, which IS modeled:

* ``except:`` / ``except BaseException`` / ``except Exception`` is
  treated as catch-all (the body's uncaught-propagation edge is
  dropped); a typed handler (``except ValueError``) keeps it, because
  an exception of another type flies past.
* handler and ``orelse`` bodies run OUTSIDE the handler-catching
  scope but INSIDE the finally scope: an EXPLICIT ``raise`` there
  (including a bare re-``raise``) runs the finally and continues
  outward — it can never loop back into a sibling handler. Ordinary
  handler statements get no implicit raise edge (a predicate call in
  ``if self._transient(e):`` is not treated as a potential raiser) —
  same noise-control reasoning as above.
* ``finally`` bodies are COPIED per crossing kind (normal completion,
  propagation, early return/break/continue) rather than shared, so no
  spurious cross-path merges arise; rules de-duplicate identical
  findings from the copies by fingerprint.
* ``with`` bodies are ordinary straight-line structure (the
  context-manager release-on-exit is the RULES' knowledge, not the
  graph's).

Nested ``def``/``lambda``/``class`` statements are single nodes: the
definition executes here, the body does not (analyses recurse into
nested functions explicitly when their semantics call for it, via
`walk_shallow`, which prunes nested scopes).
"""

import ast

#: node kinds — synthetic junctions carry no AST payload
STMT = "stmt"          # a simple statement (payload = the stmt)
TEST = "test"          # if/while test expression (payload = the stmt)
ITER = "iter"          # for-loop iterable (payload = the stmt)
WITH = "with"          # with-items evaluation (payload = the With)
ENTRY = "entry"
EXIT = "exit"
RAISE_EXIT = "raise"
JUNCTION = "junction"  # dispatch/merge points


class Node(object):
    __slots__ = ("idx", "kind", "payload", "succ", "esucc")

    def __init__(self, idx, kind, payload=None):
        self.idx = idx
        self.kind = kind
        self.payload = payload
        self.succ = []   # normal control flow
        self.esucc = []  # exceptional control flow (to a dispatch)

    @property
    def out(self):
        return self.succ + self.esucc

    @property
    def line(self):
        return getattr(self.payload, "lineno", 0)

    def scan_roots(self):
        """AST subtrees whose evaluation happens at this node (what an
        event scanner should walk — with `walk_shallow`, so nested
        function bodies are excluded)."""
        p = self.payload
        if p is None:
            return ()
        if self.kind == STMT:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                return ()  # definition executes; body does not
            return (p,)
        if self.kind == TEST:
            return (p.test,)
        if self.kind == ITER:
            return (p.iter,)
        if self.kind == WITH:
            return tuple(item.context_expr for item in p.items)
        return ()

    def __repr__(self):
        return "<Node %d %s L%d>" % (self.idx, self.kind, self.line)


def walk_shallow(node):
    """ast.walk pruned at nested-scope boundaries: never descends into
    a nested FunctionDef/AsyncFunctionDef/Lambda/ClassDef body (their
    code runs later, in another frame). The root itself is always
    yielded and entered (callers scan bodies they own)."""
    stack = [node]
    first = True
    while stack:
        n = stack.pop()
        yield n
        if not first and isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)
        ):
            continue
        first = False
        stack.extend(ast.iter_child_nodes(n))


class CFG(object):
    def __init__(self, fndef):
        self.fndef = fndef
        self.nodes = []
        self.entry = self._new(ENTRY)
        self.exit = self._new(EXIT)
        self.raise_exit = self._new(RAISE_EXIT)

    def _new(self, kind, payload=None):
        node = Node(len(self.nodes), kind, payload)
        self.nodes.append(node)
        return node

    def link(self, frm, to, exc=False):
        edges = frm.esucc if exc else frm.succ
        if to not in edges:
            edges.append(to)


class _Frame(object):
    """One enclosing try scope during construction. `dispatch` is the
    junction exceptions raised in the scope route to; `fin_stmts` is
    the finalbody any path LEAVING the scope must cross. `catches` is
    True for a try BODY (its handlers/finally react to any raise
    there) and False for the handler/orelse escape scope, where only
    EXPLICIT ``raise`` statements propagate — treating every handler
    expression as a potential raiser is exactly the "any statement may
    raise" noise this graph avoids."""

    __slots__ = ("dispatch", "fin_stmts", "catches")

    def __init__(self, dispatch, fin_stmts, catches=True):
        self.dispatch = dispatch
        self.fin_stmts = fin_stmts
        self.catches = catches


class _Loop(object):
    __slots__ = ("header", "breaks", "depth")

    def __init__(self, header, depth):
        self.header = header
        self.breaks = []
        self.depth = depth  # len(try stack) at loop entry


_CATCH_ALL = ("Exception", "BaseException")


def _is_catch_all(handler):
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Attribute):
        return t.attr in _CATCH_ALL
    if isinstance(t, ast.Name):
        return t.id in _CATCH_ALL
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _CATCH_ALL
            or isinstance(e, ast.Attribute) and e.attr in _CATCH_ALL
            for e in t.elts
        )
    return False


class _Builder(object):
    def __init__(self, fndef):
        self.cfg = CFG(fndef)
        self.tries = []   # _Frame stack (innermost last)
        self.loops = []   # _Loop stack

    # ------------------------------------------------------------ wiring

    def build(self):
        out = self._seq(self.cfg.fndef.body, [self.cfg.entry])
        self._connect(out, self.cfg.exit)
        return self.cfg

    def _connect(self, preds, target):
        for p in preds:
            self.cfg.link(p, target)

    def _exc_target(self):
        if self.tries:
            return self.tries[-1].dispatch
        return self.cfg.raise_exit

    def _finally_copy(self, fin_stmts, preds):
        """Build ONE fresh copy of a finalbody (under the CURRENT try
        stack — the finally runs outside its own try) fed by `preds`;
        returns its dangling exits."""
        if not fin_stmts:
            return list(preds)
        j = self.cfg._new(JUNCTION)
        self._connect(preds, j)
        return self._seq(fin_stmts, [j])

    def _route(self, preds, to_depth, target):
        """Route an abrupt jump (return / break / continue /
        propagation) through every finally between the current try
        depth and `to_depth`, innermost first, then to `target`."""
        saved = self.tries
        for i in range(len(saved) - 1, to_depth - 1, -1):
            frame = saved[i]
            if frame.fin_stmts:
                self.tries = saved[:i]
                preds = self._finally_copy(frame.fin_stmts, preds)
        self.tries = saved
        self._connect(preds, target)

    # ------------------------------------------------------- statements

    def _seq(self, stmts, preds):
        for stmt in stmts:
            preds = self._stmt(stmt, preds)
        return preds

    def _node(self, kind, stmt, preds, may_raise=None):
        node = self.cfg._new(kind, stmt)
        self._connect(preds, node)
        if may_raise is None:
            # implicit raising is modeled only inside a try BODY;
            # handler/orelse code raises only via explicit `raise`
            may_raise = any(f.catches for f in self.tries)
        if may_raise:
            self.cfg.link(node, self._exc_target(), exc=True)
        return node

    def _stmt(self, stmt, preds):
        if isinstance(stmt, ast.If):
            return self._if(stmt, preds)
        if isinstance(stmt, ast.While):
            return self._loop(stmt, preds, TEST)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._loop(stmt, preds, ITER)
        if isinstance(stmt, ast.Try) or type(stmt).__name__ == "TryStar":
            return self._try(stmt, preds)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            node = self._node(WITH, stmt, preds)
            return self._seq(stmt.body, [node])
        if isinstance(stmt, ast.Return):
            node = self._node(STMT, stmt, preds)
            self._route([node], 0, self.cfg.exit)
            return []
        if isinstance(stmt, ast.Raise):
            node = self._node(STMT, stmt, preds, may_raise=False)
            if self.tries:
                self.cfg.link(node, self.tries[-1].dispatch)
            else:
                self._route([node], 0, self.cfg.raise_exit)
            return []
        if isinstance(stmt, ast.Break):
            node = self._node(STMT, stmt, preds, may_raise=False)
            if self.loops:
                loop = self.loops[-1]
                j = self.cfg._new(JUNCTION)
                loop.breaks.append(j)
                self._route([node], loop.depth, j)
            return []
        if isinstance(stmt, ast.Continue):
            node = self._node(STMT, stmt, preds, may_raise=False)
            if self.loops:
                loop = self.loops[-1]
                self._route([node], loop.depth, loop.header)
            return []
        return [self._node(STMT, stmt, preds)]

    def _if(self, stmt, preds):
        test = self._node(TEST, stmt, preds)
        out = list(self._seq(stmt.body, [test]))
        if stmt.orelse:
            out.extend(self._seq(stmt.orelse, [test]))
        else:
            out.append(test)
        return out

    def _loop(self, stmt, preds, kind):
        header = self._node(kind, stmt, preds)
        self.loops.append(_Loop(header, len(self.tries)))
        body_out = self._seq(stmt.body, [header])
        self._connect(body_out, header)
        loop = self.loops.pop()
        out = list(loop.breaks)
        if stmt.orelse:
            out.extend(self._seq(stmt.orelse, [header]))
        else:
            out.append(header)
        return out

    def _try(self, stmt, preds):
        dispatch = self.cfg._new(JUNCTION)
        self.tries.append(_Frame(dispatch, stmt.finalbody))
        body_out = self._seq(stmt.body, preds)
        self.tries.pop()

        # handler/orelse scope: exceptions there (incl. re-raise) run
        # the finally and continue OUTWARD — never back into dispatch
        esc = self.cfg._new(JUNCTION)
        fin_scope = _Frame(esc, stmt.finalbody, catches=False)
        self.tries.append(fin_scope)
        if stmt.orelse:
            body_out = self._seq(stmt.orelse, body_out)
        handler_out = []
        caught_all = not stmt.handlers
        for handler in stmt.handlers:
            h_entry = self.cfg._new(JUNCTION)
            self.cfg.link(dispatch, h_entry)
            handler_out.extend(self._seq(handler.body, [h_entry]))
            caught_all = caught_all or _is_catch_all(handler)
        self.tries.pop()

        outer_exc = self._exc_target()
        # exceptions escaping a handler/orelse: finally, then outward
        self._connect(
            self._finally_copy(stmt.finalbody, [esc]), outer_exc
        )
        # uncaught propagation out of the body (typed handlers may not
        # match; a handler-less try/finally never catches)
        if not caught_all or not stmt.handlers:
            prop = self.cfg._new(JUNCTION)
            self.cfg.link(dispatch, prop)
            self._connect(
                self._finally_copy(stmt.finalbody, [prop]), outer_exc
            )
        # normal completion crosses the finally once
        return self._finally_copy(stmt.finalbody,
                                  list(body_out) + handler_out)


def build_cfg(fndef):
    """CFG for one FunctionDef/AsyncFunctionDef."""
    return _Builder(fndef).build()
