"""C10 — donated-buffer aliasing (EDL104).

``jax.jit(f, donate_argnums=(0,))`` tells XLA it may DESTROY the
argument's buffer and reuse its memory for the output — the whole
point of donating the optimizer state (no copy per step). The
contract: the caller must never touch the donated value again. A read
after the call either crashes ("array has been deleted") or — under
a backend that copies instead — silently un-does the optimization.
Correct idiom: rebind the name (``state = step(state, batch)``).

The rule resolves donated wrappers LEXICALLY, matching the codebase's
two idioms:

* ``step = jax.jit(train_step, donate_argnums=(0,))`` — a wrapper
  bound to a local/module name (also ``self._fn = jax.jit(...)``,
  matched by receiver spelling within the same function);
* ``@partial(jax.jit, donate_argnums=(0,))`` / ``@jax.jit(...)``
  decorators — calls to the decorated name.

At each call of a donated wrapper, an argument in a donated position
(``donate_argnums`` index or ``donate_argnames`` keyword) that is a
plain Name is DEAD after the call: any read of that name reachable in
the CFG without an intervening rebind is flagged. ``x = f(x)`` is
clean (the rebind happens at the call); cross-function flows (a
wrapper built in one method, called in another) are out of scope —
resolving them would need return-type tracking, and a wrong guess
here means noise on every training step.

Computed declarations (``donate_argnums=ns``) fall back to "nothing
donated" rather than "everything donated": this rule's findings read
as "this line crashes under donation", so precision beats recall.
"""

import ast

from elasticdl_tpu.analysis.cfg import build_cfg, walk_shallow
from elasticdl_tpu.analysis.core import Finding, Rule, register

_JIT_TAILS = {"jit", "pjit"}


def _tail(fn):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _const_seq(node):
    """Literal int/str or tuple/list of literals, else None."""
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)) and all(
        isinstance(e, ast.Constant) for e in node.elts
    ):
        return [e.value for e in node.elts]
    return None


class _DonateSpec(object):
    __slots__ = ("argnums", "argnames", "line")

    def __init__(self, argnums, argnames, line):
        self.argnums = argnums
        self.argnames = argnames
        self.line = line


def _donate_spec(call):
    """_DonateSpec for a jit(...) call carrying donate declarations,
    None otherwise (including undecidable computed declarations)."""
    if _tail(call.func) not in _JIT_TAILS:
        return None
    argnums, argnames = [], []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            vals = _const_seq(kw.value)
            if vals is None:
                return None
            argnums.extend(int(v) for v in vals)
        elif kw.arg == "donate_argnames":
            vals = _const_seq(kw.value)
            if vals is None:
                return None
            argnames.extend(str(v) for v in vals)
    if not argnums and not argnames:
        return None
    return _DonateSpec(tuple(argnums), tuple(argnames), call.lineno)


def _target_text(tgt):
    """'name' or 'self.attr' spelling for wrapper-binding targets."""
    if isinstance(tgt, ast.Name):
        return tgt.id
    parts = []
    node = tgt
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _callee_text(fn):
    return _target_text(fn)


def _walk_scope(stmts):
    """Walk statements of ONE scope: compound statements (if/try/for/
    with) are entered, nested function/class bodies are not — a
    wrapper bound inside them is not visible at this level. The
    def/class node itself IS yielded (its decorators belong here)."""
    stack = list(stmts)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _collect_wrappers(scope_stmts):
    """{spelling: _DonateSpec} for donated wrappers bound in these
    statements (assignment form) plus decorated functions."""
    wrappers = {}
    for stmt in scope_stmts:
        for node in _walk_scope([stmt]):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                spec = _donate_spec(node.value)
                if spec is None:
                    continue
                for tgt in node.targets:
                    text = _target_text(tgt)
                    if text:
                        wrappers[text] = spec
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    spec = _donate_spec(dec)
                    if spec is None and dec.args and _tail(
                        dec.func
                    ) == "partial":
                        inner = ast.Call(
                            func=dec.args[0], args=[],
                            keywords=dec.keywords,
                        )
                        inner.lineno = dec.lineno
                        spec = _donate_spec(inner)
                    if spec is not None:
                        wrappers[node.name] = spec
    return wrappers


def _donated_args(call, spec):
    """Names passed at donated positions of this call."""
    out = []
    for i in spec.argnums:
        if 0 <= i < len(call.args) and isinstance(
            call.args[i], ast.Name
        ):
            out.append(call.args[i].id)
    for kw in call.keywords:
        if kw.arg in spec.argnames and isinstance(kw.value, ast.Name):
            out.append(kw.value.id)
    return out


def _rebinds(node, name):
    """Does this CFG node rebind `name` (killing the dead value)?"""
    for root in node.scan_roots():
        stmt = root
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name) and n.id == name:
                        return True
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            pass  # ITER nodes pass the stmt; handled via kind below
    if node.kind == "iter":
        for n in ast.walk(node.payload.target):
            if isinstance(n, ast.Name) and n.id == name:
                return True
    return False


def _reads(node, name, skip_call):
    """Line of a read of `name` at this node (ignoring `skip_call`,
    the donating call itself), else None."""
    for root in node.scan_roots():
        for n in walk_shallow(root):
            if n is skip_call:
                continue
            if (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)):
                return n.lineno
    return None


@register
class DonateAliasRule(Rule):
    """EDL104 — see module docstring."""

    id = "EDL104"
    name = "donated-buffer-aliasing"

    def check_module(self, tree, lines, path):
        findings = []
        module_wrappers = _collect_wrappers(tree.body)
        for fndef in self._functions(tree):
            wrappers = dict(module_wrappers)
            wrappers.update(_collect_wrappers(fndef.body))
            if wrappers:
                findings.extend(
                    self._check_function(fndef, wrappers, path)
                )
        return findings

    @staticmethod
    def _functions(tree):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                yield node

    def _check_function(self, fndef, wrappers, path):
        cfg = build_cfg(fndef)
        for node in cfg.nodes:
            for root in node.scan_roots():
                for n in walk_shallow(root):
                    if not isinstance(n, ast.Call):
                        continue
                    spelling = _callee_text(n.func)
                    spec = wrappers.get(spelling)
                    if spec is None:
                        continue
                    for name in _donated_args(n, spec):
                        if self._immediately_rebound(root, n, name):
                            continue
                        line = self._read_after(cfg, node, n, name)
                        if line is not None:
                            yield Finding(
                                "EDL104", path, line, fndef.name,
                                name,
                                "%r was donated to %s (donate_arg"
                                "nums/argnames) at line %d — its "
                                "buffer may already be deleted; "
                                "rebind the result to the same name "
                                "or stop donating" % (
                                    name, spelling, n.lineno,
                                ),
                            )

    @staticmethod
    def _immediately_rebound(stmt, call, name):
        """``x = f(x)`` / ``x, y = f(x)``: the donating statement
        itself rebinds the name."""
        if isinstance(stmt, ast.Assign) and stmt.value is call:
            for tgt in stmt.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name) and n.id == name:
                        return True
        return False

    @staticmethod
    def _read_after(cfg, call_node, call, name):
        """First read of `name` CFG-reachable from the donating call
        without an intervening rebind; None if no path reads it."""
        seen = set()
        stack = list(call_node.succ)
        while stack:
            node = stack.pop()
            if node.idx in seen:
                continue
            seen.add(node.idx)
            line = _reads(node, name, call)
            if line is not None:
                return line
            if _rebinds(node, name):
                continue
            stack.extend(node.out)
        return None
