"""C9 — RPC-deadline propagation (EDL202 dropped / EDL203 replaced).

An inbound deadline must FLOW. A servicer or router dispatch entry
that receives a deadline — a ``request`` whose proto carries
``deadline_ms``, or an explicit timeout/deadline parameter — must
thread that budget (possibly decremented) into every downstream stub
RPC it causes, directly or through helpers. The two failure modes:

* **EDL202 — deadline dropped.** A helper reachable from a
  deadline-carrying dispatch entry makes a stub call with NO
  ``timeout=`` at all. (Inside any servicer/router-dispatch method —
  EDL201's syntactic surface — the bare missing-timeout case stays
  EDL201's; EDL202 covers the call chain EDL201 cannot see: helper
  classes the dispatch path flows through.)
* **EDL203 — deadline replaced by an unbounded default.** The stub
  call HAS a ``timeout=``, but the value does not derive from the
  inbound budget — a config constant, a literal — so a client with
  200 ms left waits the server's 120 s default, pinning a handler
  thread long after the client gave up. A helper that never RECEIVES
  the budget (no deadline-ish parameter threads in) cannot derive a
  correct timeout from it, so its static timeouts are EDL203 too.

Derivation is decided by forward MAY-taint over the function's CFG
(dataflow.tainted_names): seeds are the request-ish and timeout-ish
parameters (for nested ``def``s, the enclosing function's seeds are
closure-visible and carry over), plus any ``<x>.deadline_ms`` read;
anything assigned from an expression mentioning a tainted name is
tainted — so ``remaining_ms, timeout = self._budget(request, t0)``
taints both, and ``min(timeout, remaining)`` stays tainted.

Reachability uses the module call graph (``self.m()`` and
``self.attr.m()`` with the attribute's class resolved by the project
index). Heartbeat/poll paths are not dispatch-reachable and keep
their static poll timeouts without complaint.
"""

import ast

from elasticdl_tpu.analysis.cfg import build_cfg, walk_shallow
from elasticdl_tpu.analysis.core import Finding, Rule, register
from elasticdl_tpu.analysis.dataflow import (
    ModuleIndex,
    ProjectIndex,
    mentions,
    tainted_names,
)

_ROUTER_METHOD_PREFIXES = ("dispatch", "_dispatch", "_call")

#: parameter names that carry the inbound request / budget
_REQUESTISH = frozenset(["request", "req", "proto_req"])
_TIMEOUTISH = frozenset([
    "timeout", "timeout_secs", "timeout_ms", "deadline", "deadline_ms",
    "deadline_secs", "remaining", "remaining_ms", "remaining_secs",
    "budget", "budget_ms",
])

_DEADLINE_ATTRS = ("deadline_ms", "deadline")


def _is_deadline_read(node):
    return (isinstance(node, ast.Attribute)
            and node.attr in _DEADLINE_ATTRS)


def _param_names(fndef):
    names = [a.arg for a in fndef.args.args]
    names.extend(a.arg for a in fndef.args.kwonlyargs)
    return [n for n in names if n != "self"]


def _budget_params(fndef):
    return frozenset(
        n for n in _param_names(fndef)
        if n in _REQUESTISH or n in _TIMEOUTISH
    )


def _reads_deadline(fndef):
    for node in walk_shallow(fndef):
        if node is not fndef and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        if _is_deadline_read(node):
            return True
    return False


def _recv_text(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)).lower()


def _entry_methods(index):
    """[(ClassInfo, fndef, is_edl201_context)] dispatch entries."""
    out = []
    for info in index.classes.values():
        servicer = info.name.endswith("Servicer")
        router = info.name.endswith("Router")
        if not (servicer or router):
            continue
        for name, fn in info.methods.items():
            if name == "__init__":
                continue
            if router and not servicer and not name.startswith(
                _ROUTER_METHOD_PREFIXES
            ):
                continue
            out.append((info, fn))
    return out


def _callees(index, info, fndef):
    """(class_name, method_name) pairs this method may call."""
    out = []
    for node in walk_shallow(fndef):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        target = index.resolve_receiver(info, fn.value)
        if target is not None and fn.attr in target.methods:
            out.append((target.name, fn.attr))
    # nested defs are analyzed with their enclosing function; their
    # calls count as the enclosing function's
    return out


@register
class DeadlinePropagationRule(Rule):
    """EDL202/EDL203 — see module docstring. One checker, both ids."""

    id = "EDL202"
    name = "deadline-propagation"

    def check_module(self, tree, lines, path):
        index = ProjectIndex([ModuleIndex(tree, path)])
        entries = _entry_methods(index)
        if not entries:
            return []

        # dispatch-reachable closure, seeded by deadline-carrying
        # entries (an entry with no budget in scope imposes nothing).
        # EDL201's syntactic surface is EVERY servicer/router-dispatch
        # method, so the bare missing-timeout case stays EDL201's
        # there, whether or not the method is a seed.
        surface = {(info.name, fn.name) for info, fn in entries}
        reachable = set()
        work = []
        for info, fn in entries:
            if _budget_params(fn) or _reads_deadline(fn):
                key = (info.name, fn.name)
                reachable.add(key)
                work.append(key)
        while work:
            cls_name, m_name = work.pop()
            info = index.classes[cls_name]
            fn = info.methods[m_name]
            for callee in _callees(index, info, fn):
                if callee not in reachable:
                    reachable.add(callee)
                    work.append(callee)

        findings = []
        for key in sorted(reachable):
            cls_name, m_name = key
            info = index.classes[cls_name]
            fn = info.methods[m_name]
            findings.extend(self._check_function(
                path, "%s.%s" % (cls_name, m_name), fn,
                is_entry_context=key in surface,
                closure_seeds=frozenset(),
            ))
        return findings

    def _check_function(self, path, scope, fndef, is_entry_context,
                        closure_seeds):
        seeds = _budget_params(fndef) | closure_seeds
        has_budget = bool(seeds) or _reads_deadline(fndef)
        cfg = build_cfg(fndef)
        taint = tainted_names(cfg, seeds, is_source=_is_deadline_read)
        findings = []
        nested = [
            n for n in walk_shallow(fndef)
            if n is not fndef
            and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for node in cfg.nodes:
            for root in node.scan_roots():
                state = taint.get(node, seeds)
                for n in walk_shallow(root):
                    if not isinstance(n, ast.Call):
                        continue
                    if not isinstance(n.func, ast.Attribute):
                        continue
                    recv = _recv_text(n.func.value)
                    if "stub" not in recv:
                        continue
                    findings.extend(self._check_stub_call(
                        path, scope, n, recv, state, has_budget,
                        is_entry_context,
                    ))
        # nested defs (the stream-generator idiom): the closure sees
        # the enclosing seeds PLUS whatever locals are budget-tainted
        # where the def executes (``budget = request.deadline_ms;
        # def gen(): ... timeout=budget`` is a correct propagation)
        for sub in {id(n): n for n in nested}.values():
            at_def = seeds
            for node in cfg.nodes:
                if node.kind == "stmt" and node.payload is sub:
                    at_def = taint.get(node, seeds) | seeds
                    break
            findings.extend(self._check_function(
                path, "%s.%s" % (scope, sub.name), sub,
                # lexically inside the parent: EDL201's surface too
                is_entry_context=is_entry_context,
                closure_seeds=at_def,
            ))
        return findings

    def _check_stub_call(self, path, scope, call, recv, state,
                         has_budget, is_entry_context):
        timeout_kw = None
        for kw in call.keywords:
            if kw.arg == "timeout":
                timeout_kw = kw
        detail = "%s.%s" % (recv, call.func.attr)
        if timeout_kw is None:
            if is_entry_context:
                return  # EDL201 owns the bare case in entry contexts
            yield Finding(
                "EDL202", path, call.lineno, scope, detail,
                "stub RPC drops the inbound deadline: no timeout= on "
                "a dispatch-reachable call — the remaining client "
                "budget must flow into every downstream RPC",
            )
            return
        value = timeout_kw.value
        derived = (
            mentions(value, state)
            or any(_is_deadline_read(n) for n in ast.walk(value))
        )
        if derived:
            return
        if has_budget:
            msg = ("stub RPC replaces the inbound deadline with an "
                   "unbounded/static default: timeout= does not "
                   "derive from the request's remaining budget "
                   "(decrement and forward it instead)")
        else:
            msg = ("stub RPC in a dispatch-reachable helper uses a "
                   "static timeout, but the inbound deadline is never "
                   "threaded into this helper — pass the remaining "
                   "budget through and derive timeout= from it")
        yield Finding("EDL203", path, call.lineno, scope, detail, msg)
