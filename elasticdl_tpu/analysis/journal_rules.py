"""C22 — journal-protocol verification (EDL701 write/replay closure,
EDL702 payload-schema drift, EDL703 transition legality, EDL704
crash-point closure).

A module that declares ``PROTOCOL = JournalProtocol(...)`` (see
`typestate.py`) opts its write-ahead journal into four checks, all
derived from the SAME declaration the controller executes at runtime.
The checker re-reads the declaration from the AST — it never imports
the module — so it works on fixture files and in the minimal CI lint
environment where the serving dependency chain is absent.

* EDL701 — write/replay closure. Every event kind passed to the
  declared emit surface (``self._journal({...})``,
  ``registry.record({...})``) must have a branch in the paired replay
  function, and every replay branch must name a declared, emitted
  kind: a forgotten branch strands a fleet after a controller crash;
  a dead branch is recovery code nothing can ever reach. Kinds
  declared ``informational`` (forensic beacons like the router's
  ``lease``) are exempt from the replay side. On the modules listed
  in `protocol_specs.WAL_CONTROLLERS` a MISSING declaration convicts
  too — new journal consumers are born gated.
* EDL702 — payload-schema drift. The keys DEFINITELY present in the
  event dict at each emit site (dict-literal keys plus unconditional
  ``ev["k"] = ...`` stores, resolved with a MUST dataflow over the
  CFG, so a key added under ``if why:`` stays non-definite) must
  cover both the keys the replay branch reads unconditionally
  (``ev["k"]``; ``.get``/``in`` reads are tolerant by construction)
  and the spec's declared ``requires``. Conviction names the missing
  key.
* EDL703 — transition legality. A typestate pass over each method's
  CFG tracks the machine state — seeded by ``self.<attr> = LITERAL``
  assignments (the way EDL004 infers lock bindings) and advanced by
  emit sites and recognized setter calls — and flags an emit the
  declared machine forbids from the current state: ``commit`` while
  still ``staging``. Unknown state convicts nothing (unresolvable =
  silent, like every engine layer).
* EDL704 — crash-point closure. After any state-changing emit that
  can reach ANOTHER emit on a CFG path, the machine must sit in a
  state with a declared resume action (``recoverable``) or a
  terminal state: the window between two journal writes is exactly
  where a SIGKILL strands the on-disk prefix, and "the prefix
  replays to a state recovery knows how to resume" is the invariant
  rollout.py used to document by hand.

Precision over recall throughout: an emit whose payload or kind the
dataflow cannot resolve contributes nothing to 702-704 (and marks
the machine state unknown rather than guessing); only a resolved,
definitely-illegal fact convicts.
"""

import ast
import os

from elasticdl_tpu.analysis import protocol_specs
from elasticdl_tpu.analysis.cfg import build_cfg, walk_shallow
from elasticdl_tpu.analysis.core import Finding, Rule, register
from elasticdl_tpu.analysis.dataflow import forward
from elasticdl_tpu.analysis.typestate import (
    ProtocolError,
    find_protocol_decl,
    machine_from_ast,
    module_constant_env,
)

_NO = object()       # unresolvable constant
_UNKNOWN = "\x00?"   # typestate lattice top: any state


def _const(node, env):
    """The compile-time value of `node` (Constant, or a module-level
    constant Name), else the _NO sentinel."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id, _NO)
    return _NO


def _call_name(call):
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _self_attr(node):
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _sub_key(sub):
    sl = sub.slice
    if sl.__class__.__name__ == "Index":  # pre-3.9 AST compat
        sl = sl.value
    return sl


def _functions(tree):
    """[(scope, fndef, class-name-or-None)] for module-level functions
    and methods of module-level classes (the only scopes a journal
    protocol lives in)."""
    out = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node, None))
        elif isinstance(node, ast.ClassDef):
            for s in node.body:
                if isinstance(s, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    out.append(
                        ("%s.%s" % (node.name, s.name), s, node.name)
                    )
    return out


# -------------------------------------------------------- emit sites


class _Emit(object):
    """One resolved (or unresolved: kind None) emit call site."""

    __slots__ = ("kind", "keys", "open_keys", "values", "line",
                 "scope")

    def __init__(self, kind, keys, open_keys, values, line, scope):
        self.kind = kind
        self.keys = keys
        self.open_keys = open_keys
        self.values = values  # key -> resolved constant (or _NO)
        self.line = line
        self.scope = scope


def _parse_dict(d, env):
    """(definite keys, resolved values, has-star) for a dict literal
    with all-constant keys; None when a key is unresolvable."""
    keys, values, open_keys = set(), {}, False
    for k, v in zip(d.keys, d.values):
        if k is None:          # ** expansion: unknown extra keys
            open_keys = True
            continue
        kv = _const(k, env)
        if not isinstance(kv, str):
            return None
        keys.add(kv)
        values[kv] = _const(v, env)
    return frozenset(keys), values, open_keys


def _payload_flow(cfg, env, kind_key):
    """MUST dataflow: at each node, which local names definitely hold
    an event dict, with which kind and which definitely-present keys.
    State: frozenset of (var, kind, keys, open). A key added on only
    one branch of an ``if`` does not survive the intersection join —
    exactly the tolerant-``.get``-on-replay contract."""

    def effects(node, st):
        if node.kind != "stmt":
            return st
        s = node.payload
        if isinstance(s, ast.Assign) and len(s.targets) == 1:
            t = s.targets[0]
            if isinstance(t, ast.Name):
                st = frozenset(e for e in st if e[0] != t.id)
                if isinstance(s.value, ast.Dict):
                    parsed = _parse_dict(s.value, env)
                    if parsed is not None:
                        keys, values, open_keys = parsed
                        kind = values.get(kind_key, _NO)
                        if isinstance(kind, str):
                            st = st | {(t.id, kind, keys, open_keys)}
                return st
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)):
                var = t.value.id
                cur = [e for e in st if e[0] == var]
                if cur:
                    e = cur[0]
                    key = _const(_sub_key(t), env)
                    st = st - {e}
                    if isinstance(key, str):
                        st = st | {(var, e[1], e[2] | {key}, e[3])}
                return st
            return st
        if isinstance(s, (ast.AugAssign, ast.Delete)):
            names = {n.id for n in ast.walk(s)
                     if isinstance(n, ast.Name)}
            return frozenset(e for e in st if e[0] not in names)
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            fn = s.value.func
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)):
                var = fn.value.id
                cur = [e for e in st if e[0] == var]
                if cur:
                    e = cur[0]
                    if fn.attr == "setdefault" and s.value.args:
                        key = _const(s.value.args[0], env)
                        if isinstance(key, str):
                            return (st - {e}) | {
                                (var, e[1], e[2] | {key}, e[3])
                            }
                        return st - {e}
                    if fn.attr == "update":
                        # adds unknown keys; definite set is intact
                        return (st - {e}) | {(var, e[1], e[2], True)}
                    if fn.attr in ("pop", "popitem", "clear"):
                        return st - {e}
        return st

    def join(a, b):
        am = {e[0]: e for e in a}
        out = set()
        for e in b:
            o = am.get(e[0])
            if o is not None and o[1] == e[1]:
                out.add((e[0], e[1], o[2] & e[2], o[3] or e[3]))
        return frozenset(out)

    return forward(cfg, effects, entry_state=frozenset(), join=join)


def _collect_emits(scope, cfg, env, spec):
    """Every `spec.emit` call in the CFG, resolved where possible.
    Returns (emits, by_call_id) — the id-map lets the typestate pass
    reuse resolution when it re-encounters the same Call node."""
    states = _payload_flow(cfg, env, spec.kind_key)
    emits, by_id = [], {}
    for node in cfg.nodes:
        for root in node.scan_roots():
            for n in walk_shallow(root):
                if not isinstance(n, ast.Call):
                    continue
                if _call_name(n) != spec.emit or not n.args:
                    continue
                if id(n) in by_id:  # finally-copies share AST nodes
                    continue
                arg = n.args[0]
                emit = None
                if isinstance(arg, ast.Dict):
                    parsed = _parse_dict(arg, env)
                    if parsed is not None:
                        keys, values, open_keys = parsed
                        kind = values.get(spec.kind_key, _NO)
                        if isinstance(kind, str):
                            emit = _Emit(kind, keys, open_keys,
                                         values, n.lineno, scope)
                elif isinstance(arg, ast.Name):
                    match = [
                        e for e in states.get(node, frozenset())
                        if e[0] == arg.id
                    ]
                    if match:
                        _, kind, keys, open_keys = match[0]
                        emit = _Emit(kind, keys, open_keys, {},
                                     n.lineno, scope)
                if emit is None:
                    emit = _Emit(None, frozenset(), True, {},
                                 n.lineno, scope)
                emits.append(emit)
                by_id[id(n)] = emit
    return emits, by_id


# ------------------------------------------------------- replay side


class _Replay(object):
    __slots__ = ("found", "scope", "line", "branches", "required",
                 "optional", "g_required", "g_optional")

    def __init__(self):
        self.found = False
        self.scope = ""
        self.line = 0
        self.branches = {}   # kind -> first branch line
        self.required = {}   # kind -> set(keys read unconditionally)
        self.optional = {}   # kind -> set(keys read tolerantly)
        self.g_required = set()  # reads outside any kind branch
        self.g_optional = set()

    def _record(self, key, kinds, required):
        if kinds is None:
            (self.g_required if required else self.g_optional).add(key)
            return
        for k in kinds:
            bucket = self.required if required else self.optional
            bucket.setdefault(k, set()).add(key)


def _kind_expr_ev(expr, kind_key):
    """The event-var name when `expr` spells ``ev.get(kind_key)`` or
    ``ev[kind_key]``, else None."""
    if (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get"
            and isinstance(expr.func.value, ast.Name)
            and expr.args
            and isinstance(expr.args[0], ast.Constant)
            and expr.args[0].value == kind_key):
        return expr.func.value.id
    if (isinstance(expr, ast.Subscript)
            and isinstance(expr.value, ast.Name)):
        key = _sub_key(expr)
        if isinstance(key, ast.Constant) and key.value == kind_key:
            return expr.value.id
    return None


def _find_ev_binding(fn, kind_key):
    """(event var, kind var) of the replay dispatch: either a
    ``kind = ev.get("ev")`` binding or a direct ``ev["ev"] == ...``
    comparison; (None, None) when the shape is unrecognized."""
    for n in walk_shallow(fn):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)):
            ev = _kind_expr_ev(n.value, kind_key)
            if ev is not None:
                return ev, n.targets[0].id
    for n in walk_shallow(fn):
        if isinstance(n, ast.Compare):
            ev = _kind_expr_ev(n.left, kind_key)
            if ev is not None:
                return ev, None
    return None, None


def _test_kinds(test, evvar, kindvar, kind_key, env):
    """The kind literals a dispatch test selects (``kind == "x"``,
    ``kind in ("a", "b")``, possibly inside an ``and``), else None."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            kinds = _test_kinds(v, evvar, kindvar, kind_key, env)
            if kinds is not None:
                return kinds
        return None
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return None
    left = test.left
    is_kind = (
        (kindvar is not None and isinstance(left, ast.Name)
         and left.id == kindvar)
        or _kind_expr_ev(left, kind_key) == evvar
    )
    if not is_kind:
        return None
    comp = test.comparators[0]
    if isinstance(test.ops[0], ast.Eq):
        v = _const(comp, env)
        return [v] if isinstance(v, str) else None
    if (isinstance(test.ops[0], ast.In)
            and isinstance(comp, (ast.Tuple, ast.List, ast.Set))):
        kinds = [_const(e, env) for e in comp.elts]
        if kinds and all(isinstance(k, str) for k in kinds):
            return kinds
    return None


def _guard_keys(test, evvar, env):
    """Keys whose PRESENCE the test establishes on its true branch
    (``"why" in ev``, ``ev.get("ok")``): subscript reads under such a
    guard are tolerant, not required."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        out = set()
        for v in test.values:
            out |= _guard_keys(v, evvar, env)
        return out
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.In)
            and isinstance(test.comparators[0], ast.Name)
            and test.comparators[0].id == evvar):
        key = _const(test.left, env)
        return {key} if isinstance(key, str) else set()
    if (isinstance(test, ast.Call)
            and isinstance(test.func, ast.Attribute)
            and test.func.attr == "get"
            and isinstance(test.func.value, ast.Name)
            and test.func.value.id == evvar
            and test.args):
        key = _const(test.args[0], env)
        return {key} if isinstance(key, str) else set()
    return set()


def _scan_reads(node, evvar, kinds, guarded, info, env):
    """Record every read of the event var inside `node` (an expression
    or simple statement) against the kind context."""
    for n in ast.walk(node):
        if (isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and n.value.id == evvar
                and isinstance(getattr(n, "ctx", None), ast.Load)):
            key = _const(_sub_key(n), env)
            if isinstance(key, str):
                info._record(key, kinds, required=key not in guarded)
        elif (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("get", "setdefault")
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == evvar
                and n.args):
            key = _const(n.args[0], env)
            if isinstance(key, str):
                info._record(key, kinds, required=False)
        elif (isinstance(n, ast.Compare) and len(n.ops) == 1
                and isinstance(n.ops[0], (ast.In, ast.NotIn))
                and isinstance(n.comparators[0], ast.Name)
                and n.comparators[0].id == evvar):
            key = _const(n.left, env)
            if isinstance(key, str):
                info._record(key, kinds, required=False)


def _scan_replay_block(stmts, evvar, kindvar, kind_key, env, kinds,
                       guarded, info):
    for s in stmts:
        if isinstance(s, ast.If):
            branch = _test_kinds(s.test, evvar, kindvar, kind_key,
                                 env)
            _scan_reads(s.test, evvar, kinds, guarded, info, env)
            if branch is not None:
                for k in branch:
                    info.branches.setdefault(k, s.lineno)
                _scan_replay_block(s.body, evvar, kindvar, kind_key,
                                   env, branch, guarded, info)
                _scan_replay_block(s.orelse, evvar, kindvar,
                                   kind_key, env, kinds, guarded,
                                   info)
            else:
                g = guarded | _guard_keys(s.test, evvar, env)
                _scan_replay_block(s.body, evvar, kindvar, kind_key,
                                   env, kinds, g, info)
                _scan_replay_block(s.orelse, evvar, kindvar,
                                   kind_key, env, kinds, guarded,
                                   info)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            _scan_reads(s.iter, evvar, kinds, guarded, info, env)
            _scan_replay_block(s.body, evvar, kindvar, kind_key, env,
                               kinds, guarded, info)
            _scan_replay_block(s.orelse, evvar, kindvar, kind_key,
                               env, kinds, guarded, info)
        elif isinstance(s, ast.While):
            _scan_reads(s.test, evvar, kinds, guarded, info, env)
            _scan_replay_block(s.body, evvar, kindvar, kind_key, env,
                               kinds, guarded, info)
            _scan_replay_block(s.orelse, evvar, kindvar, kind_key,
                               env, kinds, guarded, info)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                _scan_reads(item.context_expr, evvar, kinds, guarded,
                            info, env)
            _scan_replay_block(s.body, evvar, kindvar, kind_key, env,
                               kinds, guarded, info)
        elif isinstance(s, ast.Try) or type(s).__name__ == "TryStar":
            for block in (s.body, s.orelse, s.finalbody):
                _scan_replay_block(block, evvar, kindvar, kind_key,
                                   env, kinds, guarded, info)
            for h in s.handlers:
                _scan_replay_block(h.body, evvar, kindvar, kind_key,
                                   env, kinds, guarded, info)
        else:
            _scan_reads(s, evvar, kinds, guarded, info, env)


def _analyze_replay(fn, scope, kind_key, env):
    info = _Replay()
    info.found = True
    info.scope = scope
    info.line = fn.lineno
    evvar, kindvar = _find_ev_binding(fn, kind_key)
    if evvar is None:
        # unrecognized dispatch shape: report nothing about branches
        # (precision over recall), but remember we saw the function
        return info
    _scan_replay_block(fn.body, evvar, kindvar, kind_key, env, None,
                       frozenset(), info)
    return info


# ----------------------------------------------------- typestate pass


class _ClassCtx(object):
    """Per-class context for the EDL703/704 machine walk."""

    __slots__ = ("spec", "env", "emit_info", "setters", "touching",
                 "state_attrs")

    def __init__(self, spec, env):
        self.spec = spec
        self.env = env
        self.emit_info = {}   # id(Call) -> _Emit
        self.setters = {}     # method -> (kind, param idx, param name)
        self.touching = set()  # methods that may move the machine
        self.state_attrs = set()


def _detect_setters(methods, spec, env):
    """Methods that journal a ``to_key`` event whose target state is
    one of their own parameters — rollout's ``_set_phase(phase, why)``
    shape. The payload dict may be passed to the emit call inline or
    built into a local first (``ev = {...}; self._journal(ev)``), so
    the scan looks at every dict literal in a method that emits at
    all. A call site passing a literal state is then a resolvable
    pseudo-emit."""
    setters = {}
    for name, fn in methods.items():
        params = [a.arg for a in fn.args.args]
        if params and params[0] == "self":
            params = params[1:]
        if not params:
            continue
        if not any(isinstance(n, ast.Call)
                   and _call_name(n) == spec.emit
                   for n in walk_shallow(fn)):
            continue
        for n in walk_shallow(fn):
            if not isinstance(n, ast.Dict):
                continue
            kind, to_param = None, None
            for k, v in zip(n.keys, n.values):
                if k is None:
                    continue
                kv = _const(k, env)
                if kv == spec.kind_key:
                    c = _const(v, env)
                    kind = c if isinstance(c, str) else None
            ev = spec.events.get(kind) if kind else None
            if ev is None or ev.to_key is None:
                continue
            for k, v in zip(n.keys, n.values):
                if (k is not None and _const(k, env) == ev.to_key
                        and isinstance(v, ast.Name)
                        and v.id in params):
                    to_param = v.id
            if to_param is not None:
                setters[name] = (kind, params.index(to_param),
                                 to_param)
    return setters


def _build_class_ctx(spec, env, members):
    """`members`: [(scope, fndef, cfg, emits_by_call_id)]."""
    ctx = _ClassCtx(spec, env)
    methods = {fn.name: fn for _s, fn, _c, _b in members}
    for _s, _f, _c, by_id in members:
        ctx.emit_info.update(by_id)
    ctx.setters = _detect_setters(methods, spec, env)
    # state attrs: assigned a state literal anywhere in the class, or
    # assigned the to_key parameter inside a setter
    for name, fn in methods.items():
        setter = ctx.setters.get(name)
        for n in walk_shallow(fn):
            if not isinstance(n, ast.Assign):
                continue
            for t in n.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                v = _const(n.value, env)
                if v is not _NO and v in spec.states:
                    ctx.state_attrs.add(attr)
                elif (setter is not None
                        and isinstance(n.value, ast.Name)
                        and n.value.id == setter[2]):
                    ctx.state_attrs.add(attr)
    # touching fixpoint: a method that emits, assigns a state attr,
    # or calls a touching method can move the machine
    calls = {}
    for name, fn in methods.items():
        touches = False
        callees = set()
        for n in walk_shallow(fn):
            if isinstance(n, ast.Call):
                if _call_name(n) == spec.emit:
                    touches = True
                attr = _self_attr(n.func)
                if attr is not None:
                    callees.add(attr)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if _self_attr(t) in ctx.state_attrs:
                        touches = True
        calls[name] = callees
        if touches:
            ctx.touching.add(name)
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in ctx.touching and callees & ctx.touching:
                ctx.touching.add(name)
                changed = True
    return ctx


def _machine_effects(node, st, ctx, sink=None):
    """Typestate transfer for one CFG node. With `sink` (the
    post-fixpoint reporting pass) also records convictions and
    emit-site post-states: sink = (convictions, emit_records,
    emit_nodes)."""
    spec = ctx.spec
    for root in node.scan_roots():
        for n in walk_shallow(root):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    attr = _self_attr(t)
                    if attr in ctx.state_attrs:
                        v = _const(n.value, ctx.env)
                        st = v if v in spec.states else _UNKNOWN
            elif isinstance(n, ast.Call):
                name = _call_name(n)
                if name == spec.emit and n.args:
                    st = _emit_effect(node, n, st, ctx, sink)
                    continue
                attr = _self_attr(n.func)
                if attr is None:
                    continue
                if attr in ctx.setters:
                    st = _setter_effect(node, n, attr, st, ctx, sink)
                elif attr in ctx.touching:
                    st = _UNKNOWN
    return st


def _emit_effect(node, call, st, ctx, sink):
    spec = ctx.spec
    e = ctx.emit_info.get(id(call))
    if e is None or e.kind is None:
        return _UNKNOWN
    ev = spec.events.get(e.kind)
    if ev is None:
        return _UNKNOWN  # EDL701 owns the conviction
    if sink is not None:
        sink[2].add(node.idx)
    if ev.informational or ev.entity_key is not None:
        return st
    payload = {k: v for k, v in e.values.items() if v is not _NO}
    cur = None if st == _UNKNOWN else st
    if sink is not None and cur is not None:
        if not spec.legal(cur, e.kind, payload):
            sink[0].append(Finding(
                "EDL703", None, e.line, e.scope,
                "%s@%s" % (e.kind, cur),
                "event %r journaled while the %r machine is in "
                "state %r, which the declared protocol forbids "
                "(legal from: %s)" % (
                    e.kind, spec.name, cur,
                    "any" if spec.events[e.kind].frm == "*" else
                    ", ".join(spec.events[e.kind].frm),
                ),
            ))
    nxt = spec.apply(cur, e.kind, payload)
    out = _UNKNOWN if nxt is None else nxt
    if sink is not None:
        sink[1].append((node.idx, e.kind, out, e.line, e.scope))
    return out


def _setter_effect(node, call, attr, st, ctx, sink):
    spec = ctx.spec
    kind, pidx, pname = ctx.setters[attr]
    ev = spec.events[kind]
    target = _NO
    if pidx < len(call.args):
        target = _const(call.args[pidx], ctx.env)
    else:
        for kw in call.keywords:
            if kw.arg == pname:
                target = _const(kw.value, ctx.env)
    if sink is not None:
        sink[2].add(node.idx)
    if target is _NO or target not in spec.states:
        return _UNKNOWN
    cur = None if st == _UNKNOWN else st
    if sink is not None and cur is not None:
        if not spec.legal(cur, kind, {ev.to_key: target}):
            sink[0].append(Finding(
                "EDL703", None, call.lineno,
                "", "%s:%s@%s" % (kind, target, cur),
                "transition to %r (via %r) while the %r machine is "
                "in state %r, which the declared transitions "
                "forbid" % (target, attr, spec.name, cur),
            ))
    if sink is not None:
        sink[1].append((node.idx, kind, target, call.lineno, ""))
    return target


def _typestate_findings(spec, env, members, path):
    """EDL703 + EDL704 findings for one class's methods."""
    ctx = _build_class_ctx(spec, env, members)
    out = []
    ok_states = (set(spec.recoverable) | set(spec.terminal)
                 | {_UNKNOWN})
    for scope, fn, cfg, _by_id in members:
        in_states = forward(
            cfg,
            lambda n, s: _machine_effects(n, s, ctx),
            entry_state=_UNKNOWN,
            join=lambda a, b: a if a == b else _UNKNOWN,
        )
        convictions, records, emit_nodes = [], [], set()
        sink = (convictions, records, emit_nodes)
        for node in cfg.nodes:
            st = in_states.get(node)
            if st is None:
                continue  # unreachable
            _machine_effects(node, st, ctx, sink=sink)
        for f in convictions:
            f.path = path
            if not f.scope:
                f.scope = scope
            out.append(f)
        for idx, kind, s_after, line, escope in records:
            if s_after in ok_states:
                continue
            # can a LATER journal write happen while the machine sits
            # in this non-recoverable state?
            seen, stack = set(), list(cfg.nodes[idx].out)
            reaches = False
            while stack and not reaches:
                n = stack.pop()
                if n.idx in seen:
                    continue
                seen.add(n.idx)
                if n.idx in emit_nodes:
                    reaches = True
                    break
                stack.extend(n.out)
            if reaches:
                out.append(Finding(
                    "EDL704", path, line, escope or scope,
                    "%s@%s" % (kind, s_after),
                    "a crash after this %r emit strands the journal "
                    "in state %r, which declares no resume action "
                    "(not in `recoverable`), yet another journal "
                    "write is reachable — the window between the "
                    "two writes is an unrecoverable crash "
                    "point" % (kind, s_after),
                ))
    return out


# ------------------------------------------------------------ checker


@register
class JournalProtocolRule(Rule):
    """C22 — journal-protocol verification: write/replay closure
    (EDL701), payload-schema drift (EDL702), transition legality
    (EDL703), crash-point closure (EDL704)."""

    id = "EDL701"
    name = "journal-protocol"

    def check_module(self, tree, lines, path):
        decl = find_protocol_decl(tree)
        if decl is None:
            return
        env = module_constant_env(tree)
        try:
            spec = machine_from_ast(decl.value, env)
        except ProtocolError as e:
            yield Finding(
                "EDL701", path, decl.lineno, "<module>",
                "malformed-protocol",
                "PROTOCOL declaration is not a valid pure-literal "
                "JournalProtocol: %s" % e,
            )
            return

        funcs = _functions(tree)
        members = []  # (scope, fn, cls, cfg, emits, by_id)
        for scope, fn, cls in funcs:
            cfg = build_cfg(fn)
            emits, by_id = _collect_emits(scope, cfg, env, spec)
            members.append((scope, fn, cls, cfg, emits, by_id))

        replay = _Replay()
        for scope, fn, cls, _cfg, _e, _b in members:
            if fn.name == spec.replay:
                replay = _analyze_replay(fn, scope, spec.kind_key,
                                         env)
                break

        all_emits = [e for _s, _f, _c, _g, es, _b in members
                     for e in es]
        resolved = [e for e in all_emits if e.kind is not None]
        unresolved = len(all_emits) - len(resolved)
        first = {}
        for e in resolved:
            first.setdefault(e.kind, e)

        # ---- EDL701: write/replay closure
        if not replay.found:
            yield Finding(
                "EDL701", path, decl.lineno, "<module>",
                "missing-replay:%s" % spec.replay,
                "the declared replay function %r does not exist in "
                "this module — every journaled event is "
                "unrecoverable" % spec.replay,
            )
        for kind in sorted(first):
            e = first[kind]
            ev = spec.events.get(kind)
            if ev is None:
                yield Finding(
                    "EDL701", path, e.line, e.scope,
                    "undeclared-kind:%s" % kind,
                    "event kind %r is journaled but absent from the "
                    "declared protocol alphabet — declare it (with "
                    "its transition and payload contract) or drop "
                    "the emit" % kind,
                )
            elif (replay.found and not ev.informational
                    and kind not in replay.branches):
                yield Finding(
                    "EDL701", path, e.line, e.scope,
                    "no-replay:%s" % kind,
                    "event kind %r is journaled here but %r has no "
                    "branch for it: after a crash the event replays "
                    "as a no-op and recovery diverges from the "
                    "pre-crash state" % (kind, spec.replay),
                )
        for kind in sorted(replay.branches):
            line = replay.branches[kind]
            if kind not in spec.events:
                yield Finding(
                    "EDL701", path, line, replay.scope,
                    "dead-replay:%s" % kind,
                    "replay branch for kind %r, which the declared "
                    "protocol does not know — dead recovery code "
                    "(or an undeclared event)" % kind,
                )
            elif resolved and not unresolved and kind not in first:
                yield Finding(
                    "EDL701", path, line, replay.scope,
                    "never-emitted:%s" % kind,
                    "replay branch for kind %r, which no emit site "
                    "in this module produces — dead recovery "
                    "code" % kind,
                )

        # ---- EDL702: payload-schema drift
        for e in resolved:
            ev = spec.events.get(e.kind)
            if ev is None or e.open_keys:
                continue
            needed = set(replay.required.get(e.kind, ()))
            needed |= set(ev.requires)
            if ev.entity_key:
                needed.add(ev.entity_key)
            missing = needed - set(e.keys) - {spec.kind_key}
            for key in sorted(missing):
                yield Finding(
                    "EDL702", path, e.line, e.scope,
                    "%s.%s" % (e.kind, key),
                    "emit site for %r does not definitely write key "
                    "%r, which replay (or the declared contract) "
                    "requires — a key added only on some branches "
                    "must be declared `optional` and read via "
                    ".get()" % (e.kind, key),
                )

        # ---- EDL703/EDL704: typestate + crash-point closure
        by_class = {}
        for scope, fn, cls, cfg, _e, by_id in members:
            by_class.setdefault(cls, []).append(
                (scope, fn, cfg, by_id)
            )
        for cls in sorted(by_class, key=lambda c: c or ""):
            for f in _typestate_findings(spec, env, by_class[cls],
                                         path):
                yield f

    def check_repo(self, root):
        out = []
        for rel in protocol_specs.WAL_CONTROLLERS:
            full = os.path.join(root, *rel.split("/"))
            if not os.path.exists(full):
                continue
            try:
                with open(full) as f:
                    tree = ast.parse(f.read(), filename=full)
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue
            if find_protocol_decl(tree) is None:
                out.append(Finding(
                    "EDL701", rel, 1, "<module>", "missing-protocol",
                    "this module is a registered WAL controller "
                    "(analysis/protocol_specs.py) but declares no "
                    "PROTOCOL = JournalProtocol(...) — its journal "
                    "is unchecked",
                ))
        return out
