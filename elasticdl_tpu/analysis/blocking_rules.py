"""C3 — blocking-call checker (EDL201).

Hot control-plane threads must never block unboundedly: a gRPC
servicer method that sleeps or waits without a timeout pins one of the
server's worker threads (the pool is finite — enough pinned handlers
is a full outage that LOOKS like load), and the router's dispatch path
is latency-budgeted end to end. This codebase's convention is that
every wait carries a timeout and every pause is the injected
``self._sleep`` (testable, bounded); raw blocking primitives are the
bug.

CONTEXTS checked (methods plus their nested functions):

* every method of a class whose name ends in ``Servicer`` — the gRPC
  handler surface;
* dispatch-path methods (``dispatch*``/``_dispatch*``/``_call*``) of a
  class whose name ends in ``Router``.

FLAGGED inside a context:

* ``time.sleep(...)`` — unconditionally (the injected ``self._sleep``
  is the sanctioned form, precisely because tests can compress it);
* ``<queue-ish>.get()`` / ``.get(block=True)`` with no ``timeout=`` —
  an unbounded consumer wait (queue-ish: the receiver name mentions
  ``queue``/``q``/``results``/``events``);
* ``.wait()`` / ``.join()`` / ``.acquire()`` with neither a positional
  timeout nor a ``timeout=`` kwarg — unbounded primitive wait;
* a synchronous RPC via a stub (receiver path mentions ``stub``)
  without a ``timeout=`` kwarg — an unbounded network wait that rides
  on a peer's liveness;
* ``concurrent.futures`` waits without a bound: ``<f>.result()`` with
  no timeout (a wedged worker pins the handler exactly like a lost
  peer — the PR 4 concurrent-heartbeat shape), and ``wait(fs)`` /
  ``as_completed(fs)`` (bare or ``futures.``-qualified) without
  ``timeout=``.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, Rule, register

_QUEUEISH = ("queue", "_q", "results", "events")
_WAITERS = {"wait", "join", "acquire"}
_FUTURES_WAITS = {"wait", "as_completed"}
_ROUTER_METHOD_PREFIXES = ("dispatch", "_dispatch", "_call")


def _expr_text(node):
    """Best-effort dotted spelling of an expression for name matching."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)).lower()


def _has_timeout(call):
    return any(kw.arg == "timeout" for kw in call.keywords)


class _BlockingVisitor(ast.NodeVisitor):
    def __init__(self, path, scope):
        self.path = path
        self.scope = scope
        self.findings = []

    def _emit(self, line, detail, message):
        self.findings.append(
            Finding("EDL201", self.path, line, self.scope, detail,
                    message)
        )

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = _expr_text(fn.value)
            if fn.attr == "sleep" and recv == "time":
                self._emit(
                    node.lineno, "time.sleep",
                    "time.sleep in a servicer/dispatch path pins a "
                    "handler thread; use the injected clock/sleep or "
                    "a bounded wait",
                )
            elif (fn.attr == "get"
                    and not _has_timeout(node)
                    and not node.args
                    and any(q in recv for q in _QUEUEISH)):
                self._emit(
                    node.lineno, "%s.get" % (recv or "queue"),
                    "unbounded queue get() in a servicer/dispatch "
                    "path can hang a handler forever; pass timeout=",
                )
            elif (fn.attr in _WAITERS
                    and not node.args
                    and not _has_timeout(node)):
                self._emit(
                    node.lineno, ".%s()" % fn.attr,
                    "unbounded .%s() in a servicer/dispatch path; "
                    "pass a timeout so a lost peer cannot pin the "
                    "thread" % fn.attr,
                )
            elif (fn.attr == "result"
                    and not node.args
                    and not _has_timeout(node)):
                self._emit(
                    node.lineno, ".result()",
                    "untimed Future.result() in a servicer/dispatch "
                    "path: a wedged worker pins the handler thread; "
                    "pass timeout= and handle TimeoutError",
                )
            elif (fn.attr in _FUTURES_WAITS
                    and "futures" in recv
                    and not _has_timeout(node)):
                self._emit(
                    node.lineno, "futures.%s" % fn.attr,
                    "untimed futures.%s() in a servicer/dispatch "
                    "path waits on every future's liveness; pass "
                    "timeout=" % fn.attr,
                )
            elif "stub" in recv and not _has_timeout(node):
                self._emit(
                    node.lineno, "%s.%s" % (recv, fn.attr),
                    "synchronous stub RPC without timeout= rides on "
                    "the peer's liveness; every dispatch-path RPC "
                    "must carry a deadline",
                )
        elif (isinstance(fn, ast.Name)
                and fn.id in _FUTURES_WAITS
                and node.args
                and not _has_timeout(node)):
            self._emit(
                node.lineno, fn.id,
                "untimed %s() in a servicer/dispatch path waits on "
                "every future's liveness; pass timeout=" % fn.id,
            )
        self.generic_visit(node)


@register
class BlockingCallRule(Rule):
    """EDL201 — see module docstring."""

    id = "EDL201"
    name = "blocking-call"

    def check_module(self, tree, lines, path):
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            servicer = node.name.endswith("Servicer")
            router = node.name.endswith("Router")
            if not (servicer or router):
                continue
            for fn in node.body:
                if not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if router and not servicer and not fn.name.startswith(
                    _ROUTER_METHOD_PREFIXES
                ):
                    continue
                visitor = _BlockingVisitor(
                    path, "%s.%s" % (node.name, fn.name)
                )
                for stmt in fn.body:
                    visitor.visit(stmt)
                findings.extend(visitor.findings)
        return findings
