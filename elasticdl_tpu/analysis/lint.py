"""edl-lint CLI: ``python -m elasticdl_tpu.analysis.lint [paths...]``.

Exit status: 0 = clean (after pragma + baseline filtering and zero
stale baseline entries), 1 = findings or stale baseline entries, 2 =
usage/internal error. `make lint` runs this over ``elasticdl_tpu/``,
``scripts/`` and ``tests/`` plus ruff; the CI ``lint`` job gates on
it before the test shards.

Options:
  --baseline PATH    vetted-exception file (default:
                     <repo>/.edl-lint-baseline.json)
  --write-baseline   rewrite the baseline to cover every current
                     finding (each new entry gets a TODO reason you
                     must edit into a real justification — the runner
                     rejects empty reasons)
  --select IDS       comma-separated rule ids to run (default: all);
                     EDL001 selects EDL002 too (one checker), EDL101
                     selects EDL102/EDL103
  --list-rules       print the rule catalogue and exit
"""

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))

#: rule ids emitted by each registered checker (a checker is selected
#: when ANY of its ids is selected)
RULE_FAMILIES = {
    "EDL001": ("EDL001", "EDL002"),
    "EDL101": ("EDL101", "EDL102", "EDL103"),
    "EDL201": ("EDL201",),
    "EDL301": ("EDL301",),
    "EDL401": ("EDL401",),
}

DEFAULT_PATHS = ("elasticdl_tpu", "scripts", "tests")


def _selected_rules(select):
    from elasticdl_tpu.analysis import all_rules

    rules = all_rules()
    if not select:
        return rules
    wanted = {s.strip() for s in select.split(",") if s.strip()}
    picked = [
        r for r in rules
        if wanted & set(RULE_FAMILIES.get(r.id, (r.id,)))
    ]
    if not picked:
        raise SystemExit("--select matched no rules: %s" % select)
    return picked


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="edl-lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="*", help="files/dirs to lint")
    parser.add_argument("--baseline", default=None)
    parser.add_argument("--write-baseline", action="store_true")
    parser.add_argument("--select", default="")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", default=REPO_ROOT,
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    from elasticdl_tpu.analysis import Baseline, run_rules

    rules = _selected_rules(args.select)
    if args.list_rules:
        for rule in rules:
            doc = (sys.modules[rule.__module__].__doc__ or "")
            title = doc.strip().splitlines()[0] if doc else rule.name
            print("%s  %s\n    %s" % (rule.id, rule.name, title))
        return 0

    root = os.path.abspath(args.root)
    paths = [
        p if os.path.isabs(p) else os.path.join(root, p)
        for p in (args.paths or DEFAULT_PATHS)
    ]
    paths = [p for p in paths if os.path.exists(p)]
    if root not in sys.path:
        sys.path.insert(0, root)  # for scripts.gen_serving_proto

    findings, errors = run_rules(paths, rules=rules, root=root)
    for err in errors:
        print("edl-lint: ERROR %s" % err, file=sys.stderr)

    baseline_path = args.baseline or os.path.join(
        root, ".edl-lint-baseline.json"
    )
    if args.write_baseline:
        baseline = Baseline.from_findings(
            findings,
            reason="TODO: justify or fix (edl-lint --write-baseline)",
            path=baseline_path,
        )
        baseline.save()
        print("edl-lint: wrote %d entries to %s"
              % (len(baseline.entries), baseline_path))
        return 0

    baseline = Baseline.load(baseline_path)
    findings, stale = baseline.apply(findings)

    for f in findings:
        print(f.format())
    for e in stale:
        print(
            "edl-lint: STALE baseline entry %s %s [%s] %s — the "
            "finding it vetted is gone; delete the entry"
            % (e["rule"], e["path"], e["scope"], e["detail"]),
            file=sys.stderr,
        )
    n_base = len(baseline.entries) - len(stale)
    if findings or stale or errors:
        print(
            "edl-lint: %d finding(s), %d stale baseline entr(ies), "
            "%d error(s)" % (len(findings), len(stale), len(errors)),
            file=sys.stderr,
        )
        return 1
    print(
        "edl-lint: clean (%d rule checker(s), %d baselined "
        "exception(s))" % (len(rules), n_base)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
