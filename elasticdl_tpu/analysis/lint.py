"""edl-lint CLI: ``python -m elasticdl_tpu.analysis.lint [paths...]``.

Exit status: 0 = clean (after pragma + baseline filtering and zero
stale baseline entries), 1 = findings or stale baseline entries, 2 =
usage/internal error. `make lint` runs this over ``elasticdl_tpu/``,
``scripts/`` and ``tests/`` plus ruff; the CI ``lint`` job gates on
it before the test shards.

Options:
  --baseline PATH    vetted-exception file (default:
                     <repo>/.edl-lint-baseline.json)
  --write-baseline   rewrite the baseline to cover every current
                     finding (each new entry gets a TODO reason you
                     must edit into a real justification — the runner
                     rejects empty reasons)
  --select IDS       comma-separated rule ids to run (default: all);
                     selecting any id of a checker selects the whole
                     checker (EDL001 -> EDL002, EDL202 -> EDL203, ...)
  --jobs N           fan per-file analysis over N processes (0 = one
                     per CPU); repo-level rules stay in-process and
                     output is byte-identical to serial
  --no-cache         skip the on-disk per-file result cache
                     (.edl-lint-cache.json at the repo root, keyed by
                     file content hash x rule-set version; warm runs
                     are byte-identical to cold ones, so the only
                     reason to disable it is benchmarking or a
                     corrupted cache file)
  --changed-only     lint only files changed vs the git merge base
                     (plus untracked files) — the pre-commit mode.
                     Stale-baseline enforcement is skipped: a subset
                     scan cannot see every vetted finding
  --format FMT       `human` (default), `github` (GitHub Actions
                     ::error annotations, rendered inline on PRs) or
                     `sarif` (SARIF 2.1.0 for GitHub code scanning;
                     byte-deterministic; human lines go to stderr)
  --output FILE      where `--format sarif` writes the document
                     (default: stdout)
  --fix-pragmas      delete every unused `# edl-lint: disable=` pragma
                     (the EDL000 findings) from the scanned files and
                     exit 0 — the suppression mirror of fixing stale
                     baseline entries
  --list-rules       print the rule catalogue and exit
"""

import argparse
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))

#: rule ids emitted by each registered checker (a checker is selected
#: when ANY of its ids is selected)
RULE_FAMILIES = {
    "EDL000": ("EDL000",),
    "EDL001": ("EDL001", "EDL002"),
    "EDL003": ("EDL003",),
    "EDL004": ("EDL004",),
    "EDL101": ("EDL101", "EDL102", "EDL103", "EDL108"),
    "EDL104": ("EDL104",),
    "EDL105": ("EDL105",),
    "EDL106": ("EDL106",),
    "EDL107": ("EDL107",),
    "EDL201": ("EDL201",),
    "EDL202": ("EDL202", "EDL203"),
    "EDL301": ("EDL301",),
    "EDL401": ("EDL401",),
    "EDL501": ("EDL501",),
    "EDL601": ("EDL601",),
    "EDL701": ("EDL701", "EDL702", "EDL703", "EDL704"),
}

DEFAULT_PATHS = ("elasticdl_tpu", "scripts", "tests")


def _selected_rules(select):
    from elasticdl_tpu.analysis import all_rules

    rules = all_rules()
    if not select:
        return rules
    wanted = {s.strip() for s in select.split(",") if s.strip()}
    picked = [
        r for r in rules
        if wanted & set(RULE_FAMILIES.get(r.id, (r.id,)))
    ]
    if not picked:
        raise SystemExit("--select matched no rules: %s" % select)
    return picked


def changed_files(root, base=None):
    """Python files changed vs the merge base with `base` (tries
    origin/main, main, then HEAD~1) plus untracked ones — the
    pre-commit / fast-CI file set. Paths are absolute. Returns None
    when git is unavailable (caller falls back to a full run)."""

    def git(*args):
        out = subprocess.run(
            ("git", "-C", root) + args,
            capture_output=True, text=True, timeout=30,
        )
        if out.returncode != 0:
            return None
        return out.stdout

    merge_base = None
    for ref in ([base] if base else ["origin/main", "main", "HEAD~1"]):
        mb = git("merge-base", "HEAD", ref)
        if mb:
            merge_base = mb.strip()
            break
    names = []
    if merge_base:
        diff = git("diff", "--name-only", merge_base, "--", "*.py")
        if diff is None:
            return None
        names.extend(diff.splitlines())
    else:
        diff = git("diff", "--name-only", "HEAD", "--", "*.py")
        if diff is None:
            return None
        names.extend(diff.splitlines())
    untracked = git("ls-files", "--others", "--exclude-standard",
                    "--", "*.py")
    if untracked:
        names.extend(untracked.splitlines())
    return sorted({
        os.path.join(root, n) for n in names
        if n.strip() and os.path.exists(os.path.join(root, n))
    })


def _fix_pragmas(findings, root):
    """Delete the pragmas behind every EDL000 finding from their
    files (baseline-vetted pragmas were filtered before this runs, so
    a consciously kept suppression survives)."""
    from elasticdl_tpu.analysis.core import strip_pragma

    dead = {}
    for f in findings:
        if f.rule == "EDL000":
            dead.setdefault(f.path, set()).add(f.line)
    removed = 0
    for rel, linenos in sorted(dead.items()):
        path = rel if os.path.isabs(rel) else os.path.join(root, rel)
        with open(path) as fh:
            lines = fh.read().splitlines(keepends=True)
        out = []
        for i, text in enumerate(lines, 1):
            if i not in linenos:
                out.append(text)
                continue
            ending = "\n" if text.endswith("\n") else ""
            stripped = strip_pragma(text.rstrip("\n"))
            if stripped is not None:
                out.append(stripped + ending)
            removed += 1
        with open(path, "w") as fh:
            fh.write("".join(out))
    print("edl-lint: removed %d unused pragma(s) from %d file(s)"
          % (removed, len(dead)))
    return 0


def _print_finding(finding, fmt):
    if fmt == "github":
        # GitHub Actions annotation: renders inline on the PR diff
        print("::error file=%s,line=%d,title=%s::%s [%s] %s" % (
            finding.path, finding.line, finding.rule, finding.rule,
            finding.scope, finding.message.replace("\n", " "),
        ))
    else:
        print(finding.format())


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="edl-lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="*", help="files/dirs to lint")
    parser.add_argument("--baseline", default=None)
    parser.add_argument("--write-baseline", action="store_true")
    parser.add_argument("--select", default="")
    parser.add_argument("--jobs", type=int, default=1,
                        help="processes for per-file analysis "
                             "(0 = cpu count)")
    parser.add_argument("--no-cache", action="store_true",
                        help="skip the per-file result cache")
    parser.add_argument("--changed-only", action="store_true")
    parser.add_argument("--base", default=None,
                        help="merge-base ref for --changed-only")
    parser.add_argument("--format", dest="fmt", default="human",
                        choices=("human", "github", "sarif"))
    parser.add_argument("--output", default=None,
                        help="sarif output file (default: stdout)")
    parser.add_argument("--fix-pragmas", action="store_true")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", default=REPO_ROOT,
                        help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    from elasticdl_tpu.analysis import Baseline, run_rules

    rules = _selected_rules(args.select)
    if args.list_rules:
        for rule in rules:
            doc = (sys.modules[rule.__module__].__doc__ or "")
            title = doc.strip().splitlines()[0] if doc else rule.name
            print("%s  %s\n    %s" % (rule.id, rule.name, title))
        return 0

    root = os.path.abspath(args.root)
    paths = [
        p if os.path.isabs(p) else os.path.join(root, p)
        for p in (args.paths or DEFAULT_PATHS)
    ]
    paths = [p for p in paths if os.path.exists(p)]
    if root not in sys.path:
        sys.path.insert(0, root)  # for scripts.gen_serving_proto

    subset_scan = False
    if args.changed_only:
        changed = changed_files(root, base=args.base)
        if changed is None:
            print("edl-lint: --changed-only needs git; running the "
                  "full set", file=sys.stderr)
        else:
            wanted = tuple(os.path.abspath(p) for p in paths)
            paths = [
                f for f in changed
                if any(f == w or f.startswith(w + os.sep)
                       for w in wanted)
            ]
            subset_scan = True
            if not paths:
                print("edl-lint: no changed python files under the "
                      "linted paths")
                return 0

    cache = None
    if not args.no_cache:
        from elasticdl_tpu.analysis.cache import (
            CACHE_BASENAME,
            ResultCache,
            cache_context,
        )

        cache = ResultCache(
            os.path.join(root, CACHE_BASENAME),
            cache_context(r.id for r in rules),
        )

    jobs = args.jobs if args.jobs > 0 else (os.cpu_count() or 1)
    t0 = time.monotonic()
    findings, errors = run_rules(paths, rules=rules, root=root,
                                 jobs=jobs, cache=cache)
    elapsed = time.monotonic() - t0
    for err in errors:
        print("edl-lint: ERROR %s" % err, file=sys.stderr)

    baseline_path = args.baseline or os.path.join(
        root, ".edl-lint-baseline.json"
    )
    if args.write_baseline:
        baseline = Baseline.from_findings(
            findings,
            reason="TODO: justify or fix (edl-lint --write-baseline)",
            path=baseline_path,
        )
        baseline.save()
        print("edl-lint: wrote %d entries to %s"
              % (len(baseline.entries), baseline_path))
        return 0

    baseline = Baseline.load(baseline_path)
    findings, stale = baseline.apply(findings)
    if subset_scan:
        # a subset scan cannot distinguish "fixed" from "not scanned"
        stale = []

    if args.fix_pragmas:
        return _fix_pragmas(findings, root)

    if args.fmt == "sarif":
        from elasticdl_tpu.analysis.sarif import render_sarif

        text = render_sarif(findings, rules)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        # keep the findings human-readable for whoever reads the log
        for f in findings:
            print(f.format(), file=sys.stderr)
    else:
        for f in findings:
            _print_finding(f, args.fmt)
    for e in stale:
        msg = ("STALE baseline entry %s %s [%s] %s — the finding it "
               "vetted is gone; delete the entry"
               % (e["rule"], e["path"], e["scope"], e["detail"]))
        if args.fmt == "github":
            print("::error file=%s,title=stale-baseline::%s"
                  % (e["path"], msg))
        else:
            print("edl-lint: %s" % msg, file=sys.stderr)
    n_base = len(baseline.entries) - len(stale)
    if findings or stale or errors:
        print(
            "edl-lint: %d finding(s), %d stale baseline entr(ies), "
            "%d error(s) in %.1fs"
            % (len(findings), len(stale), len(errors), elapsed),
            file=sys.stderr,
        )
        return 1
    print(
        "edl-lint: clean (%d rule checker(s), %d baselined "
        "exception(s), %.1fs%s)"
        % (len(rules), n_base, elapsed,
           ", %d jobs" % jobs if jobs > 1 else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
