"""Value-origin / trace-stability dataflow (the v3 engine layer).

The compile-discipline rules (EDL105/106/107) and the sharding family
(EDL601) all ask the same underlying question about an expression at a
jit call site: *would its abstract signature be the same every time
this statement executes?* The PR 14 recompile sentry answers that at
runtime, one churned executable too late; this module answers it
statically on the CFG engine from PRs 5/7.

Origins are a small closed tag set, each an UNSTABLE provenance:

* ``loop``   — derived from a Python loop counter: the target of a
  ``for i in range(...)`` / ``enumerate(...)`` loop, or a name
  augassigned inside a loop body (an accumulator). Such a value takes
  a different concrete int every iteration, so a jit signature built
  from it churns the compile cache once per iteration.
* ``len``    — ``len(c)`` / ``c.shape`` of a container that is MUTATED
  in the same function (``.append``/``.extend``/``+=`` ...): the
  classic "shape read off a growing batch list" recompile loop.
* ``clock``  — wall-clock reads (``time.time()`` and friends,
  ``datetime.now()``): different every call, by construction.
* ``config`` — environment reads (``os.environ[...]`` / ``os.getenv``):
  stable within one process run but re-read idioms (hot reload) make
  them signature poison at jit boundaries.

STABILIZERS are the repo's sanctioned bucketing idioms — they
collapse an unstable int onto a small closed set of values, which is
exactly what makes the engine/kv_pool prefill buckets safe:

* a call whose name spells the convention: ``*_bucket``/``*bucket*``,
  ``*pad*``, ``round_up*``, ``*pow2*`` (``_prefill_bucket``,
  ``_suffix_bucket``, ``pad_to_multiple`` ...);
* ceil-to-multiple arithmetic: ``-(-p // 64) * 64`` and
  ``((p + 63) // 64) * 64`` (a Mult with a constant where the other
  operand floor-divides);
* next-power-of-two: ``1 << (n - 1).bit_length()``, ``2 ** k``, or any
  expression routed through ``.bit_length()``;
* ``min``/``max`` clamps whose unstable operands are themselves
  stabilized (``min(seq_len, -(-p // 64) * 64)``);
* scalar DEVICE BINDING: ``jnp.asarray(j, jnp.int32)`` and friends —
  the unstable Python int becomes a shape-``()`` traced array, so its
  abstract signature is constant (the PR 3 "tables and positions are
  device arrays, churn never recompiles" convention). Binding a
  MUTATED CONTAINER itself (``jnp.asarray(growing_list)``) does NOT
  stabilize: there the instability IS the shape.

A stabilized expression contributes NO origin tags, and an assignment
from a stabilizer KILLS the taint — ``p_pad = _prefill_bucket(p, n)``
launders ``p``'s instability, because the repo's convention then keys
one compiled executable per bucket.

Like every v2/v3 analysis here: heuristic by design, precision over
recall. Attribute state (``self._x``) contributes nothing unless the
evidence is in the same function; unresolvable means silent.
"""

import ast

from elasticdl_tpu.analysis.cfg import build_cfg, walk_shallow
from elasticdl_tpu.analysis.dataflow import forward

ORIGIN_LOOP = "loop"
ORIGIN_LEN = "len"
#: same provenance as ``len`` but the growing container is a bare
#: LOCAL: it resets every invocation, so the instability only matters
#: when the consuming call repeats within one invocation (in a loop).
#: Attribute containers (``self._buf``) persist across calls and
#: convict anywhere. Rules gate on this distinction; both report as
#: "len".
ORIGIN_LEN_LOCAL = "len_local"
ORIGIN_CLOCK = "clock"
ORIGIN_CONFIG = "config"

#: wall-clock reads: ``time.X()`` for X here, plus ``datetime.now()``
_CLOCK_FUNCS = {
    "time", "monotonic", "perf_counter", "process_time", "thread_time",
    "monotonic_ns", "perf_counter_ns", "time_ns",
}

#: container mutators that make a later ``len()``/``.shape`` unstable
_MUTATORS = {
    "append", "extend", "insert", "add", "pop", "remove", "clear",
    "update", "appendleft", "popleft", "setdefault",
}

#: jit wrapper factories whose RESULT is a compile-cached executable —
#: the call surfaces EDL105 guards (tracked_jit and the repo's _tjit /
#: _pool_tjit adapters included; vmap/pmap alone are not caches)
JIT_WRAPPER_TAILS = {"jit", "pjit", "tracked_jit", "_tjit", "_pool_tjit"}


def dotted_text(node):
    """``self._write_fn`` -> 'self._write_fn'; bare Name -> its id;
    anything else -> None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_tail(fn):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


# ------------------------------------------------------------ stabilizers


def _is_bucket_name(name):
    if not name:
        return False
    low = name.lower()
    return ("bucket" in low or "pad" in low or "pow2" in low
            or low.startswith("round_up") or low.startswith("next_pow"))


def is_stabilizer(expr):
    """True when `expr`'s VALUE is bucketed regardless of how unstable
    its inputs are (see module docstring for the recognized idioms)."""
    if isinstance(expr, ast.Call):
        tail = call_tail(expr.func)
        if _is_bucket_name(tail):
            return True
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "bit_length"):
            return True
        if isinstance(expr.func, ast.Name) and expr.func.id in (
            "min", "max"
        ):
            return all(
                is_stabilizer(a) or not _has_any_source(a)
                for a in expr.args
            )
        return False
    if isinstance(expr, ast.BinOp):
        if isinstance(expr.op, ast.Mult):
            for const, other in (
                (expr.right, expr.left), (expr.left, expr.right),
            ):
                if isinstance(const, ast.Constant) and any(
                    isinstance(n, ast.BinOp)
                    and isinstance(n.op, ast.FloorDiv)
                    for n in ast.walk(other)
                ):
                    return True
            return False
        if isinstance(expr.op, ast.LShift):
            return isinstance(expr.left, ast.Constant)
        if isinstance(expr.op, ast.Pow):
            return isinstance(expr.left, ast.Constant)
        return False
    return False


def _has_any_source(expr):
    """Conservative: does this expression read ANY name or direct
    source? (Used only to let min/max over constants count as
    stabilized.)"""
    for n in ast.walk(expr):
        if isinstance(n, (ast.Name, ast.Call, ast.Subscript)):
            return True
    return False


#: jnp-rooted calls that bind a host scalar onto the device (value
#: becomes traced data; abstract signature pinned at shape ())
_DEVICE_BIND_TAILS = {
    "asarray", "array", "int32", "int8", "int16", "int64", "uint32",
    "uint8", "float32", "float16", "bfloat16", "float64",
}
_DEVICE_BIND_ROOTS = {"jnp", "jax.numpy"}


def _device_binding(expr):
    """The bound sub-expression of a ``jnp.asarray(x, ...)``-style
    call, else None."""
    if not (isinstance(expr, ast.Call) and expr.args):
        return None
    fn = expr.func
    if not isinstance(fn, ast.Attribute):
        return None
    if fn.attr not in _DEVICE_BIND_TAILS:
        return None
    root = dotted_text(fn.value)
    if root in _DEVICE_BIND_ROOTS:
        return expr.args[0]
    return None


# ----------------------------------------------------- per-function facts


def mutated_containers(fndef):
    """Dotted spellings of locals/attrs that GROW in this function:
    receivers of mutator calls plus AugAssign targets of list-ish
    ops. Evidence is same-function only — precision over recall."""
    out = set()
    for node in walk_shallow(fndef):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            text = dotted_text(node.func.value)
            if text:
                out.add(text)
        elif isinstance(node, ast.AugAssign):
            text = dotted_text(node.target)
            if text:
                out.add(text)
    return out


def loop_bodies(fndef):
    """[(loop stmt, frozenset(id(node) for nodes lexically inside))]
    for every for/while loop in this function (nested scopes pruned)."""
    loops = []
    for node in walk_shallow(fndef):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            inner = set()
            for stmt in node.body + node.orelse:
                for n in walk_shallow(stmt):
                    inner.add(id(n))
            loops.append((node, frozenset(inner)))
    return loops


def enclosing_loops(loops, node):
    """The loop statements whose body lexically contains `node`."""
    nid = id(node)
    return [lp for lp, inner in loops if nid in inner]


# -------------------------------------------------------- the analysis


class OriginAnalysis(object):
    """Forward may-analysis over one function's CFG: which local names
    may, entering each node, hold a value with an unstable origin
    (state = frozenset of (name, tag) pairs)."""

    def __init__(self, fndef):
        self.fndef = fndef
        self.cfg = build_cfg(fndef)
        self.mutated = mutated_containers(fndef)
        self.loops = loop_bodies(fndef)
        self._aug_in_loop = self._augassigned_loop_names()
        self.states = forward(self.cfg, self._transfer,
                              entry_state=frozenset())

    # -------------------------------------------------------- helpers

    def _augassigned_loop_names(self):
        names = set()
        for node in walk_shallow(self.fndef):
            if isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name
            ):
                if enclosing_loops(self.loops, node):
                    names.add(node.target.id)
        return names

    def _stable(self, expr):
        """Stabilized under THIS function's facts: the syntactic
        bucketing idioms, plus scalar device binding — unless the
        bound value is a growing container itself (its shape IS the
        instability)."""
        if is_stabilizer(expr):
            return True
        bound = _device_binding(expr)
        if bound is not None:
            text = dotted_text(bound)
            return not (text and text in self.mutated)
        return False

    def expr_origins(self, expr, state):
        """Union of origin tags this expression may carry under
        `state`. Stabilized subexpressions contribute nothing."""
        if self._stable(expr):
            return frozenset()
        tags = set()
        stack = [expr]
        while stack:
            n = stack.pop()
            if self._stable(n):
                continue
            if isinstance(n, ast.Name):
                for name, tag in state:
                    if name == n.id:
                        tags.add(tag)
            elif isinstance(n, ast.Call):
                tail = call_tail(n.func)
                if tail == "len" and n.args:
                    text = dotted_text(n.args[0])
                    if text and text in self.mutated:
                        tags.add(ORIGIN_LEN if "." in text
                                 else ORIGIN_LEN_LOCAL)
                elif (isinstance(n.func, ast.Attribute)
                        and n.func.attr in _CLOCK_FUNCS
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "time"):
                    tags.add(ORIGIN_CLOCK)
                elif (isinstance(n.func, ast.Attribute)
                        and n.func.attr == "now"):
                    tags.add(ORIGIN_CLOCK)
                elif tail == "getenv":
                    tags.add(ORIGIN_CONFIG)
                elif (isinstance(n.func, ast.Attribute)
                        and n.func.attr == "get"
                        and dotted_text(n.func.value) == "os.environ"):
                    tags.add(ORIGIN_CONFIG)
            elif isinstance(n, ast.Attribute):
                if n.attr == "shape":
                    text = dotted_text(n.value)
                    if text and text in self.mutated:
                        tags.add(ORIGIN_LEN if "." in text
                                 else ORIGIN_LEN_LOCAL)
            elif isinstance(n, ast.Subscript):
                if dotted_text(n.value) == "os.environ":
                    tags.add(ORIGIN_CONFIG)
            stack.extend(ast.iter_child_nodes(n))
        return frozenset(tags)

    # ------------------------------------------------------- transfer

    @staticmethod
    def _kill(state, names):
        names = set(names)
        return frozenset(
            (n, t) for n, t in state if n not in names
        )

    def _transfer(self, node, state):
        if node.kind == "iter":
            stmt = node.payload
            tgt_names = [
                n.id for n in ast.walk(stmt.target)
                if isinstance(n, ast.Name)
            ]
            tail = call_tail(stmt.iter.func) if isinstance(
                stmt.iter, ast.Call
            ) else None
            if tail in ("range", "enumerate"):
                state = state | frozenset(
                    (n, ORIGIN_LOOP) for n in tgt_names
                )
            else:
                tags = self.expr_origins(stmt.iter, state)
                if tags:
                    state = state | frozenset(
                        (n, t) for n in tgt_names for t in tags
                    )
            return state
        if node.kind != "stmt":
            return state
        stmt = node.payload
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            tgt_names = [
                n.id for tgt in stmt.targets
                for n in ast.walk(tgt) if isinstance(n, ast.Name)
            ]
            if self._stable(value):
                return self._kill(state, tgt_names)
            tags = self.expr_origins(value, state)
            state = self._kill(
                state,
                [t.id for t in stmt.targets
                 if isinstance(t, ast.Name)],
            )
            if tags:
                state = state | frozenset(
                    (n, t) for n in tgt_names for t in tags
                )
            return state
        if isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.target, ast.Name
        ):
            extra = set()
            if stmt.target.id in self._aug_in_loop:
                extra.add((stmt.target.id, ORIGIN_LOOP))
            tags = self.expr_origins(stmt.value, state)
            extra.update((stmt.target.id, t) for t in tags)
            if extra:
                state = state | frozenset(extra)
            return state
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            if self._stable(stmt.value):
                return self._kill(state, [stmt.target.id])
            tags = self.expr_origins(stmt.value, state)
            state = self._kill(state, [stmt.target.id])
            if tags:
                state = state | frozenset(
                    (stmt.target.id, t) for t in tags
                )
            return state
        return state

    # ------------------------------------------------------ rule API

    def origins_at(self, node, expr):
        """Origin tags of `expr` evaluated at CFG `node` (entry
        state)."""
        return self.expr_origins(expr, self.states.get(node,
                                                       frozenset()))


# ------------------------------------------------- jit wrapper bindings


def collect_jit_wrappers(scope_stmts):
    """{spelling: binding stmt} for names bound to a compile-cached
    executable in these statements: ``fn = jax.jit(step)``,
    ``self._fn = self._tjit("name", fn)``, ``w = tracked_jit(f, ...)``.
    Nested function/class bodies are NOT entered (their bindings are
    not visible at this level)."""
    wrappers = {}
    stack = list(scope_stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            if call_tail(node.value.func) in JIT_WRAPPER_TAILS:
                for tgt in node.targets:
                    text = dotted_text(tgt)
                    if text:
                        wrappers[text] = node
        stack.extend(ast.iter_child_nodes(node))
    return wrappers
