"""Dataflow over the CFG + the project-wide index the
interprocedural rules resolve calls against.

Three layers, each usable alone:

* `forward` — the generic worklist fixpoint: per-node transfer
  functions over join-semilattice states (sets here; may-analysis is
  union-join, must-analysis intersection-join).
* `tainted_names` / `leak_paths` — the two concrete analyses the rule
  families share: forward MAY-taint of local names from seed values
  (deadline propagation, jit-style derivation questions), and the
  path search "can this acquisition reach a function exit without
  passing a settle event" (must-release).
* `ModuleIndex` / `ProjectIndex` — classes, their lock attributes
  (instance ``self._x = threading.Lock()`` AND class-level
  ``_x = Lock()``), their methods, and an attribute→class binding map
  so ``self._evaluation_service.complete_task()`` resolves to a
  method of a concrete class. Bindings come from three sources, in
  decreasing confidence: direct construction (``self.x =
  ClassName(...)``), constructor/setter argument propagation (a
  parameter's type inferred from what every resolvable call site
  passes — ``EvaluationService(..., task_d=self.task_d, ...)`` types
  the ``task_d`` param, so ``self._task_d = task_d`` binds), and the
  camel-case naming convention (``self._router = router`` binds to a
  known class ``Router``). Heuristic by design: an unresolvable
  receiver contributes NOTHING (rules stay quiet rather than guess).
"""

import ast

from elasticdl_tpu.analysis.cfg import walk_shallow

# --------------------------------------------------------------- fixpoint


def forward(cfg, transfer, entry_state=frozenset(), join=None):
    """Worklist forward fixpoint. `transfer(node, in_state)` returns
    the node's out-state; `join` merges predecessor out-states
    (default: union — a MAY analysis). Returns {node: in_state}."""
    if join is None:
        def join(a, b):
            return a | b

    preds = {n: [] for n in cfg.nodes}
    for n in cfg.nodes:
        for s in n.out:
            preds[s].append(n)

    in_states = {cfg.entry: entry_state}
    out_states = {}
    work = [cfg.entry]
    while work:
        node = work.pop()
        in_s = in_states.get(node, None)
        if in_s is None:
            continue
        out_s = transfer(node, in_s)
        if out_states.get(node) == out_s and node in out_states:
            continue
        out_states[node] = out_s
        for succ in node.out:
            merged = out_s
            for p in preds[succ]:
                if p is not node and p in out_states:
                    merged = join(merged, out_states[p])
            if in_states.get(succ) != merged:
                in_states[succ] = merged
                work.append(succ)
    return in_states


# ------------------------------------------------------------ name taint


def _target_names(tgt):
    out = []
    for n in ast.walk(tgt):
        if isinstance(n, ast.Name):
            out.append(n.id)
    return out


def mentions(expr, names):
    """True when `expr` reads any Name in `names` (nested scopes
    included: a closure capturing a tainted name carries the taint)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in names:
            return True
    return False


def tainted_names(cfg, seeds, is_source=None):
    """Forward MAY-taint: which local names may, at each node, hold a
    value derived from the seed names (or from any expression
    `is_source` accepts — e.g. an attribute read like
    ``request.deadline_ms``). Assignments propagate: a target becomes
    tainted when its value mentions a tainted name or a source;
    otherwise a plain Name target is (per-path) untainted.
    Returns {node: frozenset(names)} of the state ENTERING the node."""
    seeds = frozenset(seeds)

    def expr_tainted(expr, state):
        if mentions(expr, state):
            return True
        if is_source is not None:
            for n in ast.walk(expr):
                if is_source(n):
                    return True
        return False

    def transfer(node, state):
        if node.kind != "stmt":
            # tests/iters only read; for-targets handled on the ITER
            p = node.payload
            if node.kind == "iter" and p is not None:
                if expr_tainted(p.iter, state):
                    state = state | frozenset(_target_names(p.target))
            return state
        stmt = node.payload
        if isinstance(stmt, ast.Assign):
            tainted = expr_tainted(stmt.value, state)
            names = []
            for tgt in stmt.targets:
                names.extend(_target_names(tgt))
            if tainted:
                state = state | frozenset(names)
            else:
                state = state - frozenset(
                    n for tgt in stmt.targets
                    if isinstance(tgt, ast.Name)
                    for n in (tgt.id,)
                )
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name) and expr_tainted(
                stmt.value, state
            ):
                state = state | frozenset([stmt.target.id])
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if isinstance(stmt.target, ast.Name):
                if expr_tainted(stmt.value, state):
                    state = state | frozenset([stmt.target.id])
                else:
                    state = state - frozenset([stmt.target.id])
        return state

    return forward(cfg, transfer, entry_state=seeds)


# ------------------------------------------------------------ leak paths


def leak_paths(start_nodes, is_settle, is_leak_exit):
    """DFS over CFG successors from `start_nodes`: does some path
    reach a node satisfying `is_leak_exit` without first passing a
    node whose entry satisfies `is_settle`? Returns the first
    leak-exit node found, else None.

    `is_settle` may return "full" (the whole node settles — release
    call, reassign, store: stop the path) or "exit" (the settle
    happens AT function exit — ``return handle`` / ``raise handle``:
    the normal continuation is settled, but the node's EXCEPTIONAL
    successors stay live, because if evaluating the statement raises,
    the handle never escaped)."""
    seen = set()
    stack = list(start_nodes)
    while stack:
        node = stack.pop()
        if node.idx in seen:
            continue
        seen.add(node.idx)
        settle = is_settle(node)
        if settle == "exit":
            stack.extend(node.esucc)
            continue
        if settle:
            continue
        if is_leak_exit(node):
            return node
        stack.extend(node.out)
    return None


# ---------------------------------------------------------- module index

_LOCK_KINDS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}


def _call_ctor_kind(value):
    """'lock'/'rlock'/'cond' for a threading-primitive constructor
    call expression, else None."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    return _LOCK_KINDS.get(name)


def _called_class_name(value, classes):
    """'ClassName' when `value` is a Call of a known class (bare name
    or dotted tail), else None."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    return name if name in classes else None


def camel(name):
    """``_evaluation_service`` -> ``EvaluationService``."""
    return "".join(p.capitalize() for p in name.strip("_").split("_"))


class ClassInfo(object):
    __slots__ = ("name", "path", "node", "lock_attrs", "methods",
                 "attr_types")

    def __init__(self, name, path, node):
        self.name = name
        self.path = path
        self.node = node
        self.lock_attrs = {}   # attr -> 'lock' | 'rlock' | 'cond'
        self.methods = {}      # name -> FunctionDef
        self.attr_types = {}   # attr -> class name

    def single_lock(self):
        if len(self.lock_attrs) == 1:
            return next(iter(self.lock_attrs))
        return None


def _self_attr(node):
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class ModuleIndex(object):
    def __init__(self, tree, path):
        self.tree = tree
        self.path = path
        self.classes = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = self._index_class(node)

    def _index_class(self, classdef):
        info = ClassInfo(classdef.name, self.path, classdef)
        for stmt in classdef.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                # class-level lock: `_ids_lock = threading.Lock()`
                kind = _call_ctor_kind(stmt.value)
                if kind:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            info.lock_attrs[tgt.id] = kind
        for node in ast.walk(classdef):
            if isinstance(node, ast.Assign):
                kind = _call_ctor_kind(node.value)
                if kind:
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            info.lock_attrs[attr] = kind
        return info


class ProjectIndex(object):
    """Classes across every module, with attribute→class bindings
    resolved by a small fixpoint (see module docstring). Class names
    appearing in more than one module are kept FIRST-wins; in this
    codebase class names are unique, and a collision would only make
    the rules quieter, never wrong-er."""

    def __init__(self, module_indexes):
        self.modules = list(module_indexes)
        self.classes = {}
        for mod in self.modules:
            for name, info in mod.classes.items():
                self.classes.setdefault(name, info)
        self._bind_attr_types()

    # -------------------------------------------------------- bindings

    def _bind_attr_types(self):
        # pass 1: direct construction + camel-case convention
        assigns = []  # (ClassInfo, attr, value expr, enclosing method)
        for info in self.classes.values():
            for mname, fn in info.methods.items():
                for node in walk_shallow(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            assigns.append((info, attr, node.value,
                                            mname))
        for info, attr, value, _m in assigns:
            cname = _called_class_name(value, self.classes)
            if cname:
                info.attr_types[attr] = cname
        for info, attr, value, _m in assigns:
            if attr in info.attr_types:
                continue
            if isinstance(value, ast.Name):
                guess = camel(value.id)
                if guess in self.classes:
                    info.attr_types[attr] = guess
                else:
                    guess = camel(attr)
                    if guess in self.classes:
                        info.attr_types[attr] = guess

        # pass 2: constructor/setter argument propagation — what type
        # does each (class, method, param) receive at resolvable call
        # sites? Two rounds so a binding discovered in round one can
        # type a call argument in round two.
        for _round in range(2):
            param_types = self._collect_param_types()
            for info, attr, value, mname in assigns:
                if attr in info.attr_types:
                    continue
                if isinstance(value, ast.Name):
                    t = param_types.get((info.name, mname, value.id))
                    if t:
                        info.attr_types[attr] = t

    def _collect_param_types(self):
        param_types = {}
        for info in self.classes.values():
            for fn in info.methods.values():
                for node in walk_shallow(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self._callee_of(info, node)
                    if callee is None:
                        continue
                    cls_name, method_name = callee
                    target = self.classes.get(cls_name)
                    if target is None:
                        continue
                    mdef = target.methods.get(method_name)
                    if mdef is None:
                        continue
                    params = [a.arg for a in mdef.args.args]
                    if params and params[0] == "self":
                        params = params[1:]
                    for i, arg in enumerate(node.args):
                        if i < len(params):
                            t = self._arg_type(info, arg)
                            if t:
                                param_types[
                                    (cls_name, method_name, params[i])
                                ] = t
                    for kw in node.keywords:
                        if kw.arg:
                            t = self._arg_type(info, kw.value)
                            if t:
                                param_types[
                                    (cls_name, method_name, kw.arg)
                                ] = t
        return param_types

    def _callee_of(self, info, call):
        """(class_name, method_name) for ClassName(...) -> __init__,
        self.m(...), or self.attr.m(...); None unresolved."""
        fn = call.func
        cname = _called_class_name(call, self.classes)
        if cname:
            return (cname, "__init__")
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                return (info.name, fn.attr)
            attr = _self_attr(recv)
            if attr is not None and attr in info.attr_types:
                return (info.attr_types[attr], fn.attr)
        return None

    def _arg_type(self, info, arg):
        if isinstance(arg, ast.Name) and arg.id == "self":
            return info.name
        attr = _self_attr(arg)
        if attr is not None:
            return info.attr_types.get(attr)
        return _called_class_name(arg, self.classes)

    # ------------------------------------------------------ resolution

    def resolve_receiver(self, info, recv, local_aliases=None):
        """ClassInfo for a call receiver expression inside a method of
        `info`: ``self`` -> info, ``self.attr`` -> bound class, a
        local alias of either, else None."""
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                return info
            if local_aliases and recv.id in local_aliases:
                kind, val = local_aliases[recv.id]
                if kind == "selfattr":
                    cname = info.attr_types.get(val)
                    return self.classes.get(cname) if cname else None
            return None
        attr = _self_attr(recv)
        if attr is not None:
            cname = info.attr_types.get(attr)
            return self.classes.get(cname) if cname else None
        return None


def build_project_index(parsed_modules):
    """`parsed_modules`: iterable of (tree, path)."""
    return ProjectIndex(ModuleIndex(t, p) for t, p in parsed_modules)
